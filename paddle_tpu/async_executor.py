"""AsyncExecutor: file-sharded multi-slot training driven by the native feed.

Reference analog: framework/async_executor.{h,cc} + executor_thread_worker —
N CPU threads, each interpreting the whole program per-sample over its shard
of a file list, sharing parameters Hogwild-style; python surface
async_executor.py AsyncExecutor.run(program, data_feed, filelist, thread_num,
fetch_list).

TPU-first redesign: per-sample per-thread interpretation wastes the chip —
instead the C++ feed threads (native.MultiSlotDataFeed) parse the file list
concurrently into the native blocking queue, the host assembles fixed-shape
batches (sparse slots padded to a bucketed length with padding_idx ids), and
ONE compiled XLA program consumes them at full batch width. thread_num maps
to parser threads — the role the reference's threads actually played that
the accelerator can't absorb (text parsing), stays parallel; the compute the
reference scattered across cores lands on the MXU instead.
"""

import numpy as np

from . import framework, native
from .executor import Executor, global_scope

__all__ = ["AsyncExecutor", "stream_batches"]


def _bucket(n, buckets=(1, 2, 4, 8, 16, 32, 64, 128)):
    for b in buckets:
        if n <= b:
            return b
    return ((n + 127) // 128) * 128


def _assemble_batch(batch, used):
    """Pack samples into fixed-shape arrays: dense float slots stack to
    (b, dim); sparse id slots pad to a bucketed max length with -1
    (= lookup_table padding_idx, zero vector) so XLA sees few shapes."""
    feeds = {}
    for slot_idx, slot in used:
        cols = [sample[slot_idx] for sample in batch]
        if slot.type == "float":
            dim = max(len(c) for c in cols)
            arr = np.zeros((len(cols), dim), np.float32)
            for i, c in enumerate(cols):
                arr[i, : len(c)] = c
        else:
            width = _bucket(max(len(c) for c in cols))
            arr = np.full((len(cols), width), -1, np.int64)
            for i, c in enumerate(cols):
                arr[i, : len(c)] = c
        feeds[slot.name] = arr
    return feeds


def stream_batches(data_feed, filelist, thread_num=1, loop=False):
    """Yield assembled feed dicts (name -> fixed-shape array) straight off
    the native multi-slot feed — the unbounded-stream source an
    online.OnlineTrainer consumes. `loop=True` restarts the file list each
    time it drains, turning a finite clickstream dump into an endless
    stream (each pass is a new feed instance, so file errors still raise
    per pass)."""
    used = data_feed.used_slots()
    if not used:
        raise ValueError("data_feed has no used slots (set_use_slots)")
    bs = data_feed.batch_size
    while True:
        feed = native.MultiSlotDataFeed(
            data_feed.native_slot_types(), queue_capacity=4 * bs
        )
        feed.start(list(filelist), nthreads=max(1, int(thread_num)))
        batch = []
        for sample in feed:
            batch.append(sample)
            if len(batch) == bs:
                yield _assemble_batch(batch, used)
                batch = []
        if batch:
            yield _assemble_batch(batch, used)
        feed.join()
        if feed.file_errors():
            raise IOError(
                "stream_batches: %d input files could not be opened"
                % feed.file_errors()
            )
        if not loop:
            return


class _FileShardDecode:
    """DataRuntime decode_fn for the async filelist: shard = one input
    file, parsed by the native feed (nthreads=1 inside the worker — the
    parallelism IS the worker pool) and assembled into fixed-shape batches.
    Deterministic per shard (single file, single parser thread), which the
    crash-replay contract requires; module-level so it pickles under
    spawn."""

    def __init__(self, files, slot_types, used, batch_size):
        self.files = list(files)
        self.slot_types = slot_types
        self.used = list(used)
        self.batch_size = int(batch_size)

    def __call__(self, shard_id):
        from . import native

        fname = self.files[shard_id]
        feed = native.MultiSlotDataFeed(
            self.slot_types, queue_capacity=4 * self.batch_size
        )
        feed.start([fname], nthreads=1)
        batch = []
        for sample in feed:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield _assemble_batch(batch, self.used)
                batch = []
        if batch:
            yield _assemble_batch(batch, self.used)
        feed.join()
        if feed.file_errors():
            raise IOError(
                "async feed: input file %r could not be opened" % fname
            )


class AsyncExecutor:
    def __init__(self, place=None):
        self.place = place
        self.executor = Executor(place)

    def run(
        self,
        program,
        data_feed,
        filelist,
        thread_num,
        fetch,
        debug=False,
        print_period=100,
        num_workers=None,
    ):
        """Train over `filelist` until the feed drains. `fetch` vars are
        averaged per print period (reference async_executor.py:run / the
        worker's PrintFetchVars). Returns the list of per-period means of the
        first fetch var.

        num_workers > 0 (or FLAGS_data_num_workers) rides the native data
        runtime (docs/data.md): each input file is a shard decoded by a
        worker PROCESS — parse, batch assembly, and padding all leave the
        trainer process, batches cross a shared-memory ring, and a killed
        worker's files replay without sample loss. Batches then pad
        per-file rather than globally (the last partial batch is per file).
        Default (0) keeps the in-process native feed threads."""
        if isinstance(fetch, (str, framework.Variable)):
            fetch = [fetch]
        fetch_names = [
            f.name if isinstance(f, framework.Variable) else str(f) for f in fetch
        ]
        used = data_feed.used_slots()
        if not used:
            raise ValueError("data_feed has no used slots (set_use_slots)")
        feed_vars = []
        block = program.global_block()
        for _, slot in used:
            if slot.name not in block.vars:
                raise ValueError(
                    "program has no var for used slot %r" % slot.name
                )
            feed_vars.append(block.vars[slot.name])

        bs = data_feed.batch_size
        period_vals = []
        results = []
        step = 0

        def flush(step):
            # fetches stay device-resident until here — converting per step
            # would sync the pipeline every iteration (ROADMAP 9)
            if not period_vals:
                return
            host = [
                [float(np.asarray(v).reshape(-1)[0]) for v in vals]
                for vals in period_vals
            ]
            means = np.mean(np.asarray(host), axis=0)
            results.append(float(means[0]))
            if debug:
                print(
                    "step %d: %s"
                    % (
                        step,
                        ", ".join(
                            "%s=%.6f" % (n, m)
                            for n, m in zip(fetch_names, means)
                        ),
                    )
                )
            period_vals.clear()

        def consume(feeds_iter):
            nonlocal step
            for feeds in feeds_iter:
                vals = self.executor.run(
                    program,
                    feed=feeds,
                    fetch_list=fetch_names,
                    scope=global_scope(),
                    return_numpy=False,
                )
                step += 1
                period_vals.append(list(vals))
                if step % print_period == 0:
                    flush(step)

        if num_workers is None:
            from .flags import get_flags

            num_workers = int(get_flags()["data_num_workers"])
        num_workers = int(num_workers or 0)

        if num_workers > 0:
            # native data runtime path: shard = file, decoded out-of-process
            from .data import DataRuntime

            files = list(filelist)
            decode = _FileShardDecode(
                files, data_feed.native_slot_types(), used, bs
            )
            rt = DataRuntime(
                decode,
                num_shards=len(files),
                num_workers=min(num_workers, max(1, len(files))),
                shuffle=False,  # filelist order is the shard order
                name="asyncexec",
            )
            rt.start()
            try:
                consume(rt())
            finally:
                rt.close()
            flush(step)
            return results

        feed = native.MultiSlotDataFeed(
            data_feed.native_slot_types(), queue_capacity=4 * bs
        )
        feed.start(list(filelist), nthreads=max(1, int(thread_num)))

        def batches():
            it = iter(feed)
            while True:
                batch = []
                try:
                    while len(batch) < bs:
                        batch.append(next(it))
                except StopIteration:
                    if batch:
                        yield self._assemble(batch, used, feed_vars)
                    return
                yield self._assemble(batch, used, feed_vars)

        # double buffering (reference operators/reader/buffered_reader.h:48):
        # a PyReader staging thread assembles the NEXT batch and device_puts
        # it while the current step runs on the chip
        from .py_reader import PyReader

        staging = PyReader([v.name for v in feed_vars], capacity=2)
        # num_workers=0 pins the in-process staging thread: `batches`
        # closes over the live native feed and cannot move to a process
        staging.decorate_tensor_provider(batches, num_workers=0)
        staging.start()
        try:
            consume(staging())
        finally:
            staging.reset()
        flush(step)
        errors = feed.join()
        missing = feed.file_errors()
        if missing:
            raise IOError(
                "async feed: %d of %d input files could not be opened"
                % (missing, len(filelist))
            )
        if errors and debug:
            print("async feed: %d unparseable lines skipped" % errors)
        return results

    def _assemble(self, batch, used, feed_vars):
        return _assemble_batch(batch, used)
