"""Evaluator API shim (reference python/paddle/fluid/evaluator.py — in-graph
metric state with reset/eval programs; already deprecation-warned there in
favor of fluid.metrics).

The reference kept per-metric state in graph variables because its executor
owned all storage; here metric state is host-side (fluid.metrics.MetricBase),
so Evaluator wraps a metric object with the reset(executor)/eval(executor)
call signatures old training loops use. New code should use fluid.metrics
directly, same as the reference's guidance."""

import warnings

import numpy as np

from . import metrics as _metrics

__all__ = ["ChunkEvaluator", "EditDistance", "DetectionMAP"]


class Evaluator:
    def __init__(self, name, **kwargs):
        warnings.warn(
            "fluid.evaluator is deprecated in the reference and here; use "
            "fluid.metrics",
            DeprecationWarning,
        )
        self.metric = None
        self._fetches = []

    def reset(self, executor, reset_program=None):
        self.metric.reset()

    def eval(self, executor, eval_program=None):
        return self.metric.eval()


class ChunkEvaluator(Evaluator):
    """Chunk F1 over (num_infer, num_label, num_correct) fetched per batch
    (reference evaluator.py:126). Given input/label variables it appends the
    chunk_eval op to the current program (layers.nn.chunk_eval), so the
    per-batch counts are computed in-framework — fetch `self.metrics` each
    step and pass the three counts to update()."""

    def __init__(
        self,
        input=None,
        label=None,
        chunk_scheme=None,
        num_chunk_types=None,
        excluded_chunk_types=None,
        seq_length=None,
    ):
        super().__init__("chunk_eval")
        self.metric = _metrics.ChunkEvaluator("chunk_eval")
        self.metrics = ()
        if input is not None:
            from .layers import nn as _nn

            (
                self.precision,
                self.recall,
                self.f1_score,
                num_infer,
                num_label,
                num_correct,
            ) = _nn.chunk_eval(
                input,
                label,
                chunk_scheme=chunk_scheme,
                num_chunk_types=num_chunk_types,
                excluded_chunk_types=excluded_chunk_types,
                seq_length=seq_length,
            )
            # per-batch count vars, in update()'s argument order
            self.metrics = (num_infer, num_label, num_correct)

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.metric.update(num_infer_chunks, num_label_chunks, num_correct_chunks)


class EditDistance(Evaluator):
    def __init__(self, input=None, label=None, ignored_tokens=None, **kwargs):
        super().__init__("edit_distance")
        self.metric = _metrics.EditDistance("edit_distance")

    def update(self, distances, seq_num):
        self.metric.update(np.asarray(distances), seq_num)


class DetectionMAP(Evaluator):
    """Mean average precision over accumulated detections (reference
    evaluator.py:298 wraps the detection_map op; here accumulation is
    host-side over per-batch (detections, gt) fetches)."""

    def __init__(
        self,
        input=None,
        gt_label=None,
        gt_box=None,
        gt_difficult=None,
        class_num=None,
        background_label=0,
        overlap_threshold=0.5,
        evaluate_difficult=True,
        ap_version="integral",
    ):
        super().__init__("map_eval")
        self.class_num = class_num
        self.overlap_threshold = overlap_threshold
        self.background_label = background_label
        self.ap_version = ap_version
        self.reset(None)

    def reset(self, executor=None, reset_program=None):
        self._dets = []  # (class, score, matched)
        self._n_gt = {}

    def update(self, detections, gt_labels, gt_boxes):
        """detections: (n, 6) [label, score, x1, y1, x2, y2]; gt per image."""
        dets = np.asarray(detections, np.float64).reshape(-1, 6)
        gt_labels = np.asarray(gt_labels).reshape(-1)
        gt_boxes = np.asarray(gt_boxes, np.float64).reshape(-1, 4)
        for c in gt_labels:
            self._n_gt[int(c)] = self._n_gt.get(int(c), 0) + 1
        used = np.zeros(len(gt_labels), bool)
        for d in dets[np.argsort(-dets[:, 1])]:
            c, score = int(d[0]), d[1]
            if c == self.background_label:
                continue
            best, best_j = 0.0, -1
            for j, (gc, gb) in enumerate(zip(gt_labels, gt_boxes)):
                if int(gc) != c or used[j]:
                    continue
                iou = _iou(d[2:6], gb)
                if iou > best:
                    best, best_j = iou, j
            matched = best >= self.overlap_threshold
            if matched:
                used[best_j] = True
            self._dets.append((c, score, matched))

    def eval(self, executor=None, eval_program=None):
        aps = []
        for c, total in self._n_gt.items():
            rows = sorted(
                ((s, m) for cc, s, m in self._dets if cc == c), reverse=True
            )
            if not rows:
                aps.append(0.0)
                continue
            tp = np.cumsum([m for _, m in rows])
            fp = np.cumsum([not m for _, m in rows])
            recall = tp / max(total, 1)
            precision = tp / np.maximum(tp + fp, 1e-12)
            if self.ap_version == "11point":
                ap = np.mean(
                    [
                        precision[recall >= t].max() if (recall >= t).any() else 0.0
                        for t in np.linspace(0, 1, 11)
                    ]
                )
            else:  # integral
                ap = float(np.sum(np.diff(np.concatenate([[0.0], recall])) * precision))
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0


def _iou(a, b):
    ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    inter = ix * iy
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - inter
    return inter / ua if ua > 0 else 0.0
