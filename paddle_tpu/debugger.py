"""Program pretty-printer + graphviz export.

Reference analog: python/paddle/fluid/debugger.py (pprint_program_codes /
pprint_block_codes over the protobuf descs, draw_block_graphviz) and
graphviz.py/net_drawer.py dot emitters; C++ side had ir/graph_viz_pass.cc.
Here the IR is the in-memory Program, so the printers walk Blocks directly.
"""

__all__ = ["pprint_program_codes", "pprint_block_codes", "draw_block_graphviz"]

from . import framework


def _repr_var(v):
    shape = "?" if v.shape is None else "x".join(str(d) for d in v.shape)
    return "%s[%s,%s]" % (v.name, v.dtype or "?", shape)


def _repr_op(op):
    ins = ", ".join(
        "%s=%s" % (slot, names) for slot, names in sorted(op.inputs.items()) if names
    )
    outs = ", ".join(
        "%s=%s" % (slot, names) for slot, names in sorted(op.outputs.items()) if names
    )
    attrs = {
        k: v
        for k, v in op.attrs.items()
        if not k.startswith("__") and k not in (framework.OpRole.OP_ROLE_KEY,)
        and not isinstance(v, framework.Block)
    }
    return "%s(%s) -> %s  %s" % (op.type, ins, outs, attrs if attrs else "")


def pprint_block_codes(block, show_backward=False):
    lines = ["block_%d {" % block.idx]
    for v in block.vars.values():
        lines.append("  var %s%s" % (_repr_var(v), " persist" if v.persistable else ""))
    for op in block.ops:
        role = op.attrs.get(framework.OpRole.OP_ROLE_KEY)
        # op_role is a bitflag (Backward|Loss on the loss-seed op): test the bit
        if (
            not show_backward
            and role is not None
            and int(role) & int(framework.OpRole.Backward)
        ):
            continue
        lines.append("  " + _repr_op(op))
    lines.append("}")
    return "\n".join(lines)


def pprint_program_codes(program, show_backward=False):
    return "\n".join(
        pprint_block_codes(program.block(i), show_backward)
        for i in range(program.num_blocks)
    )


def _normalize_costs(costs):
    """Accepts either a plain {op name: ms} mapping or a full op_profile
    record (observability/opprof.py build_record: {"ops": [{"op", "total_ms",
    ...}]}) and returns {name: ms}."""
    if not costs:
        return {}
    if isinstance(costs, dict) and isinstance(costs.get("ops"), list):
        return {
            str(row["op"]): float(row.get("total_ms", 0.0))
            for row in costs["ops"]
            if row.get("op")
        }
    return {str(k): float(v) for k, v in dict(costs).items()}


def _heat_color(frac):
    """Cold (the default box blue #d2e5ff) → hot (red) by cost fraction."""
    frac = min(max(frac, 0.0), 1.0)
    r = int(0xD2 + frac * (0xFF - 0xD2))
    g = int(0xE5 + frac * (0x84 - 0xE5))
    b = int(0xFF + frac * (0x66 - 0xFF))
    return "#%02x%02x%02x" % (r, g, b)


def _op_cost(op, costs):
    """ms for one op: exact instance match ("<type>:<out>") first, then the
    bare type (host-events tables may only resolve to type granularity)."""
    from .observability import opprof as _opprof

    ms = costs.get(_opprof.op_display_name(op))
    if ms is None:
        ms = costs.get(op.type)
    return ms


def draw_block_graphviz(block, highlights=None, path="./temp.dot", costs=None):
    """Emit a dot graph: op nodes (boxes) wired through var nodes (ellipses),
    like the reference's draw_block_graphviz / graph_viz_pass.

    costs: optional per-op device time — a {op name: ms} mapping or an
    op_profile record from tools/op_profile.py --json / the telemetry stream.
    Matching op nodes get a "(x.xx ms)" label line and a heat fill (cost
    relative to the block's most expensive op)."""
    highlights = set(highlights or [])
    costs = _normalize_costs(costs)
    max_ms = max(costs.values()) if costs else 0.0
    lines = ["digraph G {", "  rankdir=TB;"]
    seen_vars = set()

    def var_node(name):
        if name not in seen_vars:
            seen_vars.add(name)
            color = ' style=filled fillcolor="#ffd2d2"' if name in highlights else ""
            lines.append('  "v_%s" [label="%s" shape=ellipse%s];' % (name, name, color))
        return '"v_%s"' % name

    for i, op in enumerate(block.ops):
        op_id = '"op_%d_%s"' % (i, op.type)
        label = op.type
        fill = "#d2e5ff"
        ms = _op_cost(op, costs) if costs else None
        if ms is not None:
            label = "%s\\n(%.2f ms)" % (op.type, ms)
            fill = _heat_color(ms / max_ms if max_ms > 0 else 0.0)
        lines.append(
            '  %s [label="%s" shape=box style=filled fillcolor="%s"];'
            % (op_id, label, fill)
        )
        for name in op.input_arg_names:
            lines.append("  %s -> %s;" % (var_node(name), op_id))
        for name in op.output_arg_names:
            lines.append("  %s -> %s;" % (op_id, var_node(name)))
    lines.append("}")
    dot = "\n".join(lines)
    with open(path, "w") as f:
        f.write(dot)
    return dot
