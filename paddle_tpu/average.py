"""WeightedAverage metric accumulator (reference python/paddle/fluid/
average.py:40 — host-side running average for losses/accuracies printed in
train loops)."""

import numpy as np

__all__ = ["WeightedAverage"]


class WeightedAverage:
    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = 0.0
        self.denominator = 0.0

    def add(self, value, weight):
        value = np.asarray(value, dtype=np.float64)
        if value.size != 1:
            raise ValueError("WeightedAverage.add expects a scalar value")
        self.numerator += float(value.reshape(())) * weight
        self.denominator += weight

    def eval(self):
        if self.denominator == 0.0:
            raise ValueError("cannot eval() before any add()")
        return self.numerator / self.denominator
