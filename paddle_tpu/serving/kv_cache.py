"""Host-side allocator for the paged KV-cache pool (the vLLM block manager
analog, sized for the GenerationEngine's fixed-shape decode step).

The device side is dumb on purpose: per layer, one persistable
``[n_pages * page_size, feat]`` pool tensor that the compiled programs
gather/scatter through block tables (ops/generation_ops.py). All policy
lives here, on the host, where it costs nothing per token:

- **page free-list** — page 0 is a reserved *scratch* page that is never
  handed out. Idle decode slots and padded prefill tail positions write
  there (their block-table entries are 0), so a fixed-shape program can
  always run all slots without conditionals; scratch contents are garbage
  by design and masked out of every attention read.
- **slot free-list** — a slot is one decode lane in the fixed [max_slots]
  step. Admission takes a slot + enough pages for the request's worst case
  (prompt + max_new tokens, the reservation-at-admit policy: admission can
  never deadlock mid-decode needing a page that isn't there).
- **page reuse on retirement** — release() returns both to their free
  lists; the next admission reuses the pages without touching the device
  (stale rows are overwritten by prefill/decode writes before any read, see
  docs/serving.md lifecycle).

Thread-safety: the GenerationScheduler's worker thread is the only caller;
a lock still guards acquire/release so `stats()` from other threads is
consistent.
"""

import threading

import numpy as np

__all__ = ["PagedKVPool", "PoolExhausted"]

SCRATCH_PAGE = 0


class PoolExhausted(RuntimeError):
    """No free slot or not enough free pages for the reservation."""


class PagedKVPool:
    def __init__(self, n_pages, page_size, max_slots, max_pages_per_slot):
        if n_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is scratch)")
        if page_size < 1 or max_slots < 1 or max_pages_per_slot < 1:
            raise ValueError("page_size/max_slots/max_pages_per_slot must be >= 1")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.max_slots = int(max_slots)
        self.max_pages_per_slot = int(max_pages_per_slot)
        self._lock = threading.Lock()
        # LIFO free lists: hottest pages get reused first (best for any
        # future device-side page cache locality)
        self._free_pages = list(range(1, self.n_pages))
        self._free_slots = list(range(self.max_slots - 1, -1, -1))
        self._tables = {}  # slot -> np.int32 [max_pages_per_slot]

    @property
    def pool_rows(self):
        return self.n_pages * self.page_size

    def pages_for(self, n_positions):
        """Pages needed to hold `n_positions` cached tokens."""
        return -(-int(n_positions) // self.page_size)

    def can_admit(self, n_positions):
        need = self.pages_for(n_positions)
        with self._lock:
            return (
                bool(self._free_slots)
                and need <= len(self._free_pages)
                and need <= self.max_pages_per_slot
            )

    def acquire(self, n_positions):
        """Reserve a slot + pages for a request whose cache will hold at most
        `n_positions` tokens. Returns (slot, block_table) where block_table
        is the slot's np.int32 [max_pages_per_slot] page list, scratch-0
        padded. Raises PoolExhausted when it can't."""
        need = self.pages_for(n_positions)
        if need > self.max_pages_per_slot:
            raise PoolExhausted(
                "%d positions need %d pages > max_pages_per_slot %d"
                % (n_positions, need, self.max_pages_per_slot)
            )
        with self._lock:
            if not self._free_slots:
                raise PoolExhausted("no free decode slot")
            if need > len(self._free_pages):
                raise PoolExhausted(
                    "need %d pages, %d free" % (need, len(self._free_pages))
                )
            slot = self._free_slots.pop()
            table = np.full(self.max_pages_per_slot, SCRATCH_PAGE, np.int32)
            for i in range(need):
                table[i] = self._free_pages.pop()
            self._tables[slot] = table
            return slot, table

    def release(self, slot):
        """Retire a slot: its pages return to the free list for reuse."""
        with self._lock:
            table = self._tables.pop(slot, None)
            if table is None:
                return
            for p in table:
                if p != SCRATCH_PAGE:
                    self._free_pages.append(int(p))
            self._free_slots.append(slot)

    def block_table(self, slot):
        with self._lock:
            t = self._tables.get(slot)
            return None if t is None else t.copy()

    def stats(self):
        with self._lock:
            in_use = (self.n_pages - 1) - len(self._free_pages)
            slots = self.max_slots - len(self._free_slots)
            return {
                "pages_total": self.n_pages - 1,  # scratch excluded
                "pages_in_use": in_use,
                "slots_total": self.max_slots,
                "slots_in_use": slots,
                "slot_occupancy": slots / float(self.max_slots),
            }
