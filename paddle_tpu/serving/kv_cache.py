"""Host-side allocator for the paged KV-cache pool (the vLLM block manager
analog, sized for the GenerationEngine's fixed-shape decode step) plus the
prefix cache that shares immutable full pages across requests.

The device side is dumb on purpose: per layer, one persistable
``[n_pages * page_size, feat]`` pool tensor that the compiled programs
gather/scatter through block tables (ops/generation_ops.py). All policy
lives here, on the host, where it costs nothing per token:

- **page free-list + refcounts** — page 0 is a reserved *scratch* page that
  is never handed out. Idle decode slots and padded prefill tail positions
  write there (their block-table entries are 0), so a fixed-shape program
  can always run all slots without conditionals; scratch contents are
  garbage by design and masked out of every attention read. Every live page
  carries a refcount: 1 for a private page, +1 per extra slot sharing it,
  +1 while the prefix cache holds it. A page returns to the free list only
  at refcount 0.
- **slot free-list** — a slot is one decode lane in the fixed [max_slots]
  step. Admission takes a slot + enough pages for the request's worst case
  (prompt + max_new tokens, the reservation-at-admit policy: admission can
  never deadlock mid-decode needing a page that isn't there). Shared prefix
  pages satisfy the leading part of the reservation without consuming free
  pages.
- **page reuse on retirement** — release() drops one reference per table
  entry; pages nobody else holds return to their free list and the next
  admission reuses them without touching the device (stale rows are
  overwritten by prefill/decode writes before any read, see
  docs/serving.md lifecycle).

**PrefixCache** is a prompt-token trie over *full* pages: the key for depth
k is the exact first ``k * page_size`` prompt tokens (token tuples, not
hashes — no collisions), the value the pool page holding those positions'
K/V. Shared pages are immutable by construction — a prefill after a prefix
hit starts at the first uncached position, and decode writes land at
positions >= the prompt length, so no program ever writes through a shared
table entry; copy-on-write is unnecessary. Lookup always leaves at least
the final prompt token uncached (its hidden state must be computed to
produce the first sampled logits). Eviction is LRU over unreferenced
entries (descendants first, so the trie never has unreachable tails) and
runs on demand when admission wants pages the free list can't supply.

Thread-safety: the GenerationScheduler's worker thread is the only caller;
a lock still guards acquire/release so `stats()` from other threads is
consistent.
"""

import threading

import numpy as np

__all__ = ["PagedKVPool", "PoolExhausted", "PrefixCache"]

SCRATCH_PAGE = 0


class PoolExhausted(RuntimeError):
    """No free slot or not enough free pages for the reservation."""


class PagedKVPool:
    def __init__(self, n_pages, page_size, max_slots, max_pages_per_slot,
                 storage_dtype="float32", row_bytes=0):
        if n_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is scratch)")
        if page_size < 1 or max_slots < 1 or max_pages_per_slot < 1:
            raise ValueError("page_size/max_slots/max_pages_per_slot must be >= 1")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.max_slots = int(max_slots)
        self.max_pages_per_slot = int(max_pages_per_slot)
        # storage mode is bookkeeping only (the device arrays live with the
        # engine): "int8" pools store per-row levels + f32 per-page scale
        # vectors at ~1/4 the f32 bytes per token, so the same HBM budget
        # funds >= 2x the pages/slots. row_bytes is the caller-computed
        # device bytes per pooled token row across all layers (levels +
        # scales), surfaced through stats() for the monitor's kv-pool row.
        self.storage_dtype = str(storage_dtype)
        self.row_bytes = int(row_bytes)
        self._lock = threading.Lock()
        # LIFO free lists: hottest pages get reused first (best for any
        # future device-side page cache locality)
        self._free_pages = list(range(1, self.n_pages))
        self._free_slots = list(range(self.max_slots - 1, -1, -1))
        self._tables = {}  # slot -> np.int32 [max_pages_per_slot]
        self._refs = {}  # page -> live reference count (slots + prefix cache)

    @property
    def pool_rows(self):
        return self.n_pages * self.page_size

    def pages_for(self, n_positions):
        """Pages needed to hold `n_positions` cached tokens."""
        return -(-int(n_positions) // self.page_size)

    def can_admit(self, n_positions, n_shared=0):
        need = max(0, self.pages_for(n_positions) - int(n_shared))
        with self._lock:
            return (
                bool(self._free_slots)
                and need <= len(self._free_pages)
                and self.pages_for(n_positions) <= self.max_pages_per_slot
            )

    def acquire(self, n_positions, shared_pages=()):
        """Reserve a slot + pages for a request whose cache will hold at most
        `n_positions` tokens. `shared_pages` (prefix-cache hits, already
        alive) fill the leading table entries and gain a reference each;
        only the remainder is drawn from the free list. Returns
        (slot, block_table) where block_table is the slot's np.int32
        [max_pages_per_slot] page list, scratch-0 padded. Raises
        PoolExhausted when it can't."""
        need = self.pages_for(n_positions)
        shared = [int(p) for p in shared_pages]
        if need > self.max_pages_per_slot:
            raise PoolExhausted(
                "%d positions need %d pages > max_pages_per_slot %d"
                % (n_positions, need, self.max_pages_per_slot)
            )
        if len(shared) > need:
            raise ValueError("more shared pages than the reservation needs")
        need_new = need - len(shared)
        with self._lock:
            if not self._free_slots:
                raise PoolExhausted("no free decode slot")
            if need_new > len(self._free_pages):
                raise PoolExhausted(
                    "need %d pages, %d free" % (need_new, len(self._free_pages))
                )
            slot = self._free_slots.pop()
            table = np.full(self.max_pages_per_slot, SCRATCH_PAGE, np.int32)
            for i, pid in enumerate(shared):
                if self._refs.get(pid, 0) < 1:
                    raise ValueError("shared page %d is not alive" % pid)
                table[i] = pid
                self._refs[pid] += 1
            for i in range(need_new):
                pid = self._free_pages.pop()
                table[len(shared) + i] = pid
                self._refs[pid] = 1
            self._tables[slot] = table
            return slot, table

    def release(self, slot):
        """Retire a slot: drop one reference per page; pages nobody else
        holds return to the free list for reuse."""
        with self._lock:
            table = self._tables.pop(slot, None)
            if table is None:
                return
            for p in table:
                if p != SCRATCH_PAGE:
                    self._unref_locked(int(p))
            self._free_slots.append(slot)

    def pin_pages(self, pages):
        """Add one reference to each (alive) page — the prefix cache's hold."""
        with self._lock:
            for p in pages:
                p = int(p)
                if self._refs.get(p, 0) < 1:
                    raise ValueError("pin of dead page %d" % p)
                self._refs[p] += 1

    def unpin_pages(self, pages):
        """Drop one reference from each page; frees those reaching zero."""
        with self._lock:
            for p in pages:
                self._unref_locked(int(p))

    def page_refcount(self, page):
        with self._lock:
            return self._refs.get(int(page), 0)

    def _unref_locked(self, page):
        c = self._refs.get(page, 0) - 1
        if c > 0:
            self._refs[page] = c
        else:
            self._refs.pop(page, None)
            self._free_pages.append(page)

    def block_table(self, slot):
        with self._lock:
            t = self._tables.get(slot)
            return None if t is None else t.copy()

    def stats(self):
        with self._lock:
            in_use = (self.n_pages - 1) - len(self._free_pages)
            slots = self.max_slots - len(self._free_slots)
            return {
                "pages_total": self.n_pages - 1,  # scratch excluded
                "pages_in_use": in_use,
                "pages_shared": sum(1 for c in self._refs.values() if c > 1),
                "slots_total": self.max_slots,
                "slots_in_use": slots,
                "slot_occupancy": slots / float(self.max_slots),
                "storage_dtype": self.storage_dtype,
                "resident_bytes": self.row_bytes * self.n_pages * self.page_size,
            }


class _PrefixNode:
    __slots__ = ("page", "stamp")

    def __init__(self, page, stamp):
        self.page = page
        self.stamp = stamp


class PrefixCache:
    """Prompt-token trie over immutable full KV pages (module docstring)."""

    def __init__(self, pool, capacity_pages=None):
        self.pool = pool
        # default cap: the whole pool minus one slot's worst case, so the
        # cache alone can never wedge admission even before eviction runs
        if capacity_pages is None:
            capacity_pages = max(
                0, pool.n_pages - 1 - pool.max_pages_per_slot
            )
        self.capacity_pages = int(capacity_pages)
        self._lock = threading.Lock()
        self._nodes = {}  # tuple(prompt[:k*page_size]) -> _PrefixNode
        self._clock = 0
        self.hits = 0  # lookups that found >= 1 page
        self.misses = 0
        self.pages_hit = 0
        self.pages_eligible = 0
        self.evictions = 0

    def lookup(self, prompt):
        """Page ids for the longest cached prefix of `prompt`, capped so at
        least the final prompt token is always prefilled (its hidden state
        produces the first sampled logits). Each returned page is PINNED
        (+1 reference) so an eviction between lookup and acquire can never
        free it — the caller unpins once acquire() has taken the slot's own
        reference (or on admission failure). Counters feed the
        gen/prefix_hit_rate telemetry."""
        ps = self.pool.page_size
        prompt = tuple(int(t) for t in prompt)
        eligible = (len(prompt) - 1) // ps
        pages = []
        with self._lock:
            self._clock += 1
            for i in range(eligible):
                node = self._nodes.get(prompt[: (i + 1) * ps])
                if node is None:
                    break
                node.stamp = self._clock
                pages.append(node.page)
            self.pages_eligible += eligible
            self.pages_hit += len(pages)
            if pages:
                self.hits += 1
            elif eligible:
                self.misses += 1
        if pages:
            self.pool.pin_pages(pages)
        return pages

    def insert(self, prompt, table):
        """Publish a finished prefill's full prompt pages into the trie.
        Valid by the immutability invariant: pages 0..len(prompt)//ps - 1
        hold exactly the prompt tokens' K/V and nothing ever rewrites
        them. Already-cached depths are left alone."""
        ps = self.pool.page_size
        prompt = tuple(int(t) for t in prompt)
        n_full = len(prompt) // ps
        added = 0
        with self._lock:
            self._clock += 1
            for i in range(n_full):
                key = prompt[: (i + 1) * ps]
                if key in self._nodes:
                    self._nodes[key].stamp = self._clock
                    continue
                if len(self._nodes) >= self.capacity_pages:
                    if not self._evict_locked(1):
                        break
                page = int(table[i])
                if page == SCRATCH_PAGE:
                    break
                self.pool.pin_pages([page])
                self._nodes[key] = _PrefixNode(page, self._clock)
                added += 1
        return added

    def evict_for(self, n_pages):
        """Free up to `n_pages` unreferenced cached pages (LRU). Returns the
        number actually evicted — admission retries when > 0."""
        with self._lock:
            return self._evict_locked(n_pages)

    def _evict_locked(self, n_pages):
        # children before parents: a longer key is always at least as cold
        # as its prefix's extension, and dropping a parent first would leave
        # unreachable descendants pinned
        order = sorted(
            self._nodes.items(), key=lambda kv: (kv[1].stamp, -len(kv[0]))
        )
        evicted = 0
        for key, node in order:
            if evicted >= n_pages:
                break
            # only pages no slot is reading (our pin is the sole reference)
            if self.pool.page_refcount(node.page) != 1:
                continue
            if any(
                k != key and k[: len(key)] == key for k in self._nodes
            ):
                continue  # has live descendants; they sort earlier anyway
            del self._nodes[key]
            self.pool.unpin_pages([node.page])
            self.evictions += 1
            evicted += 1
        return evicted

    def reclaimable(self):
        """Cached pages only the trie holds — evictable on demand (the
        scheduler counts these as available when budgeting admissions)."""
        with self._lock:
            return sum(
                1
                for n in self._nodes.values()
                if self.pool.page_refcount(n.page) == 1
            )

    def clear(self):
        with self._lock:
            for node in self._nodes.values():
                self.pool.unpin_pages([node.page])
            n = len(self._nodes)
            self._nodes.clear()
            return n

    def stats(self):
        with self._lock:
            elig = self.pages_eligible
            return {
                "cached_pages": len(self._nodes),
                "capacity_pages": self.capacity_pages,
                "lookups_hit": self.hits,
                "lookups_miss": self.misses,
                "pages_hit": self.pages_hit,
                "pages_eligible": elig,
                "hit_rate": (self.pages_hit / elig) if elig else 0.0,
                "evictions": self.evictions,
            }
