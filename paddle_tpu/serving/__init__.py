"""Production serving runtime (ROADMAP item 1): the serving-side counterpart
of the training executors.

- engine.ServingEngine — AOT, donation-free, shape-bucketed forward executor
  over a `save_inference_model` directory; bounded compiled-variant set, no
  hot-path recompiles.
- batcher.ContinuousBatcher — continuous dynamic request batching
  (deadline-or-fill admission, bounded-queue backpressure, per-request
  timeout, drain/shutdown).
- compile_cache.CompileCache — persistent on-disk cache of serialized
  jax.export artifacts (+ XLA executable cache) so replicas cold-start in
  seconds; also owns the export_compiled artifact format.
- server.ModelServer — stdlib multi-model HTTP front end
  (`/v1/models/<name>:predict`, `/healthz`, `/metrics`).

docs/serving.md covers the architecture, bucketing policy, cache layout and
flags.
"""

from . import batcher, compile_cache, engine, server  # noqa: F401
from .batcher import (  # noqa: F401
    ContinuousBatcher,
    QueueFullError,
    RequestTimeout,
    ServingFuture,
    ShutdownError,
)
from .compile_cache import CompileCache  # noqa: F401
from .engine import ServingEngine  # noqa: F401
from .server import ModelServer  # noqa: F401

__all__ = [
    "ServingEngine",
    "ContinuousBatcher",
    "CompileCache",
    "ModelServer",
    "ServingFuture",
    "QueueFullError",
    "RequestTimeout",
    "ShutdownError",
]
