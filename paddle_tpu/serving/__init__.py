"""Production serving runtime (ROADMAP item 1): the serving-side counterpart
of the training executors.

- engine.ServingEngine — AOT, donation-free, shape-bucketed forward executor
  over a `save_inference_model` directory; bounded compiled-variant set, no
  hot-path recompiles.
- batcher.ContinuousBatcher — continuous dynamic request batching
  (deadline-or-fill admission, bounded-queue backpressure, per-request
  timeout, drain/shutdown).
- compile_cache.CompileCache — persistent on-disk cache of serialized
  jax.export artifacts (+ XLA executable cache) so replicas cold-start in
  seconds; also owns the export_compiled artifact format.
- server.ModelServer — stdlib multi-model HTTP front end
  (`/v1/models/<name>:predict`, `/v1/models/<name>:generate`, `/healthz`,
  `/metrics`).
- generation.GenerationEngine / GenerationScheduler — autoregressive
  serving (ROADMAP item 3): AOT prefill buckets + one fixed-shape decode
  step over a paged KV-cache pool (kv_cache.PagedKVPool), token-level
  continuous batching with mid-batch admission and EOS/max-len retirement.

docs/serving.md covers the architecture, bucketing policy, cache layout,
generation slot/page lifecycle, and flags.
"""

from . import batcher, compile_cache, engine, generation, kv_cache, server  # noqa: F401
from .batcher import (  # noqa: F401
    ContinuousBatcher,
    QueueFullError,
    RequestTimeout,
    ServingFuture,
    ShutdownError,
)
from .compile_cache import CompileCache  # noqa: F401
from .engine import ServingEngine  # noqa: F401
from .generation import (  # noqa: F401
    GenerationEngine,
    GenerationScheduler,
    GenRequest,
    GenResult,
)
from .kv_cache import PagedKVPool, PoolExhausted, PrefixCache  # noqa: F401
from .server import ModelServer  # noqa: F401

__all__ = [
    "ServingEngine",
    "ContinuousBatcher",
    "CompileCache",
    "ModelServer",
    "ServingFuture",
    "QueueFullError",
    "RequestTimeout",
    "ShutdownError",
    "GenerationEngine",
    "GenerationScheduler",
    "GenRequest",
    "GenResult",
    "PagedKVPool",
    "PrefixCache",
    "PoolExhausted",
]
