"""Multi-model HTTP serving front end (stdlib http.server, no deps).

One process hosts many models, each an (engine, batcher) pair; request
threads (ThreadingHTTPServer, one per connection) block on the batcher's
future while the dispatcher packs buckets — the Clipper frontend shape on
the reference's server-demo role (paddle/fluid/inference demos served one
Run() per request; here requests from all connections share device batches).

Routes:
- ``POST /v1/models/<name>:predict`` — body either JSON
  ``{"inputs": {feed: nested list, ...}}`` or a raw ``.npz`` payload
  (Content-Type ``application/x-npz``; one array per feed name). JSON
  replies as ``{"outputs": {fetch: nested list}, "latency_ms": float}``;
  npz requests reply as npz bytes.
- ``POST /v1/models/<name>:generate`` — autoregressive models only
  (add_generation_model). JSON body ``{"prompt": [ids...],
  "max_new_tokens": n, "temperature": t, "top_k": k, "seed": s,
  "eos_id": id}`` (prompt required, rest optional; no temperature means
  greedy). Replies ``{"tokens": [...], "finish_reason": "eos"|"length",
  "latency_ms": float}``.
- ``GET /healthz`` — liveness AND per-model readiness: 200 with
  ``{"status", "ready", "model_version", "models": {name: {"kind", "ready",
  "model_version", "queue_depth", "queued_rows", "variants"}}}``. A model is
  *ready* once its warmup precompiled every bucket — "up" (the process
  answers) and "routable" (this model serves without tracing) are different
  facts, and the fleet router + any external LB route on the second.
  ``/healthz?verbose=0`` keeps the original liveness-only shape.
- ``GET /v1/models`` — model metadata (feeds, fetches, buckets, stats).
- ``GET /v1/models/<name>`` — one model's metadata plus its live hot-swap
  state: ``model_version`` and the publisher's ``version_stamp`` (train
  step + wall time). Predict/generate replies carry ``model_version`` too —
  which hot-swapped version served THAT request (docs/online.md); the
  serving_staleness gauges ride ``/metrics``.
- ``GET /metrics`` — the PR 4 registry's Prometheus text exposition (same
  content observability/export.py writes to the scrape file).

Failure mapping: unknown model -> 404, malformed body -> 400, queue full /
deadline-shed admission -> 503, request timeout -> 504. 503/504 carry a
``Retry-After`` header derived from the batcher's measured queue drain rate
(rows queued / rows-per-second EWMA) instead of a constant.

Fault hooks (PADDLE_TPU_FAULTS, docs/resilience.md): every POST consults
``replica_kill`` (SIGKILL self — a replica dying mid-request),
``conn_reset`` (close the socket without replying) and ``slow_response``
(sleep spec.ms first) so the fleet router's failover, retry and breaker
paths soak under the same deterministic fault plans as the trainer.
"""

import io as _stdio
import json
import threading
import time

import numpy as np

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..observability import flightrec as _flightrec
from ..observability import tracing as _tracing
from ..observability.tracing import NULL_SPAN, TRACE_HEADER
from ..resilience import faults as _faults
from .batcher import ContinuousBatcher, QueueFullError, RequestTimeout
from .engine import ServingEngine

__all__ = ["ModelServer"]

PREDICT_PREFIX = "/v1/models/"


class _Hosted:
    __slots__ = ("engine", "batcher", "kind", "warmed")

    def __init__(self, engine, batcher, kind="predict", warmed=False):
        self.engine = engine
        self.batcher = batcher
        self.kind = kind
        # readiness, not liveness: True once warmup precompiled every
        # bucket, i.e. this model serves without tracing
        self.warmed = warmed


class ModelServer:
    """Host N models behind one threaded HTTP listener."""

    def __init__(self, host="127.0.0.1", port=0, request_timeout_ms=5000.0):
        self.host = host
        self._port = port
        self.request_timeout = float(request_timeout_ms) / 1e3
        self._models = {}
        self._httpd = None
        self._thread = None
        from ..observability import registry as _registry

        self._registry = _registry.default_registry()
        self._m_http = self._registry.counter(
            "serving/http/requests", "HTTP requests by code label"
        )

    # ---- model hosting ----------------------------------------------------
    def add_model(self, name, model_dir=None, engine=None, warmup=True,
                  warmup_feed=None, batcher_opts=None, **engine_opts):
        """Register a model. Either pass a prebuilt `engine` or a
        `model_dir` (plus ServingEngine kwargs). Warmup precompiles every
        bucket before the model is visible, so the serving hot path never
        traces."""
        if engine is None:
            if model_dir is None:
                raise ValueError("add_model needs model_dir or engine")
            engine = ServingEngine(model_dir, name=name, **engine_opts)
        if warmup:
            engine.warmup(example_feed=warmup_feed)
        batcher = ContinuousBatcher(engine, **(batcher_opts or {}))
        self._models[name] = _Hosted(engine, batcher, warmed=bool(warmup))
        return engine

    def add_generation_model(self, name, model=None, engine=None, warmup=True,
                             scheduler_opts=None, **engine_opts):
        """Register an autoregressive model behind the `:generate` route.
        Either pass a prebuilt GenerationEngine or a decoder `model`
        (GPTDecoder protocol, plus GenerationEngine kwargs). Warmup
        precompiles the decode step and every prefill bucket."""
        from .generation import GenerationEngine, GenerationScheduler

        if engine is None:
            if model is None:
                raise ValueError("add_generation_model needs model or engine")
            engine = GenerationEngine(model, name=name, **engine_opts)
        if warmup:
            engine.warmup()
        scheduler = GenerationScheduler(engine, **(scheduler_opts or {}))
        self._models[name] = _Hosted(
            engine, scheduler, kind="generate", warmed=bool(warmup)
        )
        return engine

    def models(self):
        return sorted(self._models)

    # ---- lifecycle --------------------------------------------------------
    def start(self):
        """Bind + serve on a daemon thread; returns the bound port (useful
        with port=0)."""
        server = self

        class Handler(BaseHTTPRequestHandler):
            # one handler class per ModelServer instance: the closure is the
            # routing table
            def log_message(self, fmt, *args):  # quiet by default
                pass

            def _reply(self, code, body, content_type="application/json",
                       retry_after=None, trace=None):
                server._m_http.inc(code=str(code))
                if code >= 500:
                    # a replica-side 5xx is a flight-recorder trigger: the
                    # span ring at this instant holds the request's story
                    _flightrec.trigger(
                        "http_5xx", code=code, path=self.path, trace=trace
                    )
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                if retry_after is None and code == 503:
                    retry_after = 1
                if retry_after is not None:
                    self.send_header("Retry-After", str(int(retry_after)))
                if trace:
                    self.send_header(TRACE_HEADER, trace)
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, code, obj):
                self._reply(code, json.dumps(obj).encode())

            def do_GET(self):
                try:
                    if self.path == "/healthz" or self.path.startswith(
                        "/healthz?"
                    ):
                        verbose = "verbose=0" not in self.path
                        self._reply_json(200, server._healthz(verbose))
                    elif self.path == "/v1/models":
                        self._reply_json(200, server._describe())
                    elif (self.path.startswith(PREDICT_PREFIX)
                          and ":" not in self.path):
                        code, obj = server._describe_one(
                            self.path[len(PREDICT_PREFIX):]
                        )
                        self._reply_json(code, obj)
                    elif self.path == "/metrics":
                        self._reply(
                            200,
                            server._registry.to_prometheus().encode(),
                            content_type="text/plain; version=0.0.4",
                        )
                    else:
                        self._reply_json(404, {"error": "no route %s" % self.path})
                except Exception as e:  # handler thread must answer, not die
                    self._reply_json(500, {"error": repr(e)})

            def do_POST(self):
                # the server span adopts the router's trace context before
                # the fault hooks run, so even a request that dies to an
                # injected fault leaves its span in this replica's shard
                span = _tracing.tracer().start_span(
                    "server.request",
                    parent=self.headers.get(TRACE_HEADER),
                    path=self.path,
                )
                try:
                    # serving-side fault hooks (docs/resilience.md): a
                    # replica dying mid-request, a half-open connection, a
                    # browned-out reply — the failure menu the fleet
                    # router's failover/retry/breaker paths soak against
                    _faults.kill_self("replica_kill")
                    if _faults.fires("conn_reset"):
                        span.tag(fault="conn_reset").end("error")
                        self.close_connection = True
                        self.connection.close()
                        return
                    _faults.delay("slow_response")
                    code, body, ctype, retry_after = server._predict(
                        self.path,
                        self.headers.get("Content-Type", ""),
                        self.rfile.read(
                            int(self.headers.get("Content-Length", 0))
                        ),
                        parent=span,
                    )
                    span.tag(code=code)
                    self._reply(code, body, content_type=ctype,
                                retry_after=retry_after,
                                trace=span.header())
                    span.end("ok" if code < 500 else "error")
                except Exception as e:
                    span.error(e).end()
                    self._reply_json(500, {"error": repr(e)})

        self._httpd = ThreadingHTTPServer((self.host, self._port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="model-server", daemon=True
        )
        self._thread.start()
        return self._httpd.server_address[1]

    @property
    def port(self):
        return self._httpd.server_address[1] if self._httpd else self._port

    @property
    def url(self):
        return "http://%s:%d" % (self.host, self.port)

    def stop(self, drain=True):
        """Shut the listener, then drain (or fail out) every batcher."""
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._thread.join(10.0)
            self._httpd = None
        ok = True
        for hosted in self._models.values():
            ok = hosted.batcher.close(drain=drain) and ok
        return ok

    # ---- request handling (thread-safe, called from handler threads) ------
    def _healthz(self, verbose=True):
        """Liveness + (verbose) per-model readiness. The old liveness-only
        shape survives under ``?verbose=0`` for pre-fleet scrapers."""
        if not verbose:
            return {
                "status": "ok",
                "models": {
                    name: {"variants": h.engine.stats()["variants"]}
                    for name, h in self._models.items()
                },
            }
        models = {}
        ready = bool(self._models)
        for name, h in self._models.items():
            bstats = h.batcher.stats()
            models[name] = {
                "kind": h.kind,
                "ready": h.warmed,
                "model_version": getattr(h.engine, "model_version", 0),
                "queue_depth": len(h.batcher._queue),
                "queued_rows": bstats.get("queued_rows", 0),
                "variants": h.engine.stats()["variants"],
            }
            ready = ready and h.warmed
        return {
            "status": "ok",
            "ready": ready,
            # the max over models: the fleet router gates one repo-backed
            # model, and a replica serving several reports the newest
            "model_version": max(
                [m["model_version"] for m in models.values()] or [0]
            ),
            "models": models,
        }

    def _describe(self):
        return {name: self._describe_one(name)[1] for name in self._models}

    def _describe_one(self, name):
        """(status, body) for GET /v1/models/<name>: the model's metadata
        plus its live hot-swap state — model_version and the publisher's
        staleness stamp (train step + wall time of the serving version)."""
        h = self._models.get(name)
        if h is None:
            return 404, {
                "error": "unknown model %r (have %s)" % (name, self.models())
            }
        if h.kind == "generate":
            out = {
                "kind": "generate",
                "stats": h.engine.stats(),
                "scheduler": h.batcher.stats(),
            }
        else:
            out = {
                "feeds": h.engine.feed_names,
                "fetches": h.engine.fetch_names,
                "batch_buckets": list(h.engine.batch_buckets),
                "stats": h.engine.stats(),
                "batcher": h.batcher.stats(),
            }
        out["model_version"] = getattr(h.engine, "model_version", 0)
        stamp = getattr(h.engine, "version_stamp", None)
        if stamp:
            out["version_stamp"] = dict(stamp)
        return 200, out

    def _predict(self, path, content_type, body, parent=NULL_SPAN):
        """(status, reply bytes, content type, retry-after hint) for one
        predict/generate POST. retry_after is None except on 503/504, where
        it is derived from the batcher's measured queue drain rate."""
        if path.startswith(PREDICT_PREFIX) and path.endswith(":generate"):
            return self._generate(
                path[len(PREDICT_PREFIX):-len(":generate")], body,
                parent=parent,
            )
        if not (path.startswith(PREDICT_PREFIX) and path.endswith(":predict")):
            return 404, json.dumps({"error": "no route %s" % path}).encode(), \
                "application/json", None
        name = path[len(PREDICT_PREFIX):-len(":predict")]
        hosted = self._models.get(name)
        if hosted is None:
            return 404, json.dumps(
                {"error": "unknown model %r (have %s)" % (name, self.models())}
            ).encode(), "application/json", None
        if hosted.kind != "predict":
            return 400, json.dumps(
                {"error": "model %r serves :generate, not :predict" % name}
            ).encode(), "application/json", None

        as_npz = "npz" in content_type or content_type == "application/octet-stream"
        try:
            if as_npz:
                data = np.load(_stdio.BytesIO(body), allow_pickle=False)
                feed = {k: data[k] for k in data.files}
            else:
                doc = json.loads(body.decode() or "{}")
                inputs = doc.get("inputs")
                if not isinstance(inputs, dict):
                    raise ValueError('body needs {"inputs": {feed: array}}')
                feed = {
                    k: np.asarray(v, dtype=hosted.engine._feed_dtype(k))
                    if k in hosted.engine._feed_dtypes
                    else np.asarray(v)
                    for k, v in inputs.items()
                }
        except Exception as e:
            return 400, json.dumps({"error": "bad payload: %r" % e}).encode(), \
                "application/json", None

        t0 = time.perf_counter()
        try:
            future = hosted.batcher.submit(feed, parent=parent)
        except QueueFullError as e:
            return 503, json.dumps({"error": str(e)}).encode(), \
                "application/json", self._retry_after(hosted, e)
        except ValueError as e:
            return 400, json.dumps({"error": str(e)}).encode(), \
                "application/json", None
        try:
            outs = future.result(self.request_timeout)
        except RequestTimeout as e:
            return 504, json.dumps({"error": str(e)}).encode(), \
                "application/json", self._retry_after(hosted, e)
        except Exception as e:
            return 500, json.dumps({"error": repr(e)}).encode(), \
                "application/json", None
        latency_ms = (time.perf_counter() - t0) * 1e3
        version = getattr(future, "model_version", None)
        if version is None:
            version = getattr(hosted.engine, "model_version", 0)

        if as_npz:
            buf = _stdio.BytesIO()
            np.savez(
                buf,
                **{
                    n: np.asarray(o, dtype=np.float32)
                    if "bfloat16" in str(np.asarray(o).dtype)
                    else np.asarray(o)
                    for n, o in zip(hosted.engine.fetch_names, outs)
                },
            )
            return 200, buf.getvalue(), "application/x-npz", None
        return 200, json.dumps(
            {
                "outputs": {
                    n: np.asarray(o, dtype=np.float64).tolist()
                    if "bfloat16" in str(np.asarray(o).dtype)
                    else np.asarray(o).tolist()
                    for n, o in zip(hosted.engine.fetch_names, outs)
                },
                "model_version": version,
                "latency_ms": latency_ms,
            }
        ).encode(), "application/json", None

    @staticmethod
    def _retry_after(hosted, err):
        """Retry-After seconds for a 503/504: the exception's drain estimate
        when the batcher attached one, else its live hint."""
        est = getattr(err, "retry_after_s", None)
        if est is not None:
            return int(min(max(-(-est // 1), 1), 30))
        hint = getattr(hosted.batcher, "retry_after_hint", None)
        return hint() if callable(hint) else 1

    def _generate(self, name, body, parent=NULL_SPAN):
        """(status, reply bytes, content type, retry-after hint) for one
        :generate POST."""
        hosted = self._models.get(name)
        if hosted is None:
            return 404, json.dumps(
                {"error": "unknown model %r (have %s)" % (name, self.models())}
            ).encode(), "application/json", None
        if hosted.kind != "generate":
            return 400, json.dumps(
                {"error": "model %r serves :predict, not :generate" % name}
            ).encode(), "application/json", None
        try:
            doc = json.loads(body.decode() or "{}")
            prompt = doc.get("prompt")
            if not isinstance(prompt, (list, tuple)) or not prompt:
                raise ValueError('body needs {"prompt": [token ids...]}')
            kw = {
                k: doc[k]
                for k in ("max_new_tokens", "eos_id", "temperature",
                          "top_k", "seed")
                if doc.get(k) is not None
            }
        except (ValueError, json.JSONDecodeError) as e:
            return 400, json.dumps({"error": "bad payload: %r" % e}).encode(), \
                "application/json", None

        t0 = time.perf_counter()
        try:
            future = hosted.batcher.submit(prompt, parent=parent, **kw)
        except QueueFullError as e:
            return 503, json.dumps({"error": str(e)}).encode(), \
                "application/json", self._retry_after(hosted, e)
        except ValueError as e:
            return 400, json.dumps({"error": str(e)}).encode(), \
                "application/json", None
        try:
            res = future.result(self.request_timeout)
        except RequestTimeout as e:
            return 504, json.dumps({"error": str(e)}).encode(), \
                "application/json", self._retry_after(hosted, e)
        except Exception as e:
            return 500, json.dumps({"error": repr(e)}).encode(), \
                "application/json", None
        return 200, json.dumps(
            {
                "tokens": list(res.tokens),
                "finish_reason": res.finish_reason,
                "prompt_len": res.prompt_len,
                # the live version at completion time (token-level attribution
                # across a mid-request swap is meaningless for AR decode)
                "model_version": getattr(hosted.engine, "model_version", 0),
                "latency_ms": (time.perf_counter() - t0) * 1e3,
            }
        ).encode(), "application/json", None
