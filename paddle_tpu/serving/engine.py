"""AOT serving engine: one model, a bounded set of shape-bucketed compiled
variants, no hot-path recompiles.

The training executors compile per exact feed shape and donate state — both
wrong for serving: request batch sizes vary per call (an unbounded compile
set), and a replica's parameters must survive every call. The engine
instead:

- loads a `save_inference_model` directory into a private Scope and lowers
  it ONCE through executor.aot_serve_lowering (donation-free, params as
  arguments);
- pads every request's batch dim to a small set of power-of-two buckets and
  slices outputs back to the true rows, so the number of compiled variants
  is bounded by the bucket grid, never by traffic;
- builds each variant through serving.compile_cache: a warm replica
  deserializes `jax.export` artifacts and replays XLA executables from disk
  instead of tracing (cold-start-from-cache, the SERVING bench's 5× bar);
Batch-dim padding is invisible to callers: every op in a forward program is
row-independent along the batch dim, so padded rows never contaminate real
rows and slicing them away restores the exact unpadded result.

Declared-dynamic TRAILING dims (-1 in the program's var shape — sequence
lengths) are a different story. Zero-padding a sequence changes the output
of any model that reduces across it (softmax attention, mean-pooling,
layernorm over time): the engine has no mask plumbing, so the padded
positions would participate in the math. The `trailing_pad` policy makes
that hazard explicit:

- ``"exact"`` (default): dynamic trailing dims are never padded — each
  distinct trailing shape compiles its own variant, so results are correct
  for EVERY model. The variant count is bounded by the bucket grid times
  the distinct trailing shapes in traffic; clients wanting a bounded set
  should quantize sequence lengths themselves (that quantization belongs
  where the mask/real-length knowledge lives).
- ``"pow2"``: trailing dims pad to the next power of two with zeros —
  bounded variants under arbitrary lengths, but ONLY sound for models
  proven padding-invariant along those dims (e.g. masked attention that
  consumes an explicit length feed). Opting in asserts that proof; the
  engine cannot check it.

Thread-safety: variant construction is locked; the compiled calls themselves
are jax jitted functions, safe to invoke from any thread (the batcher
serializes device work anyway). Telemetry (device-time histogram, batch-fill
histogram, padded-rows counter, trace counter) rides the PR 4 registry under
`serving/<model>/...`.
"""

import threading
import time

import numpy as np

from .. import io as _io
from ..executor import Executor, Scope, aot_serve_lowering, scope_guard
from ..observability import tracing as _tracing
from . import compile_cache as _cc

__all__ = ["ServingEngine", "DEFAULT_BATCH_BUCKETS"]

DEFAULT_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32)

# batch-fill ratio buckets: 0..1 in tenths
_FILL_BUCKETS = tuple(i / 10.0 for i in range(1, 11))


def _next_pow2(n):
    n = max(int(n), 1)
    p = 1
    while p < n:
        p *= 2
    return p


class ServingEngine:
    """Shape-bucketed, donation-free forward executor for one saved model."""

    def __init__(self, model_dir, name=None, place=None, params_filename=None,
                 batch_buckets=None, cache_dir=None, trailing_pad="exact",
                 precision="native", calibration_feeds=None):
        import jax

        if trailing_pad not in ("exact", "pow2"):
            raise ValueError(
                "trailing_pad must be 'exact' or 'pow2', got %r" % (trailing_pad,)
            )
        self.trailing_pad = trailing_pad
        if precision not in ("native", "int8"):
            raise ValueError(
                "precision must be 'native' or 'int8', got %r" % (precision,)
            )
        if precision == "int8" and not calibration_feeds:
            raise ValueError(
                "precision='int8' needs calibration_feeds (a list of "
                "representative feed dicts) to set activation scales"
            )
        self.precision = precision

        self.name = name or model_dir.rstrip("/").rsplit("/", 1)[-1]
        self.scope = Scope()
        exe = Executor(place)
        with scope_guard(self.scope):
            program, feed_names, fetch_vars = _io.load_inference_model(
                model_dir, exe, params_filename=params_filename
            )
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = [v.name for v in fetch_vars]
        self.fingerprint = _io.inference_model_fingerprint(model_dir)

        block = program.global_block()
        self._var_shapes = {}
        self._feed_dtypes = {}
        for n in self.feed_names:
            v = block.vars.get(n)
            if v is None:
                continue
            self._var_shapes[n] = (
                tuple(v.shape) if v.shape is not None else None
            )
            if v.dtype is not None:
                self._feed_dtypes[n] = v.dtype

        # FLAGS_static_verify: lint the loaded artifact as-deserialized (the
        # aot_serve_lowering gate below re-verifies post-pipeline), so a
        # corrupt or mis-exported model names its defect at load, not at the
        # first request
        from ..analysis import maybe_static_verify

        maybe_static_verify(
            program, self.feed_names, self.fetch_names, scope=self.scope,
            mode="serving", where="serving:%s" % self.name,
        )
        self.quant_results = None
        if precision == "int8":
            # calibrated-int8 pipeline (passes/quant.py): calibrate with the
            # representative feeds, freeze weights + bake static scales into
            # this engine's PRIVATE scope, tag the int8 chains — then lower
            # the rewritten program verbatim (the pipeline already ran, so
            # aot_serve_lowering must not re-apply "inference" on top)
            from ..passes.manager import PassManager

            program = PassManager("inference_int8").apply(
                program, scope=self.scope, feed_names=self.feed_names,
                fetch_names=self.fetch_names,
                attrs={"calibrate": {"feeds": list(calibration_feeds)}},
            )
            self.program = program
            self.quant_results = {
                k: program._pass_results.get(k)
                for k in ("calibrate", "quantize_serving", "fuse_quant_gemm")
            }
            if not (self.quant_results["quantize_serving"] or {}).get(
                "quantized"
            ):
                raise ValueError(
                    "precision='int8': no mul op quantized — the model has "
                    "no fc/mul layers with scope weights and calibrated "
                    "inputs (ranges recorded: %d)"
                    % len(
                        (self.quant_results["calibrate"] or {}).get(
                            "ranges", {}
                        )
                    )
                )
        with scope_guard(self.scope):
            self._serve, self._ro, self._mut = aot_serve_lowering(
                program, self.feed_names, self.fetch_names, self.scope,
                pass_pipeline="off" if precision == "int8" else "inference",
            )

        # hot-swap state (docs/online.md): set_params atomically replaces
        # the _ro/_mut dict OBJECTS under _swap_lock; _run_bucket snapshots
        # (ro, mut, version) under the same lock, so an in-flight call
        # finishes on the params it started with and a swap never waits on
        # device work. version 0 = as-loaded from disk.
        self.model_version = 0
        self.version_stamp = {}
        self._swap_lock = threading.Lock()
        self._served_tls = threading.local()

        buckets = batch_buckets or DEFAULT_BATCH_BUCKETS
        self.batch_buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.batch_buckets or self.batch_buckets[0] < 1:
            raise ValueError("batch_buckets must be positive: %r" % (buckets,))
        self.max_batch = self.batch_buckets[-1]

        if cache_dir is None:
            from .. import flags as _flags

            cache_dir = _flags.get_flags("serving_cache_dir")["serving_cache_dir"]
        self.cache = _cc.CompileCache(cache_dir) if cache_dir else None

        self._variants = {}
        self._variant_tags = {}  # id(compiled fn) -> trace display string
        self._build_lock = threading.Lock()
        self.traces = 0  # variants traced+compiled (not served from cache)
        self.cache_hits = 0  # variants deserialized from the compile cache

        from ..observability import registry as _registry

        reg = _registry.default_registry()
        p = "serving/%s" % self.name
        self._m_device_ms = reg.histogram(
            p + "/device_ms", "per-engine-call device time (padded bucket)"
        )
        self._m_fill = reg.histogram(
            p + "/batch_fill", "real rows / bucket rows per engine call",
            buckets=_FILL_BUCKETS,
        )
        self._m_rows = reg.counter(p + "/rows", "real request rows executed")
        self._m_padded = reg.counter(
            p + "/padded_rows", "padding-waste rows added to fill buckets"
        )
        self._m_traces = reg.counter(
            p + "/traces", "serving variants traced (compile-cache misses)"
        )
        self._m_variants = reg.gauge(
            p + "/variants", "compiled serving variants resident"
        )
        self._m_version = reg.gauge(
            p + "/model_version", "live hot-swapped parameter version"
        )
        self._m_swaps = reg.counter(
            p + "/hot_swaps", "set_params hot swaps applied"
        )
        self._m_version.set(0.0)
        self._m_precision = reg.gauge(
            p + "/precision",
            "serving numeric tier (0 = native float, 1 = calibrated int8)",
        )
        self._m_precision.set(1.0 if self.precision == "int8" else 0.0)

    # ---- bucketing --------------------------------------------------------
    def bucket_batch(self, n):
        """Smallest configured bucket >= n (n > max_batch is chunked by
        run())."""
        for b in self.batch_buckets:
            if n <= b:
                return b
        return self.max_batch

    def _bucket_shape(self, name, shape):
        """Padded shape for one feed: batch dim -> bucket; trailing dims pass
        through exactly unless trailing_pad='pow2', in which case dims the
        program declares dynamic (-1) pad to the next power of two — sound
        ONLY for padding-invariant models (see the module docstring)."""
        out = [self.bucket_batch(shape[0])]
        if self.trailing_pad == "pow2":
            declared = self._var_shapes.get(name)
            for i, d in enumerate(shape[1:], start=1):
                dd = (
                    declared[i]
                    if declared is not None and len(declared) == len(shape)
                    else None
                )
                out.append(_next_pow2(d) if dd in (-1, None) else int(d))
        else:
            out.extend(int(d) for d in shape[1:])
        return tuple(out)

    def _feed_dtype(self, name, default=None):
        """The program's declared dtype for a feed, or `default` when the
        program declares none (the request array then keeps its own dtype —
        an undeclared integer id feed must not silently become float32)."""
        dt = self._feed_dtypes.get(name)
        if dt is None:
            return default
        if dt == "bfloat16":
            import jax.numpy as jnp

            return jnp.bfloat16
        return np.dtype(dt)

    # ---- variants ---------------------------------------------------------
    def _variant(self, avals):
        """Compiled callable for one padded-shape signature, building through
        the persistent cache on first sight. `avals` is {feed name:
        jax.ShapeDtypeStruct}."""
        import jax
        from jax import export as jax_export

        vkey = tuple(
            sorted((n, s.shape, str(s.dtype)) for n, s in avals.items())
        )
        fn = self._variants.get(vkey)
        if fn is not None:
            return fn
        with self._build_lock:
            fn = self._variants.get(vkey)
            if fn is not None:
                return fn

            def build():
                self.traces += 1
                self._m_traces.inc()
                return jax_export.export(jax.jit(self._serve))(
                    avals, self._ro, self._mut
                )

            if self.cache is not None:
                # int8 variants key on a precision geometry: the rewritten
                # program shares the model dir's fingerprint with the native
                # lowering, so without it an int8 boot could replay a native
                # executable (and vice versa). Native keys stay unchanged.
                ck = _cc.variant_key(
                    self.fingerprint,
                    {n: (s.shape, s.dtype) for n, s in avals.items()},
                    self.fetch_names,
                    geometry=(
                        {"precision": self.precision}
                        if self.precision != "native"
                        else None
                    ),
                )
                exported, hit = self.cache.get_or_build(
                    ck, build,
                    meta={
                        "model": self.name,
                        "feeds": {
                            n: [list(s.shape), str(s.dtype)]
                            for n, s in avals.items()
                        },
                        "fetches": self.fetch_names,
                    },
                )
                if hit:
                    self.cache_hits += 1
            else:
                exported = build()

            # AOT-compile the wrapper for this exact signature: the variant
            # is a jax Compiled object, so warmup pays the full
            # StableHLO->executable step up front (a disk hit when the xla/
            # persistent cache is warm) and the hot path can never retrace
            fn = jax.jit(
                lambda feeds, ro, mut, _call=exported.call: _call(feeds, ro, mut)
            ).lower(avals, self._ro, self._mut).compile()
            self._variants[vkey] = fn
            self._m_variants.set(len(self._variants))
            return fn

    def warmup(self, example_feed=None):
        """Precompile every batch bucket so the hot path never traces.

        Builds (does not execute) each bucket's variant — compilation is what
        the hot path must never re-pay; running zeros through the model would
        only add device time. Trailing dims come from the program's declared
        var shapes; models with dynamic (-1) trailing dims need
        `example_feed` (one array per feed name) to pin them. Returns the
        number of variants built."""
        import jax

        shapes = {}
        dtypes = {}
        for n in self.feed_names:
            if example_feed is not None and n in example_feed:
                ex = np.asarray(example_feed[n])
                shapes[n] = tuple(ex.shape[1:])
                dtypes[n] = self._feed_dtype(n, default=ex.dtype)
                continue
            declared = self._var_shapes.get(n)
            if declared is None or any(d in (-1, None) for d in declared[1:]):
                raise ValueError(
                    "feed %r has dynamic non-batch dims %r: warmup needs an "
                    "example_feed to pin them" % (n, declared)
                )
            shapes[n] = tuple(int(d) for d in declared[1:])
            dtypes[n] = self._feed_dtype(n, default=np.dtype("float32"))
        for b in self.batch_buckets:
            avals = {
                n: jax.ShapeDtypeStruct(
                    self._bucket_shape(n, (b,) + shapes[n]), dtypes[n]
                )
                for n in self.feed_names
            }
            self._variant(avals)
        return len(self._variants)

    # ---- hot swap ---------------------------------------------------------
    def param_names(self):
        """Every live parameter/state name a hot swap may target."""
        with self._swap_lock:
            return sorted(set(self._ro) | set(self._mut))

    def set_params(self, updates, version=None, stamp=None):
        """Hot-swap parameter values WITHOUT recompiling or dropping
        requests. `updates` maps name -> new full array; names the lowering
        doesn't close over are ignored (a publisher may ship a superset).
        Values are cast to the stored dtype; a shape mismatch raises — a
        geometry change would invalidate every compiled variant, which is a
        new model, not a swap (compile_cache.variant_key hashes avals, never
        values, so same-aval swaps keep the cache and variants valid).

        The swap is two dict replacements under _swap_lock — O(params)
        host-side pointer updates, no device sync. Returns the number of
        arrays applied."""
        import jax.numpy as jnp

        new_ro = dict(self._ro)
        new_mut = dict(self._mut)
        applied = 0
        for name, val in updates.items():
            tgt = new_ro if name in new_ro else (
                new_mut if name in new_mut else None
            )
            if tgt is None:
                continue
            old = tgt[name]
            arr = jnp.asarray(np.asarray(val), dtype=old.dtype)
            if tuple(arr.shape) != tuple(np.shape(old)):
                raise ValueError(
                    "set_params(%r): shape %s != lowered aval %s — geometry "
                    "changes need a model reload, not a hot swap"
                    % (name, tuple(arr.shape), tuple(np.shape(old)))
                )
            tgt[name] = arr
            self.scope.vars[name] = arr
            applied += 1
        with self._swap_lock:
            self._ro = new_ro
            self._mut = new_mut
            self.model_version = (
                int(version) if version is not None else self.model_version + 1
            )
            self.version_stamp = dict(stamp or {})
            ver = self.model_version
        self._m_version.set(float(ver))
        self._m_swaps.inc()
        return applied

    def last_served_version(self):
        """The model_version the CALLING thread's most recent engine call
        executed against (the batcher's dispatcher reads this right after
        run() to stamp each response)."""
        return getattr(self._served_tls, "version", self.model_version)

    # ---- serving ----------------------------------------------------------
    def run(self, feed):
        """Serve one feed dict (or list zipped with feed_names): pad to the
        bucket, execute the compiled variant, slice outputs back to the true
        row count. Returns numpy arrays for the model's fetch targets."""
        if isinstance(feed, (list, tuple)):
            feed = dict(zip(self.feed_names, feed))
        missing = [n for n in self.feed_names if n not in feed]
        if missing:
            raise ValueError("missing feeds: %s" % missing)
        unknown = sorted(set(feed) - set(self.feed_names))
        if unknown:
            raise ValueError(
                "unknown feeds: %s (model takes %s)" % (unknown, self.feed_names)
            )
        arrays = {n: np.asarray(feed[n]) for n in self.feed_names}
        rows = {np.shape(a)[0] if np.ndim(a) else 1 for a in arrays.values()}
        if len(rows) != 1:
            raise ValueError(
                "feeds disagree on batch rows: %s"
                % {n: np.shape(a) for n, a in arrays.items()}
            )
        n = rows.pop()
        if n == 0:
            raise ValueError("empty batch")
        if n > self.max_batch:
            # oversize request: chunk through the largest bucket. Batch-major
            # outputs concatenate; non-batch outputs (rare for inference —
            # e.g. a scalar mean) keep the last chunk's value.
            outs = None
            for lo in range(0, n, self.max_batch):
                part = self._run_bucket(
                    {k: a[lo:lo + self.max_batch] for k, a in arrays.items()}
                )
                if outs is None:
                    outs = [[o] for o in part]
                else:
                    for acc, o in zip(outs, part):
                        acc.append(o)
            return [
                np.concatenate(acc) if np.ndim(acc[0]) else acc[-1]
                for acc in outs
            ]
        return self._run_bucket(arrays)

    def _run_bucket(self, arrays):
        import jax

        n = next(iter(arrays.values())).shape[0]
        padded = {}
        avals = {}
        for name, a in arrays.items():
            dt = self._feed_dtype(name)
            a = (
                np.ascontiguousarray(a)
                if dt is None
                else np.ascontiguousarray(a, dtype=dt)
            )
            shape = self._bucket_shape(name, a.shape)
            if tuple(a.shape) != shape:
                buf = np.zeros(shape, dtype=a.dtype)
                buf[tuple(slice(0, d) for d in a.shape)] = a
                a = buf
            padded[name] = a
            avals[name] = jax.ShapeDtypeStruct(shape, a.dtype)
        bucket = next(iter(padded.values())).shape[0]

        fn = self._variant(avals)
        # snapshot the param dicts + version together: a concurrent
        # set_params replaces the dict objects, so this call runs entirely
        # on one coherent version and reports it faithfully
        with self._swap_lock:
            ro, mut, ver = self._ro, self._mut, self.model_version
        # execute span under the caller's activated span (the batcher's
        # serving.batch); truthiness-gated so the tracing-off path never
        # builds the variant-key string
        span = _tracing.current()
        if span:
            # the variant display string is a pure function of the compiled
            # variant: build it once per variant, not per request
            vtag = self._variant_tags.get(id(fn))
            if vtag is None:
                vtag = ",".join(
                    "%s:%s:%s" % (nm, "x".join(map(str, s.shape)), s.dtype)
                    for nm, s in sorted(avals.items())
                )
                self._variant_tags[id(fn)] = vtag
            span = span.child(
                "engine.execute", variant=vtag, bucket=bucket, rows=n,
                precision=self.precision, model_version=ver,
            )
        t0 = time.perf_counter()
        outs = fn(padded, ro, mut)
        self._served_tls.version = ver
        outs = [np.asarray(o) for o in outs]
        device_ms = (time.perf_counter() - t0) * 1e3
        span.tag(device_ms=round(device_ms, 3)).end()
        self._m_device_ms.observe(device_ms)
        self._m_rows.inc(n)
        self._m_padded.inc(bucket - n)
        self._m_fill.observe(n / float(bucket))
        # slice only batch-major outputs back to the true rows; outputs that
        # don't carry the padded batch dim (scalar stats) pass through
        return [
            o[:n] if np.ndim(o) and o.shape[0] == bucket else o for o in outs
        ]

    def stats(self):
        """Variant/compile accounting for benches and smoke tests."""
        out = {
            "variants": len(self._variants),
            "traces": self.traces,
            "cache_hits": self.cache_hits,
            "trailing_pad": self.trailing_pad,
            "model_version": self.model_version,
            "precision": self.precision,
        }
        if self.quant_results is not None:
            qs = self.quant_results.get("quantize_serving") or {}
            fq = self.quant_results.get("fuse_quant_gemm") or {}
            out["quant"] = {
                "quantized_muls": qs.get("quantized", 0),
                "weights_frozen": len(qs.get("weights_frozen", ())),
                "fused_groups": fq.get("groups", 0),
                "calibrated_ranges": len(
                    (self.quant_results.get("calibrate") or {}).get(
                        "ranges", {}
                    )
                ),
            }
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        return out
