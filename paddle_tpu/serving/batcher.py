"""Continuous dynamic batcher: a background thread that packs concurrent
requests into engine buckets.

Request-handling model: Clipper's adaptive-batching frontend crossed with
Orca's continuous admission — the dispatcher does not wait for a full batch
boundary; it admits whatever is queued the moment either (a) enough rows are
waiting to fill the largest bucket, or (b) the oldest request has waited
`max_batch_delay_ms`. Padding to the power-of-two bucket is the engine's
job; admitted requests that disagree on dynamic trailing dims (mixed
sequence lengths) are packed and executed per same-trailing-shape group, so
mixed-length traffic costs extra engine calls, never failed requests. The
batcher's job is the time/row tradeoff and the failure modes:

- **backpressure**: the queue is bounded in ROWS (not requests — a single
  512-row request is 512 rows of device debt). A full queue fast-fails
  submit() with QueueFullError, the HTTP front end's 503.
- **deadline-aware admission**: beyond the row cap, submit() sheds work it
  cannot finish inside the per-request timeout — once the measured drain
  rate (EWMA rows/s over engine calls) says the rows already queued will
  take longer than `timeout_ms` to clear, accepting more would only
  manufacture future 504s, so the request is rejected NOW while the client
  can still fail over. Both rejection flavors carry `retry_after_s`
  (queued_rows / drain_rate) — the HTTP front end's Retry-After hint.
- **per-request timeout**: a request that ages past `timeout_ms` before its
  batch executes fails with RequestTimeout (HTTP 504) instead of occupying
  a bucket slot.
- **drain/shutdown**: close(drain=True) stops admission, lets the worker
  finish the queue, and joins it; close(drain=False) fails queued requests
  with ShutdownError.

Telemetry (PR 4 registry, `serving/<model>/...`): queue_ms and latency_ms
histograms split queue wait from the engine's device_ms, queue-depth and
in-flight gauges, and a `requests` counter labelled by outcome
(ok/rejected/timeout/error/shutdown).
"""

import threading
import time

import numpy as np

from ..observability import tracing as _tracing
from ..observability.tracing import NULL_SPAN

__all__ = [
    "ContinuousBatcher",
    "ServingFuture",
    "QueueFullError",
    "RequestTimeout",
    "ShutdownError",
]


class QueueFullError(RuntimeError):
    """Bounded request queue is full, or the measured drain rate says the
    queue cannot clear inside the request deadline — fast-fail admission
    (HTTP 503). `retry_after_s` estimates when the queue will have drained
    (None when no drain rate is known yet)."""

    def __init__(self, msg, retry_after_s=None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class RequestTimeout(RuntimeError):
    """Request aged past its deadline before a batch executed (HTTP 504).
    `retry_after_s` carries the batcher's current drain estimate when the
    dispatcher raised it (None from a bare result() wait)."""

    def __init__(self, msg, retry_after_s=None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class ShutdownError(RuntimeError):
    """Batcher was closed without draining this request."""


class ServingFuture:
    """One request's result slot. result() blocks the CALLER's thread; the
    dispatcher thread only ever sets."""

    def __init__(self):
        self._done = threading.Event()
        self._outputs = None
        self._error = None
        # which hot-swapped parameter version served this request (set by
        # the dispatcher before _set_result; None until then / on error)
        self.model_version = None

    def _set_result(self, outputs):
        self._outputs = outputs
        self._done.set()

    def _set_error(self, err):
        self._error = err
        self._done.set()

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise RequestTimeout("no result within %ss" % timeout)
        if self._error is not None:
            raise self._error
        return self._outputs


class _Request:
    __slots__ = ("feed", "rows", "future", "t_submit", "span")

    def __init__(self, feed, rows, span=NULL_SPAN):
        self.feed = feed
        self.rows = rows
        self.future = ServingFuture()
        self.t_submit = time.perf_counter()
        # the request's lifecycle span (queued -> admitted -> dispatched ->
        # completed events); NULL_SPAN when tracing is off — zero per-
        # request allocation on the disabled path
        self.span = span


class ContinuousBatcher:
    def __init__(self, engine, max_queue_rows=256, max_batch_delay_ms=5.0,
                 timeout_ms=2000.0):
        self.engine = engine
        self.max_queue_rows = int(max_queue_rows)
        self.max_batch_delay = float(max_batch_delay_ms) / 1e3
        self.timeout = float(timeout_ms) / 1e3
        self._cond = threading.Condition()
        self._queue = []  # FIFO of _Request
        self._queued_rows = 0
        self._alive = True
        self._draining = False
        # measured service rate (rows/s, EWMA over engine calls): admission's
        # can-this-finish-in-time estimate and the Retry-After hint's basis.
        # None until the first engine call completes — a cold batcher must
        # not shed load off a guess.
        self._drain_rate = None

        from ..observability import registry as _registry

        reg = _registry.default_registry()
        p = "serving/%s" % engine.name
        self._m_queue_ms = reg.histogram(
            p + "/queue_ms", "request wait in the batcher queue"
        )
        self._m_latency_ms = reg.histogram(
            p + "/latency_ms", "request submit->result latency"
        )
        self._m_depth = reg.gauge(p + "/queue_rows", "rows waiting in queue")
        self._m_inflight = reg.gauge(
            p + "/inflight_rows", "rows in the engine call in progress"
        )
        self._m_requests = reg.counter(
            p + "/requests", "requests by outcome label"
        )
        self._batches_dispatched = 0

        self._worker = threading.Thread(
            target=self._loop, name="batcher-%s" % engine.name, daemon=True
        )
        self._worker.start()

    # ---- client side ------------------------------------------------------
    def submit(self, feed, parent=None):
        """Enqueue one request (dict name->array or list zipped with the
        engine's feed_names); returns a ServingFuture. Raises QueueFullError
        when admission would exceed max_queue_rows, ShutdownError after
        close(). `parent` (a Span or trace header) parents the request's
        lifecycle span when tracing is on."""
        if isinstance(feed, (list, tuple)):
            feed = dict(zip(self.engine.feed_names, feed))
        feed = {k: np.asarray(v) for k, v in feed.items()}
        missing = [n for n in self.engine.feed_names if n not in feed]
        if missing:
            raise ValueError("missing feeds: %s" % missing)
        unknown = sorted(set(feed) - set(self.engine.feed_names))
        if unknown:
            raise ValueError(
                "unknown feeds: %s (model takes %s)"
                % (unknown, self.engine.feed_names)
            )
        rows = {np.shape(a)[0] if np.ndim(a) else 1 for a in feed.values()}
        if len(rows) != 1:
            raise ValueError(
                "feeds disagree on batch rows: %s"
                % {n: np.shape(a) for n, a in feed.items()}
            )
        n = rows.pop()
        if n < 1:
            raise ValueError("empty batch")
        if n > self.engine.max_batch:
            raise ValueError(
                "request rows %d exceed the largest bucket %d; split the "
                "request" % (n, self.engine.max_batch)
            )
        req = _Request(feed, n, span=_tracing.tracer().start_span(
            "serving.request", parent=parent, model=self.engine.name, rows=n,
        ))
        req.span.event("queued")
        with self._cond:
            if not self._alive or self._draining:
                self._m_requests.inc(outcome="shutdown")
                req.span.tag(outcome="shutdown").end("error")
                raise ShutdownError("batcher is shut down")
            if self._queued_rows + n > self.max_queue_rows:
                self._m_requests.inc(outcome="rejected")
                req.span.tag(outcome="rejected").end("error")
                raise QueueFullError(
                    "queue full (%d rows queued, limit %d)"
                    % (self._queued_rows, self.max_queue_rows),
                    retry_after_s=self._retry_after_locked(),
                )
            # deadline-aware admission: if the rows ahead of this request
            # will (by the measured drain rate) take longer than the request
            # timeout to clear, it is already doomed to a 504 — reject with
            # the honest wait estimate instead of accepting work we cannot
            # finish
            if self._drain_rate:
                est_wait = (self._queued_rows + n) / self._drain_rate
                if est_wait > self.timeout:
                    self._m_requests.inc(outcome="rejected")
                    req.span.tag(outcome="shed").end("error")
                    raise QueueFullError(
                        "queue drain estimate %.0f ms exceeds request "
                        "timeout %.0f ms (%d rows queued at %.0f rows/s)"
                        % (est_wait * 1e3, self.timeout * 1e3,
                           self._queued_rows, self._drain_rate),
                        retry_after_s=self._retry_after_locked(),
                    )
            self._queue.append(req)
            self._queued_rows += n
            self._m_depth.set(self._queued_rows)
            self._cond.notify_all()
        return req.future

    def run(self, feed, timeout=None):
        """Synchronous convenience: submit + result."""
        return self.submit(feed).result(
            self.timeout * 2 if timeout is None else timeout
        )

    def _retry_after_locked(self):
        """Seconds until the currently queued rows should have drained (the
        Retry-After hint); None before any drain rate is measured."""
        if not self._drain_rate:
            return None
        return max(self._queued_rows / self._drain_rate, 0.05)

    def retry_after_hint(self):
        """Thread-safe Retry-After estimate for the HTTP front end: how long
        a rejected/timed-out client should wait before retrying THIS
        replica. Clamped to [1, 30] whole seconds; 1 when unknown."""
        with self._cond:
            est = self._retry_after_locked()
        if est is None:
            return 1
        return int(min(max(-(-est // 1), 1), 30))

    # ---- dispatcher -------------------------------------------------------
    def _admit_locked(self):
        """Pop the next batch: FIFO requests up to the largest bucket's rows
        (requests are never split — each fits a bucket by submit's check)."""
        batch = []
        rows = 0
        while self._queue:
            nxt = self._queue[0]
            if batch and rows + nxt.rows > self.engine.max_batch:
                break
            batch.append(self._queue.pop(0))
            rows += nxt.rows
        self._queued_rows -= rows
        self._m_depth.set(self._queued_rows)
        return batch, rows

    def _loop(self):
        while True:
            with self._cond:
                # untimed: submit() and close() notify, so an empty queue
                # costs zero wakeups
                while self._alive and not self._queue:
                    self._cond.wait()
                if not self._queue:
                    if not self._alive:
                        return
                    continue
                # continuous admission: dispatch when the waiting rows can
                # fill the largest bucket OR the oldest request's batch-delay
                # deadline passes — never both idle and holding work
                deadline = self._queue[0].t_submit + self.max_batch_delay
                while (
                    self._alive
                    and self._queued_rows < self.engine.max_batch
                    and time.perf_counter() < deadline
                ):
                    self._cond.wait(
                        max(deadline - time.perf_counter(), 0.001)
                    )
                batch, rows = self._admit_locked()
            if batch:
                self._dispatch(batch, rows)

    def _dispatch(self, batch, rows):
        now = time.perf_counter()
        live = []
        for req in batch:
            if now - req.t_submit > self.timeout:
                self._m_requests.inc(outcome="timeout")
                req.span.tag(outcome="timeout").end("error")
                with self._cond:
                    hint = self._retry_after_locked()
                req.future._set_error(
                    RequestTimeout(
                        "queued %.0f ms > timeout %.0f ms"
                        % ((now - req.t_submit) * 1e3, self.timeout * 1e3),
                        retry_after_s=hint,
                    )
                )
            else:
                live.append(req)
        if not live:
            return
        for req in live:
            self._m_queue_ms.observe((now - req.t_submit) * 1e3)
            req.span.event(
                "admitted", queue_ms=round((now - req.t_submit) * 1e3, 3)
            )
        # requests may disagree on dynamic trailing dims (sequence lengths);
        # np.concatenate across mixed trailing shapes raises and would fail
        # the whole batch, so pack and execute one same-trailing-shape group
        # at a time (FIFO order preserved within and across groups)
        groups = {}
        for req in live:
            sig = tuple(
                tuple(np.shape(req.feed[n])[1:])
                for n in self.engine.feed_names
            )
            groups.setdefault(sig, []).append(req)
        self._m_inflight.set(sum(r.rows for r in live))
        try:
            for members in groups.values():
                self._run_group(members)
        finally:
            self._m_inflight.set(0)

    def _run_group(self, live):
        """Execute one same-trailing-shape group and answer its futures."""
        packed = {
            n: np.concatenate(
                [np.atleast_1d(np.asarray(r.feed[n])) for r in live]
            )
            for n in self.engine.feed_names
        }
        self._batches_dispatched += 1
        total_rows = sum(r.rows for r in live)
        # one batch span per engine call, parented on the first request of
        # the group (FIFO head); co-batched requests cross-link to it via a
        # "dispatched" event so the chrome-trace view shows the sharing
        bspan = live[0].span.child(
            "serving.batch", requests=len(live), rows=total_rows,
        )
        if bspan:
            for req in live[1:]:
                req.span.event("dispatched", batch_span=bspan.span_id)
        t_run = time.perf_counter()
        try:
            # activate: the engine opens its execute span under this parent
            # without the engine API taking a span argument
            with _tracing.tracer().activate(bspan):
                outs = self.engine.run(packed)
        except Exception as e:
            bspan.error(e).end()
            # a fresh exception per future: the same instance re-raised from
            # several caller threads would share (and mutate) one traceback
            for req in live:
                self._m_requests.inc(outcome="error")
                req.span.tag(outcome="error").end("error")
                err = RuntimeError("engine failed: %s" % (repr(e),))
                err.__cause__ = e
                req.future._set_error(err)
            return
        done = time.perf_counter()
        elapsed = max(done - t_run, 1e-6)
        rate = sum(r.rows for r in live) / elapsed
        with self._cond:
            self._drain_rate = (
                rate if self._drain_rate is None
                else 0.7 * self._drain_rate + 0.3 * rate
            )
        # which hot-swapped version the engine call above ran on: read on
        # THIS (dispatcher) thread, where the engine recorded it
        served = getattr(self.engine, "last_served_version", None)
        version = served() if callable(served) else None
        lo = 0
        total = sum(r.rows for r in live)
        for req in live:
            part = [
                o[lo:lo + req.rows]
                if np.ndim(o) and np.shape(o)[0] == total
                else o
                for o in outs
            ]
            lo += req.rows
            req.future.model_version = version
            req.future._set_result(part)
        # bookkeeping AFTER answering the futures: span ends (and the root
        # end's segment serialization) and metric updates stay off the
        # client's measured request latency
        bspan.tag(model_version=version).end()
        for req in live:
            self._m_latency_ms.observe((done - req.t_submit) * 1e3)
            self._m_requests.inc(outcome="ok")
            req.span.tag(outcome="ok", model_version=version).end()
        if self._batches_dispatched % 32 == 0:
            # periodic telemetry snapshot (flag-gated inside stepstats):
            # serving has no training step to ride, so the batcher is the
            # interval clock that lands serving/* metrics in the JSONL
            # shards tools/monitor.py reads
            from ..observability import stepstats as _stepstats

            _stepstats.maybe_flush()

    # ---- lifecycle --------------------------------------------------------
    def close(self, drain=True, timeout=30.0):
        """Stop admission; with drain, the worker finishes the queue before
        exiting, else queued requests fail with ShutdownError."""
        with self._cond:
            self._draining = True
            if not drain:
                for req in self._queue:
                    self._m_requests.inc(outcome="shutdown")
                    req.span.tag(outcome="shutdown").end("error")
                    req.future._set_error(ShutdownError("batcher closed"))
                self._queued_rows = 0
                self._queue = []
                self._m_depth.set(0)
            self._alive = False
            self._cond.notify_all()
        self._worker.join(timeout)
        return not self._worker.is_alive()

    def stats(self):
        with self._cond:
            return {
                "queued_rows": self._queued_rows,
                "batches_dispatched": self._batches_dispatched,
                "drain_rate_rows_per_s": self._drain_rate,
                "alive": self._alive,
            }
