"""Autoregressive generation serving: prefill/decode split over a paged
KV-cache pool, with token-level continuous batching.

The PR 6 ServingEngine serves *single-shot* programs — one compiled call
per request. A decode loop breaks that model twice: sequence lengths grow
every step (an unbounded retrace set), and a whole-sequence-per-request
loop wastes nearly all decode FLOPs on finished or padded positions. This
module is the Orca/vLLM answer, built from the same parts:

**GenerationEngine** AOT-compiles exactly TWO variant families through
`executor.aot_serve_lowering(return_state=True)`:

- *prefill* — one CHUNK program per pow2 bucket up to `prefill_chunk`
  (batch 1): the chunk's rows take positions `gen_start + [0, t)`, write
  their K/V into the slot's pages, and attend the pool causally-by-position
  through the same `paged_attention` path decode uses — so a long prompt
  prefills as a sequence of fixed-shape chunk calls (interleaved with
  decode steps by the scheduler: short requests keep streaming while a long
  prompt works through its chunks), and a chunk at start 0 covering the
  whole prompt IS whole-prompt prefill. One family, zero new retraces.
- *decode* — ONE fixed shape, `[max_slots]`: every live slot advances one
  token through `paged_attention` gather/scatter. Idle slots ride along
  pointing at the scratch page.

Admission consults a **PrefixCache** (kv_cache.py): requests whose prompt
shares full cached pages with an earlier prompt start prefill at the first
uncached position, with the shared (refcounted, immutable) pages filling
the leading block-table entries — the system-prompt workload prefills its
common prefix once.

Every variant builds through the persistent CompileCache with the decode
state avals and page geometry folded into the key, then AOT-compiles
(`.lower().compile()`) at warmup — the hot loop calls only precompiled
executables, so it can never retrace regardless of the prompt/output
length mix (`stats()["traces"]` is the proof the smoke stage asserts).
Prefill/decode wrappers are jitted with `donate_argnums=(2,)`: the pool
buffers update in place, verified by input-output aliasing in
tests/test_generation.py; single-shot serving stays donation-free.

**GenerationScheduler** extends ContinuousBatcher into a token-level
scheduler: the worker loop admits queued requests into free decode slots
*mid-batch* between steps (admission is host-only; prefill CHUNKS are
interleaved with decode under a queue-pressure policy — one chunk per step
when idle, draining every pending prompt when the queue is deep), runs one
decode step for all live slots, and retires slots on EOS/max-len,
releasing their pages for reuse.

Sampling (greedy / temperature / top-k) happens host-side on the fetched
logits with a per-request counter-based RNG stream seeded from the scope
seed — so a request's tokens are a pure function of (params, prompt,
sampling config, seed), independent of which slot it lands in or who
shares the batch. That determinism is the parity contract the tests pin.
"""

import hashlib
import json
import threading
import time

import numpy as np

from ..executor import Scope, aot_serve_lowering, scope_guard
from ..observability import tracing as _tracing
from ..observability.tracing import NULL_SPAN
from .batcher import (
    ContinuousBatcher,
    QueueFullError,
    RequestTimeout,
    ServingFuture,
    ShutdownError,
)
from .kv_cache import PagedKVPool, PoolExhausted, PrefixCache
from . import compile_cache as _cc

__all__ = [
    "GenerationEngine",
    "GenerationScheduler",
    "GenRequest",
    "GenResult",
]


def _pow2_buckets(lo, hi):
    out = []
    b = max(2, lo)
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return tuple(sorted(set(out)))


def program_fingerprint(program, scope, extra=None):
    """Content hash of a program built in memory (no model_dir to
    fingerprint): op list (type/slots/attrs) + the scope avals of every
    persistable the ops touch. Mirrors io.inference_model_fingerprint's
    role for the compile-cache key."""

    def _jsonable(v):
        if isinstance(v, np.ndarray):
            return ["ndarray", str(v.dtype), list(v.shape),
                    hashlib.sha256(np.ascontiguousarray(v).tobytes()).hexdigest()]
        if isinstance(v, (list, tuple)):
            return [_jsonable(x) for x in v]
        if isinstance(v, (bool, int, float, str)) or v is None:
            return v
        return repr(v)

    ops = []
    touched = set()
    for op in program.global_block().ops:
        ops.append([
            op.type,
            sorted((k, list(v)) for k, v in op.inputs.items()),
            sorted((k, list(v)) for k, v in op.outputs.items()),
            sorted((k, _jsonable(v)) for k, v in op.attrs.items()),
        ])
        touched.update(op.input_arg_names)
    avals = sorted(
        (n, list(np.shape(scope.vars[n])), str(np.asarray(scope.vars[n]).dtype)
         if not hasattr(scope.vars[n], "dtype") else str(scope.vars[n].dtype))
        for n in touched
        if n in scope.vars
    )
    doc = {"ops": ops, "avals": avals, "extra": extra}
    return hashlib.sha256(json.dumps(doc, sort_keys=True).encode()).hexdigest()


class GenRequest:
    """One generation request (validated by scheduler/engine entry points).
    temperature None/0 means greedy; top_k limits sampling to the k most
    likely tokens; seed pins the request's sample stream (defaults to a
    per-engine counter so concurrent requests draw independent streams)."""

    __slots__ = ("prompt", "max_new_tokens", "eos_id", "temperature",
                 "top_k", "seed")

    def __init__(self, prompt, max_new_tokens=16, eos_id=None,
                 temperature=None, top_k=None, seed=None):
        self.prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.temperature = None if not temperature else float(temperature)
        self.top_k = None if not top_k else int(top_k)
        self.seed = None if seed is None else int(seed)
        if not self.prompt:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")


class GenResult:
    __slots__ = ("tokens", "finish_reason", "prompt_len")

    def __init__(self, tokens, finish_reason, prompt_len):
        self.tokens = tokens
        self.finish_reason = finish_reason
        self.prompt_len = prompt_len


class _SlotRun:
    """Engine-side state of one admitted request occupying a decode slot."""

    __slots__ = ("req", "slot", "table", "tokens", "next_pos", "rng",
                 "pf_pos", "done", "finish_reason", "future", "t_submit",
                 "t_first", "span")

    def __init__(self, req, slot, table, rng):
        self.req = req
        self.slot = slot
        self.table = table
        self.tokens = []
        self.next_pos = len(req.prompt)
        self.rng = rng
        self.pf_pos = 0  # next prompt position to prefill (past prefix hits)
        self.done = False
        self.finish_reason = None
        self.future = None
        self.t_submit = None
        self.t_first = None
        self.span = NULL_SPAN

    def result(self):
        return GenResult(list(self.tokens), self.finish_reason,
                         len(self.req.prompt))


class _Variant:
    __slots__ = ("fn", "ro", "mut_names", "feed_names", "avals")

    def __init__(self, fn, ro, mut_names, feed_names, avals):
        self.fn = fn
        self.ro = ro
        self.mut_names = mut_names
        self.feed_names = feed_names
        self.avals = avals


class GenerationEngine:
    """AOT prefill/decode engine for one decoder model over one paged pool.

    `model` implements the GPTDecoder protocol: build_prefill / build_decode
    / kv_pool_names / ensure_params / d_model / max_context / eos_id (see
    models/gpt_decoder.py — the hook point for other decode-loop models).
    """

    def __init__(self, model, name="generation", scope=None, place=None,
                 max_slots=4, page_size=8, pool_pages=None, max_context=None,
                 prefill_buckets=None, prefill_chunk=None, prefix_cache=True,
                 cache_dir=None):
        import jax.numpy as jnp

        self.model = model
        self.name = name
        self.max_context = int(max_context or model.max_context)
        if self.max_context > model.max_context:
            raise ValueError(
                "max_context %d exceeds the model's position table %d"
                % (self.max_context, model.max_context)
            )
        self.page_size = int(page_size)
        self.max_slots = int(max_slots)
        if self.max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        self.max_pages = -(-self.max_context // self.page_size)
        if pool_pages is None:
            # full reservation capacity for every slot, plus scratch page 0
            pool_pages = self.max_slots * self.max_pages + 1
        self.pool_pages = int(pool_pages)
        self.pool = PagedKVPool(
            self.pool_pages, self.page_size, self.max_slots, self.max_pages,
            storage_dtype=getattr(model, "kv_dtype", "float32"),
        )
        # prefill compiles one chunk program per pow2 bucket up to
        # prefill_chunk; prompts longer than the largest bucket run as a
        # sequence of chunk calls, so buckets stop growing with the context
        # window (default cap 32 rows: past that a chunk's FLOPs amortize
        # its launch and chunking wins back scheduler interleaving)
        chunk = int(prefill_chunk) if prefill_chunk else min(self.max_context, 32)
        self.prefill_buckets = tuple(sorted(set(
            int(b)
            for b in (
                prefill_buckets
                or _pow2_buckets(2, min(self.max_context, chunk))
            )
        )))
        if self.prefill_buckets[-1] > self.max_context:
            raise ValueError("prefill bucket > max_context")
        self.prefill_chunk = self.prefill_buckets[-1]
        # longest admissible prompt must leave room for >= 1 generated
        # token; chunking covers any prompt up to the context bound
        self.max_prompt_len = self.max_context - 1
        self.prefix_cache = PrefixCache(self.pool) if prefix_cache else None

        self.scope = scope or Scope()
        model.ensure_params(self.scope, place)
        pool_rows = self.pool_pages * self.page_size
        # int8 pool mode (model.kv_dtype == "int8"): level pools are int8
        # and each gains a [pool_rows] f32 per-row scale pool sibling
        # (model.kv_scale_names) — ~1/4 the f32 bytes per cached token
        self.kv_dtype = getattr(model, "kv_dtype", "float32")
        self._state = {}
        for pair in model.kv_pool_names():
            for n in pair:
                arr = jnp.zeros(
                    (pool_rows, model.d_model), jnp.dtype(self.kv_dtype)
                )
                self.scope.vars[n] = arr
                self._state[n] = arr
        for pair in getattr(model, "kv_scale_names", lambda: [])():
            for n in pair:
                # scale 1.0 everywhere: scratch-page reads dequantize to
                # in-range garbage instead of inf/nan before being masked
                arr = jnp.ones((pool_rows,), jnp.float32)
                self.scope.vars[n] = arr
                self._state[n] = arr
        self.kv_state_bytes = sum(
            int(np.prod(a.shape)) * jnp.dtype(a.dtype).itemsize
            for a in self._state.values()
        )
        self.pool.row_bytes = self.kv_state_bytes // pool_rows

        if cache_dir is None:
            from .. import flags as _flags

            cache_dir = _flags.get_flags("serving_cache_dir")["serving_cache_dir"]
        self.cache = _cc.CompileCache(cache_dir) if cache_dir else None

        # persistent decode-step feed buffers: the hot loop allocates
        # nothing. Rows are slot-owned — armed when a slot's prefill
        # completes, refreshed for the runs in each step, zeroed (back to
        # the scratch page) at finish(). A mid-prefill slot therefore keeps
        # writing scratch during interleaved decode steps (its table row is
        # still zeros), and a live slot skipped by one step merely rewrites
        # its last K/V row with identical bits.
        self._dec_feeds = {
            "dec_tokens": np.zeros((self.max_slots, 1), np.int64),
            "dec_positions": np.zeros((self.max_slots, 1), np.int64),
            "dec_block_table": np.zeros(
                (self.max_slots, self.max_pages), np.int32
            ),
        }

        self._variants = {}
        self._build_lock = threading.Lock()
        self._sample_counter = 0
        self.traces = 0
        self.cache_hits = 0
        self.tokens_generated = 0

        from ..observability import registry as _registry

        reg = _registry.default_registry()
        p = "serving/%s" % self.name
        self._m_tokens = reg.counter(p + "/gen_tokens", "tokens generated")
        self._m_prefills = reg.counter(p + "/gen_prefills", "prompts prefilled")
        self._m_steps = reg.counter(p + "/gen_steps", "decode steps executed")
        self._m_traces = reg.counter(
            p + "/traces", "generation variants traced (compile-cache misses)"
        )
        self._m_slots = reg.gauge(p + "/gen_slots_live", "live decode slots")
        self._m_slots_total = reg.gauge(
            p + "/gen_slots_total", "decode slot capacity of the KV pool"
        )
        self._m_slots_total.set(float(self.max_slots))
        self._m_occ = reg.gauge(
            p + "/gen_slot_occupancy", "live slots / max_slots"
        )
        self._m_pages = reg.gauge(
            p + "/gen_kv_pages_used", "KV pool pages in use"
        )
        self._m_step_ms = reg.histogram(
            p + "/gen_step_ms", "one decode step, wall ms"
        )
        self._m_prefill_ms = reg.histogram(
            p + "/gen_prefill_ms", "one prefill chunk call, wall ms"
        )
        self._m_chunks = reg.counter(
            p + "/gen_prefill_chunks", "prefill chunk calls executed"
        )
        self._m_prefix_hit = reg.gauge(
            p + "/gen_prefix_hit_rate",
            "prefix-cache page hit rate (pages hit / pages eligible)",
        )
        self._m_pages_shared = reg.gauge(
            p + "/gen_pages_shared", "KV pool pages held by > 1 reference"
        )
        self._m_paged_flash = reg.gauge(
            p + "/gen_paged_flash_dispatches",
            "paged_attention lowerings that chose the Pallas kernel",
        )
        self._m_kv_bytes = reg.gauge(
            p + "/gen_kv_bytes",
            "resident KV state bytes (level pools + scale pools)",
        )
        self._m_kv_bytes.set(float(self.kv_state_bytes))
        # precision label for the monitor's serve rows: 0 = fp32 pools,
        # 1 = int8 pools (tools/monitor.py maps it back to a string)
        self._m_precision = reg.gauge(
            p + "/precision",
            "KV storage precision (0 = fp32, 1 = int8)",
        )
        self._m_precision.set(1.0 if self.kv_dtype == "int8" else 0.0)
        # hot-swap state (docs/online.md): each _Variant holds its own ro
        # dict; set_params swaps them (and the scope) under _swap_lock.
        self.model_version = 0
        self.version_stamp = {}
        self._swap_lock = threading.Lock()
        self._m_version = reg.gauge(
            p + "/model_version", "live hot-swapped parameter version"
        )
        self._m_swaps = reg.counter(
            p + "/hot_swaps", "set_params hot swaps applied"
        )
        self._m_version.set(0.0)

    # ---- geometry / cache keys --------------------------------------------
    def geometry(self):
        return {
            "page_size": self.page_size,
            "pool_pages": self.pool_pages,
            "max_slots": self.max_slots,
            "max_pages": self.max_pages,
            "max_context": self.max_context,
            "kv_dtype": self.kv_dtype,
        }

    def _canon_dtype(self, dtype):
        import jax.numpy as jnp

        return jnp.asarray(np.zeros((), np.dtype(dtype))).dtype

    # ---- variants ---------------------------------------------------------
    def _variant(self, kind):
        """Compiled stateful callable for 'decode' or 'prefill:<bucket>',
        building through the persistent cache on first sight."""
        v = self._variants.get(kind)
        if v is not None:
            return v
        with self._build_lock:
            v = self._variants.get(kind)
            if v is not None:
                return v
            pool_rows = self.pool_pages * self.page_size
            if kind == "decode":
                main, _, feeds, fetches = self.model.build_decode(
                    self.max_slots, self.page_size, self.max_pages, pool_rows
                )
            elif kind.startswith("prefill:"):
                t = int(kind.split(":", 1)[1])
                main, _, feeds, fetches = self.model.build_prefill(
                    t, self.page_size, self.max_pages, pool_rows
                )
            else:
                raise ValueError("unknown variant kind %r" % kind)
            v = self._build_variant(kind, main, feeds, fetches)
            self._variants[kind] = v
            return v

    def _build_variant(self, kind, main, feed_names, fetch_names):
        import jax
        from jax import export as jax_export

        from ..analysis import maybe_static_verify

        maybe_static_verify(
            main, feed_names, fetch_names, scope=self.scope,
            mode="serving", where="generation:%s" % kind,
        )
        with scope_guard(self.scope):
            serve, ro, mut = aot_serve_lowering(
                main, feed_names, fetch_names, self.scope, return_state=True
            )
        block = main.global_block()
        avals = {}
        for n in feed_names:
            var = block.vars[n]
            avals[n] = jax.ShapeDtypeStruct(
                tuple(int(d) for d in var.shape), self._canon_dtype(var.dtype)
            )

        def build():
            self.traces += 1
            self._m_traces.inc()
            return jax_export.export(jax.jit(serve))(avals, ro, mut)

        if self.cache is not None:
            fp = program_fingerprint(main, self.scope, extra=kind)
            ck = _cc.variant_key(
                fp,
                {n: (s.shape, s.dtype) for n, s in avals.items()},
                fetch_names,
                state_avals={
                    n: (tuple(a.shape), str(a.dtype)) for n, a in mut.items()
                },
                geometry=self.geometry(),
            )
            exported, hit = self.cache.get_or_build(
                ck, build,
                meta={
                    "model": self.name,
                    "variant": kind,
                    "geometry": self.geometry(),
                    "feeds": {
                        n: [list(s.shape), str(s.dtype)]
                        for n, s in avals.items()
                    },
                    "fetches": list(fetch_names),
                },
            )
            if hit:
                self.cache_hits += 1
        else:
            exported = build()

        # decode-state donation: the KV pool buffers (arg 2) are consumed
        # each call and replaced by the returned new state, so XLA may alias
        # them in place — the aliasing test asserts this on the executable
        fn = jax.jit(
            lambda feeds, ro_, mut_, _call=exported.call: _call(feeds, ro_, mut_),
            donate_argnums=(2,),
        ).lower(avals, ro, {n: self._state[n] for n in mut}).compile()
        return _Variant(fn, ro, sorted(mut), list(feed_names), avals)

    def warmup(self):
        """Precompile the decode step and every prefill bucket. Returns the
        variant count; after this the hot loop never traces."""
        self._variant("decode")
        for b in self.prefill_buckets:
            self._variant("prefill:%d" % b)
        return len(self._variants)

    # ---- hot swap ---------------------------------------------------------
    def set_params(self, updates, version=None, stamp=None):
        """Hot-swap parameter values without recompiling or dropping
        requests. KV-pool state names (self._state) never swap — a publisher
        shipping them by accident must not clobber live caches. Each
        variant's ro dict is replaced wholesale (one attribute store;
        _call reads variant.ro exactly once per step, so an in-flight decode
        step finishes coherently on the old params) and the scope is updated
        so variants built later capture the new values. Returns the number
        of arrays applied."""
        import jax.numpy as jnp

        with self._swap_lock:
            conv = {}
            for name, val in updates.items():
                if name in self._state:
                    continue
                cur = self.scope.vars.get(name)
                if cur is None:
                    continue
                arr = jnp.asarray(np.asarray(val), dtype=np.asarray(cur).dtype)
                if tuple(arr.shape) != tuple(np.shape(cur)):
                    raise ValueError(
                        "set_params(%r): shape %s != live %s — geometry "
                        "changes need a model reload, not a hot swap"
                        % (name, tuple(arr.shape), tuple(np.shape(cur)))
                    )
                conv[name] = arr
            for v in self._variants.values():
                if any(n in v.ro for n in conv):
                    nro = dict(v.ro)
                    nro.update({n: a for n, a in conv.items() if n in v.ro})
                    v.ro = nro
            self.scope.vars.update(conv)
            self.model_version = (
                int(version) if version is not None else self.model_version + 1
            )
            self.version_stamp = dict(stamp or {})
            ver = self.model_version
        self._m_version.set(float(ver))
        self._m_swaps.inc()
        return len(conv)

    def _call(self, variant, np_feeds):
        feeds = {}
        for n in variant.feed_names:
            s = variant.avals[n]
            feeds[n] = np.ascontiguousarray(np_feeds[n], dtype=s.dtype)
        mut_in = {n: self._state[n] for n in variant.mut_names}
        fetches, new_mut = variant.fn(feeds, variant.ro, mut_in)
        self._state.update(new_mut)
        return fetches

    # ---- admission / prefill / decode / retire -----------------------------
    def prefill_bucket(self, n):
        """Smallest chunk bucket covering `n` remaining prompt tokens, or
        the largest (= prefill_chunk) when the remainder spans chunks."""
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.prefill_buckets[-1]

    def can_admit(self, req):
        """Whether a free slot + pages exist for this request right now."""
        budget = len(req.prompt) + self._max_new(req)
        return self.pool.can_admit(budget)

    def _max_new(self, req):
        # a request can never run past the context window
        return min(req.max_new_tokens, self.max_context - len(req.prompt))

    def free_slots(self):
        return self.max_slots - self.pool.stats()["slots_in_use"]

    def admit(self, req):
        """Reserve a slot + pages for one request — host work only, no
        device call. Prefix-cache hits fill the leading block-table entries
        and skip those pages' prefill; the caller then advances the prompt
        with prefill_step() until it returns True. Raises PoolExhausted
        when no capacity (after trying to evict cold cached pages),
        ValueError on an inadmissible request."""
        L = len(req.prompt)
        if L > self.max_prompt_len:
            raise ValueError(
                "prompt of %d tokens exceeds max_prompt_len %d"
                % (L, self.max_prompt_len)
            )
        max_new = self._max_new(req)
        shared = []
        if self.prefix_cache is not None:
            shared = self.prefix_cache.lookup(req.prompt)  # pages pinned
        try:
            try:
                slot, table = self.pool.acquire(L + max_new, shared)
            except PoolExhausted:
                need = self.pool.pages_for(L + max_new) - len(shared)
                if self.prefix_cache is None or not self.prefix_cache.evict_for(need):
                    raise
                slot, table = self.pool.acquire(L + max_new, shared)
        finally:
            if shared:
                self.pool.unpin_pages(shared)  # slot ref (or nothing) holds now
        seed = req.seed
        if seed is None:
            seed = (self.scope._seed, self._sample_counter)
            self._sample_counter += 1
        run = _SlotRun(req, slot, table, np.random.default_rng(seed))
        run.pf_pos = len(shared) * self.page_size
        self._set_pool_gauges()
        return run

    def prefill_step(self, run):
        """Advance one admitted run by ONE prefill chunk (one device call).
        Returns True when the prompt is fully prefilled — the first token
        has then been sampled and the run is decodable (or already done)."""
        req = run.req
        L = len(req.prompt)
        start = run.pf_pos
        remaining = L - start
        if remaining <= 0:
            raise ValueError("prefill_step on a fully prefilled run")
        c = self.prefill_bucket(remaining)
        n_real = min(c, remaining)
        tokens = np.zeros((1, c, 1), np.int64)
        tokens[0, :n_real, 0] = req.prompt[start:start + n_real]
        span = _tracing.current()
        if span:
            span = span.child(
                "engine.prefill", chunk=c, start=start, rows=n_real,
                kv_dtype=self.kv_dtype, model_version=self.model_version,
            )
        t0 = time.perf_counter()
        try:
            (logits,) = self._call(
                self._variant("prefill:%d" % c),
                {
                    "gen_tokens": tokens,
                    "gen_start": np.array([start], np.int64),
                    "gen_last": np.array([n_real - 1], np.int64),
                    "gen_pages": run.table,
                },
            )
        except Exception as e:
            span.error(e).end()
            raise
        prefill_ms = (time.perf_counter() - t0) * 1e3
        span.tag(device_ms=round(prefill_ms, 3)).end()
        self._m_prefill_ms.observe(prefill_ms)
        self._m_chunks.inc()
        run.pf_pos = start + n_real
        if run.pf_pos < L:
            return False
        self._m_prefills.inc()
        # parity surface: tests assert these rows bit-stable under
        # batching/admission/chunking changes (docs/serving.md contract)
        self.last_prefill_logits = np.asarray(logits)[0]
        self._append_token(run, self.last_prefill_logits, self._max_new(req))
        if self.prefix_cache is not None:
            self.prefix_cache.insert(req.prompt, run.table)
        # arm the slot's persistent decode-feed rows only now: until the
        # last chunk lands, an interleaved decode step must keep this slot
        # on the scratch page, never writing a page a chunk already filled
        self._dec_feeds["dec_block_table"][run.slot] = run.table
        self._dec_feeds["dec_tokens"][run.slot, 0] = run.tokens[-1]
        self._dec_feeds["dec_positions"][run.slot, 0] = run.next_pos
        self._set_pool_gauges()
        return True

    def start(self, req):
        """Admit one request and run its whole prefill back-to-back,
        sampling the first token. Returns a _SlotRun (possibly already
        done). Raises PoolExhausted when no capacity, ValueError on an
        inadmissible request. The scheduler instead interleaves
        prefill_step() chunks with decode steps."""
        run = self.admit(req)
        try:
            while not self.prefill_step(run):
                pass
            return run
        except Exception:
            self.finish(run)
            raise

    def decode_step(self, runs):
        """One fixed-shape decode step advancing every run in `runs` by one
        token (all must be live). Finished runs are NOT auto-released — the
        caller retires them via finish()."""
        if not runs:
            return
        feeds = self._dec_feeds
        tokens, positions = feeds["dec_tokens"], feeds["dec_positions"]
        for run in runs:
            if run.done:
                raise ValueError("decode_step on a finished run")
            tokens[run.slot, 0] = run.tokens[-1]
            positions[run.slot, 0] = run.next_pos
        span = _tracing.current()
        if span:
            span = span.child(
                "engine.decode", slots=len(runs),
                kv_dtype=self.kv_dtype, model_version=self.model_version,
            )
        t0 = time.perf_counter()
        try:
            (logits,) = self._call(self._variant("decode"), feeds)
        except Exception as e:
            span.error(e).end()
            raise
        logits = np.asarray(logits)
        self.last_logits = logits  # parity surface, see prefill_step()
        step_ms = (time.perf_counter() - t0) * 1e3
        span.tag(device_ms=round(step_ms, 3)).end()
        self._m_step_ms.observe(step_ms)
        self._m_steps.inc()
        for run in runs:
            run.next_pos += 1
            self._append_token(run, logits[run.slot], self._max_new(run.req))

    def finish(self, run):
        """Retire a run's slot: pages return to the pool for reuse (cached
        prefix pages stay alive under the trie's reference) and the slot's
        persistent decode-feed rows drop back to the scratch page so the
        next tenant can't inherit a stale table."""
        self.pool.release(run.slot)
        self._dec_feeds["dec_block_table"][run.slot] = 0
        self._dec_feeds["dec_tokens"][run.slot] = 0
        self._dec_feeds["dec_positions"][run.slot] = 0
        self._set_pool_gauges()

    def _append_token(self, run, logits_row, max_new):
        tok = self._sample(logits_row, run.req, run.rng)
        run.tokens.append(tok)
        self.tokens_generated += 1
        self._m_tokens.inc()
        eos = run.req.eos_id
        if eos is None:
            eos = getattr(self.model, "eos_id", None)
        if eos is not None and tok == eos:
            run.done, run.finish_reason = True, "eos"
        elif len(run.tokens) >= max_new:
            run.done, run.finish_reason = True, "length"

    def _sample(self, logits, req, rng):
        if not req.temperature:
            # greedy stays on the raw fetch dtype: the float64 upcast can't
            # change the argmax winner and costs real time per decode step
            return int(np.asarray(logits).argmax())
        logits = np.asarray(logits, np.float64)
        z = logits / req.temperature
        if req.top_k and req.top_k < z.size:
            kth = np.partition(z, -req.top_k)[-req.top_k]
            z = np.where(z < kth, -np.inf, z)
        z = z - z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(rng.choice(z.size, p=p))

    def _set_pool_gauges(self):
        from ..ops import pallas_kernels as _pk

        st = self.pool.stats()
        self._m_slots.set(st["slots_in_use"])
        self._m_occ.set(st["slot_occupancy"])
        self._m_pages.set(st["pages_in_use"])
        self._m_pages_shared.set(st["pages_shared"])
        self._m_paged_flash.set(_pk.KERNEL_DISPATCHES.get("paged_flash", 0))
        if self.prefix_cache is not None:
            self._m_prefix_hit.set(self.prefix_cache.stats()["hit_rate"])

    # ---- convenience / stats ----------------------------------------------
    def generate(self, prompt, max_new_tokens=16, **kw):
        """Serial one-request decode (no scheduler): admit, step to
        completion, retire. The whole-sequence tests' reference path."""
        req = GenRequest(prompt, max_new_tokens=max_new_tokens, **kw)
        run = self.start(req)
        try:
            while not run.done:
                self.decode_step([run])
        finally:
            self.finish(run)
        return run.result()

    def stats(self):
        from ..ops import pallas_kernels as _pk

        out = {
            "variants": len(self._variants),
            "traces": self.traces,
            "cache_hits": self.cache_hits,
            "model_version": self.model_version,
            "tokens_generated": self.tokens_generated,
            "prefill_buckets": list(self.prefill_buckets),
            "prefill_chunk": self.prefill_chunk,
            "prefill_chunks": self._m_chunks.value(),
            "geometry": self.geometry(),
            "pool": self.pool.stats(),
            # lowering-time kernel choices (counts are per trace, not per
            # call): the smoke/bench stages assert paged_flash shows up here
            # when the flag forces it
            "kernel_dispatches": {
                k: v
                for k, v in _pk.KERNEL_DISPATCHES.items()
                if k in ("paged_flash", "paged_flash_int8", "gemm_dbuf",
                         "gemm_epilogue", "gemm_int8", "gemm_fp8")
            },
            "kv": {
                "dtype": self.kv_dtype,
                "resident_bytes": self.kv_state_bytes,
            },
        }
        if self.prefix_cache is not None:
            out["prefix_cache"] = self.prefix_cache.stats()
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        return out


class _Pending:
    __slots__ = ("req", "future", "t_submit", "span")

    def __init__(self, req, span=NULL_SPAN):
        self.req = req
        self.future = ServingFuture()
        self.t_submit = time.perf_counter()
        self.span = span


class GenerationScheduler(ContinuousBatcher):
    """Token-level continuous scheduler over a GenerationEngine.

    Reuses the ContinuousBatcher shell (bounded queue, condition variable,
    worker thread, outcome metrics, drain/shutdown) but replaces the batch
    dispatcher with a step loop:

      1. admit queued requests into free slots — normally at most
         `prefill_per_step` prefills per step (prefill latency rides on top
         of every live slot's token latency), escalating to ALL free slots
         when the queue is deeper than `pressure_queue` (throughput beats
         tail latency once a backlog forms);
      2. run ONE fixed-shape decode step for every live slot;
      3. retire finished slots (EOS / max-new / context bound), releasing
         their pages, and resolve their futures with GenResult.

    The queue is bounded in REQUESTS (one row each — a generation request's
    device debt is a slot, not its prompt length).
    """

    def __init__(self, engine, max_queue_requests=64, timeout_ms=30000.0,
                 prefill_per_step=1, pressure_queue=4):
        self.prefill_per_step = max(1, int(prefill_per_step))
        self.pressure_queue = int(pressure_queue)
        self._runs = {}  # slot -> _SlotRun
        self._prefills = []  # admitted runs still working through chunks
        self._drain_flag = True
        from ..observability import registry as _registry

        reg = _registry.default_registry()
        p = "serving/%s" % engine.name
        self._m_ttft_ms = reg.histogram(
            p + "/gen_ttft_ms", "submit -> first token, wall ms"
        )
        self._m_token_ms = reg.histogram(
            p + "/gen_token_ms", "per-token latency (decode step wall)"
        )
        super().__init__(
            engine,
            max_queue_rows=max_queue_requests,
            max_batch_delay_ms=0.0,
            timeout_ms=timeout_ms,
        )

    # ---- client side ------------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, eos_id=None, temperature=None,
               top_k=None, seed=None, parent=None):
        """Enqueue one generation request; returns a ServingFuture resolving
        to a GenResult. `parent` optionally links the request's trace span
        under a caller span (or an X-Fleet-Trace header value)."""
        req = GenRequest(
            prompt, max_new_tokens=max_new_tokens, eos_id=eos_id,
            temperature=temperature, top_k=top_k, seed=seed,
        )
        if len(req.prompt) > self.engine.max_prompt_len:
            raise ValueError(
                "prompt of %d tokens exceeds max_prompt_len %d"
                % (len(req.prompt), self.engine.max_prompt_len)
            )
        pending = _Pending(req, span=_tracing.tracer().start_span(
            "serving.genrequest", parent=parent, model=self.engine.name,
            prompt_len=len(req.prompt), max_new=req.max_new_tokens,
        ))
        with self._cond:
            if not self._alive or self._draining:
                self._m_requests.inc(outcome="shutdown")
                pending.span.tag(outcome="shutdown").end("error")
                raise ShutdownError("scheduler is shut down")
            if self._queued_rows + 1 > self.max_queue_rows:
                self._m_requests.inc(outcome="rejected")
                pending.span.tag(outcome="rejected").end("error")
                raise QueueFullError(
                    "queue full (%d requests queued, limit %d)"
                    % (self._queued_rows, self.max_queue_rows)
                )
            pending.span.event("queued", depth=self._queued_rows)
            self._queue.append(pending)
            self._queued_rows += 1
            self._m_depth.set(self._queued_rows)
            self._cond.notify_all()
        return pending.future

    def run(self, prompt, timeout=None, **kw):
        return self.submit(prompt, **kw).result(
            self.timeout * 2 if timeout is None else timeout
        )

    # ---- step loop --------------------------------------------------------
    def _loop(self):
        while True:
            with self._cond:
                while (self._alive and not self._queue and not self._runs
                       and not self._prefills):
                    self._cond.wait()
                if not self._alive:
                    if not self._drain_flag:
                        self._fail_runs_locked()
                        return
                    if (not self._queue and not self._runs
                            and not self._prefills):
                        return
                admits = self._admit_requests_locked()
            self._step(admits)

    def _admit_requests_locked(self):
        """Pop queued requests that fit free capacity right now. Admission
        is host-only (slot + page reservation); the chunk budget in _step
        governs device-side prefill pacing, so an in-flight chunked
        prefill never blocks admitting the next request — a short prompt
        admitted behind a long one overtakes it in the
        shortest-remaining-first chunk order. Pages held only by the
        prefix cache count as free — admit() evicts them on demand."""
        budget = self.prefill_per_step
        if len(self._queue) >= self.pressure_queue:
            budget = self.engine.max_slots
        pool = self.engine.pool
        st = pool.stats()
        slots_left = st["slots_total"] - st["slots_in_use"]
        pages_left = st["pages_total"] - st["pages_in_use"]
        if self.engine.prefix_cache is not None:
            pages_left += self.engine.prefix_cache.reclaimable()
        admits = []
        while self._queue and len(admits) < min(budget, slots_left):
            nxt = self._queue[0]
            if now_expired(nxt, self.timeout):
                self._queue.pop(0)
                self._queued_rows -= 1
                self._m_requests.inc(outcome="timeout")
                nxt.span.tag(outcome="timeout").end("error")
                nxt.future._set_error(RequestTimeout(
                    "queued %.0f ms > timeout %.0f ms"
                    % ((time.perf_counter() - nxt.t_submit) * 1e3,
                       self.timeout * 1e3)
                ))
                continue
            # reservation-aware: each admit here WILL acquire pages before
            # the pool state refreshes, so account for the whole batch
            need = pool.pages_for(
                len(nxt.req.prompt) + self.engine._max_new(nxt.req)
            )
            if need > pages_left:
                break
            pages_left -= need
            admits.append(self._queue.pop(0))
            self._queued_rows -= 1
        self._m_depth.set(self._queued_rows)
        return admits

    def _step(self, admits):
        eng = self.engine
        for pending in admits:
            queue_ms = (time.perf_counter() - pending.t_submit) * 1e3
            self._m_queue_ms.observe(queue_ms)
            try:
                run = eng.admit(pending.req)
            except PoolExhausted as e:
                # capacity raced away (shouldn't happen single-threaded,
                # but never drop a request on the floor)
                self._m_requests.inc(outcome="error")
                pending.span.error(e).tag(outcome="error").end()
                pending.future._set_error(e)
                continue
            except Exception as e:
                self._m_requests.inc(outcome="error")
                pending.span.error(e).tag(outcome="error").end()
                err = RuntimeError("admit failed: %s" % (repr(e),))
                err.__cause__ = e
                pending.future._set_error(err)
                continue
            run.future = pending.future
            run.t_submit = pending.t_submit
            run.span = pending.span
            run.span.tag(
                prefix_hit=run.pf_pos > 0, prefix_tokens=run.pf_pos,
                kv_dtype=eng.kv_dtype,
            ).event("admitted", slot=run.slot, queue_ms=round(queue_ms, 3))
            self._prefills.append(run)

        # advance prefill chunk-by-chunk: normally one chunk per step (its
        # latency rides on every live slot's token), draining every pending
        # prompt when the queue is deep OR when no slot is decoding (then
        # there is nobody to stall). Chunks go shortest-remaining-first, so
        # a short prompt admitted behind a half-prefilled long one
        # overtakes it and samples its first token next step — the
        # queue-pressure escalation bounds how long the long prompt can be
        # overtaken. TTFT starts at the chunk that samples the first token.
        if self._prefills:
            n_chunks = self.prefill_per_step
            if not self._runs or self._queued_rows >= self.pressure_queue:
                n_chunks = len(self._prefills)
            order = sorted(self._prefills,
                           key=lambda r: len(r.req.prompt) - r.pf_pos)
            for run in order[:n_chunks]:
                try:
                    with _tracing.tracer().activate(run.span):
                        finished = eng.prefill_step(run)
                except Exception as e:
                    self._prefills.remove(run)
                    self._m_requests.inc(outcome="error")
                    run.span.error(e).tag(outcome="error").end()
                    err = RuntimeError("prefill failed: %s" % (repr(e),))
                    err.__cause__ = e
                    run.future._set_error(err)
                    eng.finish(run)
                    continue
                if finished:
                    self._prefills.remove(run)
                    run.t_first = time.perf_counter()
                    ttft_ms = (run.t_first - run.t_submit) * 1e3
                    self._m_ttft_ms.observe(ttft_ms)
                    run.span.event("first_token", ttft_ms=round(ttft_ms, 3))
                    if run.done:
                        self._retire(run)
                    else:
                        self._runs[run.slot] = run

        live = list(self._runs.values())
        if live:
            t0 = time.perf_counter()
            try:
                # the decode step is shared across slots; its engine.decode
                # span hangs off one representative request's trace
                with _tracing.tracer().activate(live[0].span):
                    eng.decode_step(live)
            except Exception as e:
                for run in live:
                    self._m_requests.inc(outcome="error")
                    run.span.error(e).tag(outcome="error").end()
                    err = RuntimeError("decode failed: %s" % (repr(e),))
                    err.__cause__ = e
                    run.future._set_error(err)
                    eng.finish(run)
                self._runs.clear()
                return
            step_ms = (time.perf_counter() - t0) * 1e3
            for run in live:
                self._m_token_ms.observe(step_ms)
                if run.done:
                    del self._runs[run.slot]
                    self._retire(run)

    def _retire(self, run):
        self.engine.finish(run)
        self._m_requests.inc(outcome="ok")
        self._m_latency_ms.observe((time.perf_counter() - run.t_submit) * 1e3)
        run.span.tag(
            outcome="ok", finish_reason=run.finish_reason,
            tokens=len(run.tokens),
            decode_steps=max(0, len(run.tokens) - 1),
            model_version=self.engine.model_version,
        ).end()
        run.future._set_result(run.result())

    def _fail_runs_locked(self):
        for run in list(self._runs.values()) + self._prefills:
            self._m_requests.inc(outcome="shutdown")
            run.span.tag(outcome="shutdown").end("error")
            run.future._set_error(ShutdownError("scheduler closed"))
            self.engine.finish(run)
        self._runs.clear()
        del self._prefills[:]

    def close(self, drain=True, timeout=30.0):
        self._drain_flag = bool(drain)
        return super().close(drain=drain, timeout=timeout)

    def stats(self):
        with self._cond:
            return {
                "queued_requests": self._queued_rows,
                "live_slots": len(self._runs),
                "prefilling": len(self._prefills),
                "alive": self._alive,
            }


def now_expired(pending, timeout):
    return (time.perf_counter() - pending.t_submit) > timeout
