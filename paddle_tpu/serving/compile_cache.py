"""Persistent on-disk compile cache for the serving runtime.

A serving replica must cold-start in seconds, not re-pay one trace + XLA
compile per (model, bucket) variant on every boot. Two layers make that
true, both rooted in one cache directory:

1. **Artifact cache** (this module's CompileCache): serialized `jax.export`
   artifacts keyed by (program fingerprint, feed avals, fetch names,
   jax/jaxlib version, backend platform). A hit skips the Python-side
   program lowering and StableHLO trace entirely — the replica deserializes
   and calls.
2. **XLA executable cache**: the same directory's `xla/` subdir is handed to
   JAX's persistent compilation cache, so the StableHLO→executable compile
   of each deserialized artifact is also a disk hit on second boot.

Cache writes are atomic (tmp + os.replace) and keyed content-addressed, so
concurrent replicas sharing a cache directory race only to write identical
bytes. Hit/miss counts ride the PR 4 metric registry
(`serving/compile_cache/{hits,misses}`), which is how the bench and the CI
smoke stage assert "zero compilations after warmup".

This module also owns the `export_compiled` artifact layout (an .npz holding
the serialized StableHLO plus parameters), folded in from inference.py so
the offline-export and serving paths share one format.
"""

import hashlib
import json
import os

import numpy as np

__all__ = [
    "CompileCache",
    "variant_key",
    "write_artifact",
    "read_artifact",
    "enable_xla_executable_cache",
]

ARTIFACT_SUFFIX = ".npz"

_xla_cache_dir = None  # process-global: jax's persistent-cache config is too


def _versions():
    import jax

    try:
        import jaxlib

        jl = getattr(jaxlib, "__version__", "?")
    except Exception:
        jl = "?"
    try:
        platform = jax.default_backend()
    except Exception:
        platform = "?"
    return jax.__version__, jl, platform


def variant_key(fingerprint, feed_avals, fetch_names, state_avals=None,
                geometry=None):
    """Content key for one compiled serving variant.

    `feed_avals` is {name: (shape tuple, dtype str)} for the PADDED bucket
    shapes. The jax/jaxlib versions and backend platform are folded in
    because a serialized artifact is only replayable on a compatible stack —
    a version bump misses cleanly instead of deserializing garbage.

    Stateful (generation) variants must also pass `state_avals` — the
    decode-state dict's {name: (shape, dtype)}, i.e. the KV pool tensors —
    and `geometry`, the engine's page layout (page_size, pool_pages,
    max_slots, ...). Both change the compiled gather/scatter indexing
    without necessarily changing any feed shape, so leaving them out of the
    key would let a config flip replay a stale executable against a
    differently-shaped pool.

    Parameter VALUES are deliberately absent: the key hashes the program
    fingerprint and avals only. That asymmetry is the hot-swap contract
    (docs/online.md) — ServingEngine.set_params replaces param values with
    same-aval arrays, so every cached variant (and the in-process compiled
    set) stays valid across an online-learning swap; only a geometry/program
    change misses.
    """
    jax_v, jaxlib_v, platform = _versions()
    doc = {
        "fingerprint": fingerprint,
        "feeds": sorted(
            (n, list(shape), str(dtype)) for n, (shape, dtype) in feed_avals.items()
        ),
        "fetches": list(fetch_names),
        "jax": jax_v,
        "jaxlib": jaxlib_v,
        "platform": platform,
    }
    if state_avals:
        doc["state"] = sorted(
            (n, list(shape), str(dtype))
            for n, (shape, dtype) in state_avals.items()
        )
    if geometry:
        doc["geometry"] = {k: geometry[k] for k in sorted(geometry)}
    return hashlib.sha256(json.dumps(doc, sort_keys=True).encode()).hexdigest()


def enable_xla_executable_cache(cache_dir):
    """Point JAX's persistent compilation cache at `<cache_dir>/xla` (once
    per process — the jax config is global). Makes the StableHLO→executable
    compile of every deserialized artifact a disk hit on later boots; the
    thresholds are zeroed because serving variants are small models whose
    compiles would otherwise fall under the default 1s/min-size cutoffs."""
    global _xla_cache_dir
    if _xla_cache_dir is not None:
        return _xla_cache_dir
    import jax

    d = os.path.join(cache_dir, "xla")
    os.makedirs(d, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        # the cache binds its directory at first use; by the time a serving
        # engine constructs, model loading has already touched the backend,
        # so force a re-read of the config or the dir update is silently
        # ignored (no files ever written)
        from jax.experimental.compilation_cache import (
            compilation_cache as _jax_cc,
        )

        _jax_cc.reset_cache()
        _xla_cache_dir = d
    except Exception:
        # an older jax without these knobs: the artifact layer still works
        _xla_cache_dir = ""
    return _xla_cache_dir


def _atomic_write_bytes(path, blob):
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)


class CompileCache:
    """Keyed blob store for serialized jax.export artifacts.

    Layout: `<dir>/<key>.stablehlo` (the serialized artifact) plus
    `<key>.json` (human-readable meta: model name, feed avals, versions —
    never read back for correctness, the key IS the identity).
    """

    def __init__(self, cache_dir, enable_xla_cache=True):
        self.dir = cache_dir
        os.makedirs(cache_dir, exist_ok=True)
        if enable_xla_cache:
            enable_xla_executable_cache(cache_dir)
        from ..observability import registry as _registry

        reg = _registry.default_registry()
        self._hits = reg.counter(
            "serving/compile_cache/hits",
            "serving variants served from the persistent compile cache",
        )
        self._misses = reg.counter(
            "serving/compile_cache/misses",
            "serving variants traced+compiled because the cache had no entry",
        )

    def _path(self, key):
        return os.path.join(self.dir, key + ".stablehlo")

    def get(self, key):
        """Deserialized jax.export Exported for `key`, or None. Counts a
        hit/miss on the registry either way."""
        from jax import export as jax_export

        path = self._path(key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            self._misses.inc()
            return None
        try:
            exported = jax_export.deserialize(blob)
        except Exception:
            # torn/incompatible entry: treat as a miss and let the caller
            # rebuild + overwrite it
            self._misses.inc()
            return None
        self._hits.inc()
        return exported

    def put(self, key, exported, meta=None):
        """Serialize + store atomically; concurrent writers of the same key
        write identical bytes, so last-rename-wins is safe."""
        _atomic_write_bytes(self._path(key), exported.serialize())
        doc = dict(meta or {})
        jax_v, jaxlib_v, platform = _versions()
        doc.update({"jax": jax_v, "jaxlib": jaxlib_v, "platform": platform})
        _atomic_write_bytes(
            os.path.join(self.dir, key + ".json"),
            json.dumps(doc, sort_keys=True, indent=1).encode(),
        )

    def get_or_build(self, key, build, meta=None):
        """(exported, hit). `build()` runs only on a miss; its result is
        stored before returning."""
        exported = self.get(key)
        if exported is not None:
            return exported, True
        exported = build()
        self.put(key, exported, meta=meta)
        return exported, False

    def stats(self):
        return {
            "hits": int(self._hits.value()),
            "misses": int(self._misses.value()),
        }


# ---------------------------------------------------------------------------
# export_compiled artifact layout (one .npz: StableHLO + parameters).
# Folded in from inference.py so the offline-export deliverable and the
# serving cache share one serializer.
# ---------------------------------------------------------------------------

def artifact_path(out_path):
    """The path np.savez actually writes for `out_path` (it appends `.npz`
    when missing — the export_compiled return-path bug this normalizes)."""
    return out_path if out_path.endswith(ARTIFACT_SUFFIX) else out_path + ARTIFACT_SUFFIX


def write_artifact(out_path, blob, feed_names, fetch_names, ro, mut):
    """Write one export_compiled artifact; returns the ACTUAL written path.

    bf16 parameters are stored as f32 with a dtype record (np.savez cannot
    serialize ml_dtypes arrays — the same constraint io._bf16_safe_save
    handles for checkpoints) and restored to bf16 by read_artifact so the
    deserialized computation sees the avals it was traced with."""
    from .. import io as _io

    path = artifact_path(out_path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    params = {}
    param_dtypes = {}
    for prefix, group in (("ro:", ro), ("mut:", mut)):
        for k, v in group.items():
            arr, orig_dtype = _io._bf16_safe_save(v)
            params[prefix + k] = arr
            if orig_dtype:
                param_dtypes[prefix + k] = orig_dtype
    np.savez(
        path,
        __stablehlo__=np.frombuffer(blob, np.uint8),
        __feed_names__=np.array(list(feed_names)),
        __fetch_names__=np.array(list(fetch_names)),
        __param_dtypes__=np.array(json.dumps(param_dtypes)),
        **params,
    )
    return path


def read_artifact(path):
    """Inverse of write_artifact: {exported, feed_names, fetch_names, ro,
    mut} with parameters as jax arrays."""
    from jax import export as jax_export
    import jax.numpy as jnp

    data = np.load(artifact_path(path))
    dtypes = {}
    if "__param_dtypes__" in data.files:
        dtypes = json.loads(str(data["__param_dtypes__"]))

    def _param(k):
        arr = jnp.asarray(data[k])
        if dtypes.get(k) == "bfloat16":
            arr = arr.astype(jnp.bfloat16)
        return arr

    return {
        "exported": jax_export.deserialize(data["__stablehlo__"].tobytes()),
        "feed_names": [str(s) for s in data["__feed_names__"]],
        "fetch_names": [str(s) for s in data["__fetch_names__"]],
        "ro": {k[3:]: _param(k) for k in data.files if k.startswith("ro:")},
        "mut": {k[4:]: _param(k) for k in data.files if k.startswith("mut:")},
    }
