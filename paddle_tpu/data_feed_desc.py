"""DataFeedDesc: declarative description of a multi-slot data feed.

Reference analog: python/paddle/fluid/data_feed_desc.py wrapping the
framework/data_feed.proto textproto (MultiSlotDataFeedDesc: per-slot name,
type, is_dense, is_used; batch_size). The same textproto surface is accepted
here — parsed with a small text-format reader instead of protobuf — and
lowered to the native MultiSlotDataFeed's slot-type vector
(paddle_tpu/native, C++ parser threads).
"""

import re

__all__ = ["DataFeedDesc"]


class _Slot:
    def __init__(self):
        self.name = None
        self.type = "uint64"  # reference types: uint64 | float
        self.is_dense = False
        self.is_used = False
        self.dense_dim = 1


class DataFeedDesc:
    def __init__(self, proto_text_or_path):
        try:
            with open(proto_text_or_path) as f:
                text = f.read()
        except (OSError, ValueError):
            text = proto_text_or_path
        self.name = "MultiSlotDataFeed"
        self.batch_size = 32
        self.slots = []
        self._parse(text)
        self._slot_by_name = {s.name: s for s in self.slots}

    def _parse(self, text):
        # minimal textproto reader for the data_feed.proto schema:
        # name/batch_size at top level, slots{...} blocks under multi_slot_desc
        m = re.search(r'name\s*:\s*"([^"]+)"', text)
        if m:
            self.name = m.group(1)
        m = re.search(r"batch_size\s*:\s*(\d+)", text)
        if m:
            self.batch_size = int(m.group(1))
        for block in re.findall(r"slots\s*\{([^}]*)\}", text):
            s = _Slot()
            m = re.search(r'name\s*:\s*"([^"]+)"', block)
            if m:
                s.name = m.group(1)
            m = re.search(r'type\s*:\s*"([^"]+)"', block)
            if m:
                s.type = m.group(1)
            m = re.search(r"is_dense\s*:\s*(\w+)", block)
            if m:
                s.is_dense = m.group(1) in ("true", "True", "1")
            m = re.search(r"is_used\s*:\s*(\w+)", block)
            if m:
                s.is_used = m.group(1) in ("true", "True", "1")
            self.slots.append(s)

    def set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def set_use_slots(self, use_slots_name):
        for name in use_slots_name:
            self._slot_by_name[name].is_used = True

    def set_dense_slots(self, dense_slots_name):
        for name in dense_slots_name:
            self._slot_by_name[name].is_dense = True

    def native_slot_types(self):
        """Per-slot dtype codes for the native parser (file column order)."""
        from . import native

        return [
            native.FLOAT32_SLOT if s.type == "float" else native.INT64_SLOT
            for s in self.slots
        ]

    def used_slots(self):
        return [(i, s) for i, s in enumerate(self.slots) if s.is_used]

    def desc(self):
        lines = ['name: "%s"' % self.name, "batch_size: %d" % self.batch_size]
        lines.append("multi_slot_desc {")
        for s in self.slots:
            lines.append(
                '  slots {\n    name: "%s"\n    type: "%s"\n    is_dense: %s\n    is_used: %s\n  }'
                % (s.name, s.type, str(s.is_dense).lower(), str(s.is_used).lower())
            )
        lines.append("}")
        return "\n".join(lines)
