"""Per-replica health: active probes + passive outcomes + staleness gate.

One `Replica` record per fleet member, owned by the router. Three signal
sources feed it, deliberately different in what they can prove:

- **active probes** (`probe()`, driven by the router's prober thread): GET
  ``/healthz`` — the PR 16 readiness contract — yields liveness ("the
  process answers"), readiness ("every model warmed; serving won't trace"),
  the per-model ``model_version`` actually live in the engines, and queue
  depth. Consecutive probe failures past `down_after` mark the replica DOWN.
- **passive outcomes** (`record_success`/`record_failure`, from real request
  attempts): feed the replica's CircuitBreaker and a latency EWMA. Passive
  signals react in one request; probes take a poll interval — both are
  needed (a replica can pass probes while failing real work, and vice
  versa).
- **staleness acks** (`apply_ack`): the PR 15 HotReloader writes
  ``ack-<consumer>.json`` into the model repository when a version has
  LANDED in the engines. The router reads those acks and gates routing on
  them — a freshly restarted replica is UP and READY long before it has
  replayed the published delta chain, and routing to it would serve stale
  predictions. `version_for_gate` prefers the ack (proof of landing) and
  falls back to the probed engine version.

`routable(targets)` is the single question the router asks: alive, ready,
not draining, breaker permitting, and at-or-past every target version.
"""

import http.client
import json
import threading
import time

from .breaker import CircuitBreaker

__all__ = ["Replica", "STARTING", "READY", "UNREADY", "DOWN", "DRAINING",
           "parse_url"]

STARTING = "starting"   # registered, no successful probe yet
READY = "ready"         # probed: live and every model warmed
UNREADY = "unready"     # probed: live but not (yet) warmed
DOWN = "down"           # `down_after` consecutive probe failures
DRAINING = "draining"   # administratively unroutable; in-flight finishing


def parse_url(url):
    """'http://host:port' -> (host, port). Scheme optional; no paths."""
    rest = url.split("//", 1)[-1].rstrip("/")
    if "/" in rest:
        raise ValueError("replica url %r must not carry a path" % url)
    host, _, port = rest.partition(":")
    if not host or not port:
        raise ValueError("replica url %r needs host:port" % url)
    return host, int(port)


class Replica:
    """One fleet member's live health record (thread-safe)."""

    def __init__(self, name, url, breaker=None, down_after=3,
                 latency_alpha=0.2):
        self.name = name
        self.url = url.rstrip("/")
        self.host, self.port = parse_url(url)
        self.breaker = breaker or CircuitBreaker(name=name)
        self.down_after = int(down_after)
        self._latency_alpha = float(latency_alpha)
        self._lock = threading.Lock()
        self.state = STARTING
        self.draining = False
        self.ready = False
        self.model_versions = {}   # model -> engine-reported version (probe)
        self.acked_version = None  # newest HotReloader ack seen in the repo
        self.queue_depth = 0
        self.inflight = 0
        self.probe_failures = 0
        self.last_probe_t = None
        self.last_error = None
        self.latency_ewma_ms = None
        self.requests_ok = 0
        self.requests_failed = 0

    # ------------------------------------------------------------ probing
    def probe(self, timeout_s=2.0):
        """One active probe: GET /healthz, fold the readiness doc in.
        Returns True when the replica answered (regardless of readiness)."""
        try:
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=timeout_s
            )
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    raise IOError("healthz status %d" % resp.status)
                doc = json.loads(body.decode())
            finally:
                conn.close()
        except Exception as e:
            with self._lock:
                self.probe_failures += 1
                self.last_error = repr(e)
                self.last_probe_t = time.monotonic()
                if self.probe_failures >= self.down_after:
                    self.state = DOWN
                    self.ready = False
            return False
        with self._lock:
            self.probe_failures = 0
            self.last_error = None
            self.last_probe_t = time.monotonic()
            self.ready = bool(doc.get("ready", True))
            self.model_versions = {
                m: int(info.get("model_version", 0))
                for m, info in (doc.get("models") or {}).items()
                if isinstance(info, dict)
            }
            self.queue_depth = sum(
                int(info.get("queue_depth", 0))
                for info in (doc.get("models") or {}).values()
                if isinstance(info, dict)
            )
            if not self.draining:
                self.state = READY if self.ready else UNREADY
        return True

    def apply_ack(self, version):
        """Fold in the newest HotReloader ack the router read from the model
        repository for this replica's consumer name."""
        with self._lock:
            self.acked_version = int(version)

    # ---------------------------------------------------- passive outcomes
    def begin_request(self):
        with self._lock:
            self.inflight += 1

    def end_request(self):
        with self._lock:
            self.inflight = max(self.inflight - 1, 0)

    def record_success(self, latency_ms=None):
        self.breaker.record_success()
        with self._lock:
            self.requests_ok += 1
            if latency_ms is not None:
                self.latency_ewma_ms = (
                    latency_ms if self.latency_ewma_ms is None
                    else (1.0 - self._latency_alpha) * self.latency_ewma_ms
                    + self._latency_alpha * latency_ms
                )

    def record_failure(self, err=None):
        self.breaker.record_failure()
        with self._lock:
            self.requests_failed += 1
            if err is not None:
                self.last_error = repr(err)

    # -------------------------------------------------------------- gating
    def version_for_gate(self, model):
        """The version this replica can PROVE it serves for `model`: the
        repo ack when one exists (landing proof), else the probed engine
        version."""
        with self._lock:
            if self.acked_version is not None:
                return self.acked_version
            return self.model_versions.get(model, 0)

    def routable(self, target_versions=None):
        """May the router send NEW requests here? Alive + ready + not
        draining + breaker closed/probing + current on every gated model.
        Does NOT claim a half-open probe slot (allow() does, at pick time)."""
        with self._lock:
            if self.draining or self.state != READY:
                return False
        if self.breaker.state == "open":
            return False
        for model, target in (target_versions or {}).items():
            if target is not None and self.version_for_gate(model) < target:
                return False
        return True

    def stats(self):
        with self._lock:
            return {
                "name": self.name,
                "url": self.url,
                "state": DRAINING if self.draining else self.state,
                "ready": self.ready,
                "breaker": self.breaker.stats(),
                "model_versions": dict(self.model_versions),
                "acked_version": self.acked_version,
                "queue_depth": self.queue_depth,
                "inflight": self.inflight,
                "latency_ewma_ms": (
                    round(self.latency_ewma_ms, 3)
                    if self.latency_ewma_ms is not None else None
                ),
                "requests_ok": self.requests_ok,
                "requests_failed": self.requests_failed,
                "last_error": self.last_error,
            }
