"""Per-replica circuit breaker: closed -> open -> half-open -> closed.

The router's passive defense against a replica that is up but wrong — a
browned-out process answering every request with a timeout still costs each
client a full attempt deadline unless something stops sending traffic at it.
The breaker is that something:

- **closed** (normal): every request flows. Failures are counted two ways —
  a consecutive-failure streak (`failure_threshold`) for hard crashes, and a
  sliding-window error rate (`error_rate_threshold` over the last `window`
  outcomes, armed only past `min_requests`) for brown-outs that still answer
  sometimes. Either trips the breaker open.
- **open**: requests are refused locally (allow() == False) — the caller
  fails over to another replica without paying this one's timeout. After
  `open_for_s` the breaker lets PROBE traffic through (half-open).
- **half-open**: at most `half_open_probes` outstanding requests are let
  through as probes. `success_threshold` consecutive probe successes close
  the breaker (streaks and window reset); any probe failure reopens it with
  the open interval DOUBLED (capped at `max_open_s`) — a replica that keeps
  failing its probes gets exponentially less probe traffic, the same
  backoff-shape argument as retry.py.

`clock` is injectable (monotonic seconds) so the state machine unit-tests
run at zero wall time; `on_transition(name, old, new)` is the metrics hook
the router uses to count breaker flips.

Thread-safe: the router's handler threads record outcomes concurrently.
"""

import threading
import time

__all__ = ["CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    def __init__(self, name="", failure_threshold=5, error_rate_threshold=0.5,
                 window=20, min_requests=10, open_for_s=2.0, max_open_s=30.0,
                 half_open_probes=1, success_threshold=2,
                 clock=time.monotonic, on_transition=None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if not 0.0 < error_rate_threshold <= 1.0:
            raise ValueError("error_rate_threshold must be in (0, 1]")
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.error_rate_threshold = float(error_rate_threshold)
        self.window = int(window)
        self.min_requests = int(min_requests)
        self.open_for_s = float(open_for_s)
        self.max_open_s = float(max_open_s)
        self.half_open_probes = int(half_open_probes)
        self.success_threshold = int(success_threshold)
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._outcomes = []  # sliding window of 0/1 (1 = failure)
        self._consecutive_failures = 0
        self._probe_successes = 0
        self._probes_outstanding = 0
        self._opened_at = None
        self._open_interval = self.open_for_s
        self.opens = 0  # lifetime trips, for stats/tests

    # ------------------------------------------------------------ internals
    def _transition_locked(self, new):
        old, self._state = self._state, new
        if new == OPEN:
            self.opens += 1
            self._opened_at = self._clock()
        if new == CLOSED:
            self._outcomes = []
            self._consecutive_failures = 0
            self._open_interval = self.open_for_s
        if new in (CLOSED, HALF_OPEN):
            self._probe_successes = 0
            self._probes_outstanding = 0
        if self._on_transition is not None and old != new:
            self._on_transition(self.name, old, new)

    def _maybe_half_open_locked(self):
        if self._state == OPEN and (
            self._clock() - self._opened_at >= self._open_interval
        ):
            self._transition_locked(HALF_OPEN)

    # ------------------------------------------------------------------ api
    @property
    def state(self):
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def allow(self):
        """May a request be sent to this replica right now? In half-open
        this CLAIMS a probe slot — callers that get True must report the
        outcome via record_success/record_failure."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN:
                if self._probes_outstanding < self.half_open_probes:
                    self._probes_outstanding += 1
                    return True
            return False

    def record_success(self):
        with self._lock:
            self._consecutive_failures = 0
            self._push_outcome_locked(0)
            if self._state == HALF_OPEN:
                self._probes_outstanding = max(self._probes_outstanding - 1, 0)
                self._probe_successes += 1
                if self._probe_successes >= self.success_threshold:
                    self._transition_locked(CLOSED)

    def record_failure(self):
        with self._lock:
            self._consecutive_failures += 1
            self._push_outcome_locked(1)
            if self._state == HALF_OPEN:
                # a failed probe: back off harder before the next one
                self._open_interval = min(
                    self._open_interval * 2.0, self.max_open_s
                )
                self._transition_locked(OPEN)
                return
            if self._state != CLOSED:
                return
            if self._consecutive_failures >= self.failure_threshold:
                self._transition_locked(OPEN)
                return
            n = len(self._outcomes)
            if n >= self.min_requests:
                rate = sum(self._outcomes) / float(n)
                if rate >= self.error_rate_threshold:
                    self._transition_locked(OPEN)

    def _push_outcome_locked(self, failed):
        self._outcomes.append(failed)
        if len(self._outcomes) > self.window:
            self._outcomes.pop(0)

    def stats(self):
        with self._lock:
            self._maybe_half_open_locked()
            n = len(self._outcomes)
            return {
                "state": self._state,
                "opens": self.opens,
                "consecutive_failures": self._consecutive_failures,
                "window_error_rate": (
                    round(sum(self._outcomes) / float(n), 3) if n else 0.0
                ),
                "open_interval_s": self._open_interval,
            }
