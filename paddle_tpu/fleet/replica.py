"""Replica child process + launcher for the serving fleet.

`python -m paddle_tpu.fleet.replica <spec.json>` boots ONE ModelServer from
a declarative spec and serves until SIGTERM (drain + exit 0) or SIGKILL
(the chaos case — no goodbye, in-flight requests fail over at the router).
`ReplicaProcess` is the parent-side handle bench.py and the fleet tests use
to spawn/kill/restart replicas as real OS processes — a SIGKILLed thread is
not a thing, so fleet failover can only be exercised with subprocesses.

Spec (JSON):
  name                 replica name == HotReloader consumer == ack identity
  host, port           bind address (port 0 = ephemeral; see port_file)
  request_timeout_ms   ModelServer request timeout
  predict: {model, model_dir, cache_dir?, batch_buckets?, batcher_opts?}
  generate: {model, model_kw, seed?, max_slots?, page_size?, max_context?,
             scheduler_opts?}        (GPTDecoder; seed fixes the params, so
                                      same-seed replicas decode bit-equal)
  repo, poll_interval_s  model repository to follow: a HotReloader applies
                         published versions to the predict engine and acks
                         as `name` — the router's staleness gate reads
                         those acks
  port_file            where to atomically write {"port", "url", "pid"}
                       once serving (the parent's readiness rendezvous)
  router_url           optional: self-register with the fleet router

Both entry points stay import-light at module load so the launcher can be
imported (e.g. by tests collecting under JAX_PLATFORMS=cpu) without paying
for jax until a replica actually boots.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

__all__ = ["ReplicaProcess", "main"]


def _atomic_json(path, doc):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _register_with_router(router_url, name, url, attempts=10):
    import http.client

    from .health import parse_url

    host, port = parse_url(router_url)
    body = json.dumps({"name": name, "url": url}).encode()
    for i in range(attempts):
        try:
            conn = http.client.HTTPConnection(host, port, timeout=2.0)
            try:
                conn.request("POST", "/fleet/register", body=body,
                             headers={"Content-Type": "application/json"})
                if conn.getresponse().status == 200:
                    return True
            finally:
                conn.close()
        except OSError:
            pass
        time.sleep(0.1 * (i + 1))
    return False


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m paddle_tpu.fleet.replica <spec.json>",
              file=sys.stderr)
        return 2
    with open(argv[0]) as f:
        spec = json.load(f)

    from ..serving import ModelServer

    name = spec.get("name", "replica")
    server = ModelServer(
        host=spec.get("host", "127.0.0.1"),
        port=int(spec.get("port", 0)),
        request_timeout_ms=float(spec.get("request_timeout_ms", 5000.0)),
    )
    engines = {}

    p = spec.get("predict")
    if p:
        kw = {}
        if p.get("cache_dir"):
            kw["cache_dir"] = p["cache_dir"]
        if p.get("batch_buckets"):
            kw["batch_buckets"] = tuple(p["batch_buckets"])
        eng = server.add_model(
            p["model"], model_dir=p["model_dir"],
            batcher_opts=p.get("batcher_opts"), **kw
        )
        engines[p["model"]] = eng

    g = spec.get("generate")
    if g:
        from ..executor import Scope
        from ..models.gpt_decoder import GPTDecoder

        model = GPTDecoder(**g.get("model_kw", {}))
        server.add_generation_model(
            g["model"], model=model,
            scope=Scope(seed=int(g.get("seed", 0))),
            max_slots=int(g.get("max_slots", 4)),
            page_size=int(g.get("page_size", 8)),
            max_context=g.get("max_context"),
            scheduler_opts=g.get("scheduler_opts"),
        )

    reloader = None
    if spec.get("repo") and engines:
        from ..online.reloader import HotReloader

        reloader = HotReloader(
            spec["repo"], engines, consumer=name,
            poll_interval_s=float(spec.get("poll_interval_s", 0.2)),
        )
        reloader.check_once()  # land whatever is already published, pre-ack
        reloader.start()

    port = server.start()
    if spec.get("port_file"):
        _atomic_json(spec["port_file"], {
            "name": name, "port": port, "url": server.url, "pid": os.getpid(),
        })
    if spec.get("router_url"):
        _register_with_router(spec["router_url"], name, server.url)

    done = threading.Event()

    def _term(signum, frame):
        done.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    done.wait()
    if reloader is not None:
        reloader.stop()
    server.stop(drain=True)
    return 0


class ReplicaProcess:
    """Parent-side handle on one replica subprocess.

    start() writes the spec + spawns the child; wait_ready() blocks on the
    port-file rendezvous and then on /healthz ready; kill() is SIGKILL (the
    chaos primitive); terminate() is the polite SIGTERM drain. restart()
    re-spawns with the same spec — same name, so after its HotReloader
    re-acks, the router's staleness gate lets it rejoin.
    """

    def __init__(self, spec, workdir, env=None, faults=None):
        self.spec = dict(spec)
        self.workdir = workdir
        self.name = self.spec.get("name", "replica")
        self.spec_path = os.path.join(workdir, "%s.spec.json" % self.name)
        self.port_file = os.path.join(workdir, "%s.port.json" % self.name)
        self.log_path = os.path.join(workdir, "%s.log" % self.name)
        self.spec["port_file"] = self.port_file
        self._extra_env = dict(env or {})
        if faults:
            from ..resilience.faults import ENV_VAR

            self._extra_env[ENV_VAR] = faults
        self.proc = None
        self._log = None

    # ------------------------------------------------------------ lifecycle
    def start(self):
        if self.proc is not None and self.proc.poll() is None:
            raise RuntimeError("replica %s already running" % self.name)
        try:
            os.remove(self.port_file)  # stale rendezvous from a prior run
        except OSError:
            pass
        _atomic_json(self.spec_path, self.spec)
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(self._extra_env)
        self._log = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.fleet.replica",
             self.spec_path],
            stdout=self._log, stderr=subprocess.STDOUT, env=env,
        )
        return self

    def wait_ready(self, timeout=120.0):
        """Block until the child serves AND reports ready; returns its url."""
        import http.client

        deadline = time.monotonic() + float(timeout)
        port = None
        while time.monotonic() < deadline:
            if self.proc is not None and self.proc.poll() is not None:
                raise RuntimeError(
                    "replica %s exited rc=%d before ready (log: %s)"
                    % (self.name, self.proc.returncode, self.log_path)
                )
            if port is None:
                try:
                    with open(self.port_file) as f:
                        port = json.load(f)["port"]
                except (OSError, ValueError, KeyError):
                    time.sleep(0.05)
                    continue
            try:
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=2.0)
                try:
                    conn.request("GET", "/healthz")
                    doc = json.loads(conn.getresponse().read().decode())
                finally:
                    conn.close()
                if doc.get("ready"):
                    return self.url
            except (OSError, ValueError):
                pass
            time.sleep(0.05)
        raise TimeoutError(
            "replica %s not ready in %.0fs (log: %s)"
            % (self.name, timeout, self.log_path)
        )

    @property
    def port(self):
        with open(self.port_file) as f:
            return json.load(f)["port"]

    @property
    def url(self):
        return "http://127.0.0.1:%d" % self.port

    def alive(self):
        return self.proc is not None and self.proc.poll() is None

    def kill(self):
        """SIGKILL — no drain, no handlers; the chaos primitive."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(10.0)
        self._close_log()

    def terminate(self, timeout=30.0):
        """SIGTERM — the child drains its batchers and exits 0."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(10.0)
        self._close_log()
        return self.proc.returncode if self.proc is not None else None

    def restart(self):
        """Spawn a fresh process from the same spec (post-kill rejoin)."""
        if self.alive():
            raise RuntimeError("replica %s still running" % self.name)
        self._close_log()
        return self.start()

    def _close_log(self):
        if self._log is not None:
            try:
                self._log.close()
            except OSError:
                pass
            self._log = None


if __name__ == "__main__":
    sys.exit(main())
