"""Health-aware fleet router: retries, hedging, circuit breakers, drain.

One `Router` process fronts N replica ModelServers and owns the fleet's
failure policy, so clients see ONE url and (within the deadline they chose)
zero 5xx while replicas die, restart, brown out and hot-swap underneath:

- **placement**: least-inflight among routable replicas (random tie-break).
  Routable = probed READY + not draining + breaker not open + at-or-past
  every gated model version (see health.Replica.routable).
- **retries**: a failed attempt (connect error, reset, 5xx, attempt
  timeout) fails over to a DIFFERENT replica — same one only when there is
  no alternative — under `resilience.RetryPolicy` with decorrelated jitter,
  never past the request's total deadline (`with_deadline` on the remaining
  budget). A fleet-wide token-bucket retry budget (`retry_budget_ratio`
  tokens earned per request, spent 1 per retry) keeps a brown-out from
  amplifying load: when the fleet is failing broadly, retries stop first.
- **hedging** (`:predict` only — idempotent; `:generate` is not hedged): if
  the primary hasn't answered within the hedge delay (p95 of recent fleet
  latency once warmed up, `hedge_delay_ms` until then), the SAME request is
  sent to a second replica; first reply wins, the loser's connection is
  closed and its outcome is NOT counted against its breaker (cancellation
  is not failure).
- **membership**: register/deregister/drain, programmatic or via POST
  ``/fleet/register|deregister|drain``. Drain stops new sends immediately
  and waits for the replica's in-flight requests; a SIGKILLed replica's
  in-flight requests fail over via the retry path.
- **staleness gate**: pass `repo=` (a PR 15 model repository) and
  `repo_model=` to refuse routing to replicas that haven't landed+acked the
  published version — a restarted replica rejoins only after its
  HotReloader catches up, so a fleet mid-hot-swap never serves two model
  generations to one client.

Routes: ``POST /v1/models/<name>:predict|:generate`` (proxied),
``GET /healthz`` (router liveness + routable count), ``GET /fleet`` (full
per-replica stats), ``GET /v1/models`` (proxied to one routable replica),
``GET /metrics``, ``POST /fleet/register|deregister|drain``.
"""

import http.client
import json
import queue
import threading
import time
from random import Random

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..observability import flightrec as _flightrec
from ..observability import tracing as _tracing
from ..observability.tracing import NULL_SPAN, TRACE_HEADER
from ..resilience.retry import DeadlineExceeded, FatalError, RetryPolicy
from .health import Replica

__all__ = ["Router", "RetryBudget", "NoReplicaAvailable", "UpstreamError"]

PREDICT_PREFIX = "/v1/models/"


class NoReplicaAvailable(ConnectionError):
    """No routable replica right now — retryable: one may close its breaker,
    finish warmup or land the target version within the deadline."""


class UpstreamError(ConnectionError):
    """A replica answered 5xx. Retryable on another replica; carries the
    upstream reply so an exhausted retry loop can surface the real error."""

    def __init__(self, status, body, content_type, retry_after=None):
        super().__init__("upstream status %d" % status)
        self.status = status
        self.body = body
        self.content_type = content_type
        self.retry_after = retry_after


class RetryBudget:
    """Fleet-wide token bucket bounding retry amplification: every routed
    request earns `ratio` tokens (capped), every retry spends one. Under a
    broad brown-out the bucket empties and retries stop — the fleet sheds
    the *extra* load retries would add, instead of melting down twice."""

    def __init__(self, ratio=0.2, max_tokens=50.0):
        self.ratio = float(ratio)
        self.max_tokens = float(max_tokens)
        self._tokens = self.max_tokens  # start full: a cold fleet may retry
        self._lock = threading.Lock()

    def on_request(self):
        with self._lock:
            self._tokens = min(self._tokens + self.ratio, self.max_tokens)

    def take(self):
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    @property
    def tokens(self):
        with self._lock:
            return self._tokens


class Router:
    """Fleet front end (see module docstring)."""

    def __init__(self, host="127.0.0.1", port=0, attempt_timeout_s=5.0,
                 total_deadline_s=15.0, max_attempts=4,
                 retry_budget_ratio=0.2, retry_budget_max=50.0,
                 hedge=True, hedge_delay_ms=75.0, hedge_after_observations=20,
                 probe_interval_s=0.5, down_after=3,
                 repo=None, repo_model=None, breaker_opts=None, seed=0,
                 fleet_metrics=False, scrape_interval_s=2.0,
                 slos=None, sentinels=None, alert_rules=None,
                 alerts_path=None):
        self.host = host
        self._port = port
        self.attempt_timeout_s = float(attempt_timeout_s)
        self.total_deadline_s = float(total_deadline_s)
        self.hedge_enabled = bool(hedge)
        self.hedge_delay_ms = float(hedge_delay_ms)
        self.hedge_after_observations = int(hedge_after_observations)
        self.probe_interval_s = float(probe_interval_s)
        self.down_after = int(down_after)
        self.repo = repo
        self.repo_model = repo_model
        self.breaker_opts = dict(breaker_opts or {})
        self._rng = Random(seed)
        self._lock = threading.Lock()
        self._replicas = {}
        self._targets = {}  # model -> minimum version (manual overrides)
        self._budget = RetryBudget(retry_budget_ratio, retry_budget_max)
        # template only — every request derives a fresh copy (fresh jitter
        # state) via with_deadline, so concurrent requests don't share RNG
        self._retry_template = RetryPolicy(
            max_attempts=int(max_attempts), base_delay=0.02, max_delay=0.5,
            jitter="decorrelated", seed=seed,
            retryable=(NoReplicaAvailable, UpstreamError, ConnectionError,
                       TimeoutError, OSError, EOFError),
        )
        self._httpd = None
        self._http_thread = None
        self._probe_stop = threading.Event()
        self._probe_thread = None
        # fleet-wide observability (PR 20) — entirely off unless asked for:
        # no scrape loop, no SLO evaluation, no extra request-path work
        self.fleet_metrics = bool(fleet_metrics)
        self.scrape_interval_s = float(scrape_interval_s)
        self._slos = list(slos or [])
        self._sentinels = list(sentinels or [])
        self._alert_rules = alert_rules
        self._alerts_path = alerts_path
        self._aggregator = None
        self._alert_engine = None

        from ..observability import registry as _registry

        self._registry = _registry.default_registry()
        self._m_requests = self._registry.counter(
            "fleet/requests", "routed requests by kind + final code"
        )
        self._m_retries = self._registry.counter(
            "fleet/retries", "failover retry attempts by kind"
        )
        self._m_hedges = self._registry.counter(
            "fleet/hedges", "hedge requests launched / won by the hedge"
        )
        self._m_breaker = self._registry.counter(
            "fleet/breaker_transitions", "circuit breaker flips by to-state"
        )
        self._m_budget_denied = self._registry.counter(
            "fleet/retry_budget_denied", "retries refused by the fleet budget"
        )
        self._g_routable = self._registry.gauge(
            "fleet/replicas_routable", "replicas eligible for new requests"
        )
        self._g_total = self._registry.gauge(
            "fleet/replicas_total", "registered replicas"
        )
        self._h_latency = self._registry.histogram(
            "fleet/request_ms", "end-to-end routed request latency"
        )

    # ---- membership -------------------------------------------------------
    def register(self, name, url):
        """Add (or re-add) a replica. It becomes routable only after a
        probe reports ready — registering is cheap and safe mid-traffic."""
        from .breaker import CircuitBreaker

        rep = Replica(
            name, url,
            breaker=CircuitBreaker(
                name=name,
                on_transition=self._on_breaker,
                **self.breaker_opts,
            ),
            down_after=self.down_after,
        )
        with self._lock:
            self._replicas[name] = rep
        rep.probe()  # first look now, not a poll interval later
        self._refresh_acks()
        return rep

    def _on_breaker(self, name, old, new):
        self._m_breaker.inc(replica=name, to=new)
        # a breaker flip is exactly the moment worth a black-box dump: the
        # recent span ring holds the failed attempts that tripped it
        _flightrec.trigger(
            "breaker_transition", replica=name, from_state=old, to_state=new
        )

    def deregister(self, name):
        with self._lock:
            return self._replicas.pop(name, None) is not None

    def drain(self, name, wait_s=10.0):
        """Stop NEW requests to `name` immediately; wait for its in-flight
        requests to finish. Returns True when it drained within `wait_s`."""
        with self._lock:
            rep = self._replicas.get(name)
        if rep is None:
            return False
        rep.draining = True
        deadline = time.monotonic() + float(wait_s)
        while time.monotonic() < deadline:
            if rep.inflight == 0:
                return True
            time.sleep(0.01)
        return rep.inflight == 0

    def replicas(self):
        with self._lock:
            return dict(self._replicas)

    def set_target_version(self, model, version):
        """Manually gate `model` on `version` (repo-less deployments); pass
        None to drop the gate."""
        with self._lock:
            if version is None:
                self._targets.pop(model, None)
            else:
                self._targets[model] = int(version)

    def target_versions(self):
        """{model: minimum version} — manual gates plus the repo's
        LATEST.json pointer for `repo_model`."""
        with self._lock:
            targets = dict(self._targets)
        if self.repo and self.repo_model:
            from ..online.publisher import read_latest

            pointer = read_latest(self.repo)
            if pointer:
                v = int(pointer.get("version", 0))
                if v > targets.get(self.repo_model, -1):
                    targets[self.repo_model] = v
        return targets

    # ---- probing ----------------------------------------------------------
    def _refresh_acks(self):
        if not self.repo:
            return
        from ..online.staleness import read_acks

        acks = read_acks(self.repo)
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            ack = acks.get(rep.name)
            if ack is not None:
                rep.apply_ack(ack.get("version", 0))

    def probe_once(self):
        """One active probe round over every replica + one ack refresh.
        Called by the prober thread; tests call it directly for lockstep."""
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            rep.probe()
        self._refresh_acks()
        targets = self.target_versions()
        self._g_total.set(len(reps))
        self._g_routable.set(
            sum(1 for r in reps if r.routable(targets))
        )

    def _probe_loop(self):
        while not self._probe_stop.wait(self.probe_interval_s):
            try:
                self.probe_once()
            except Exception:
                pass  # the prober must outlive any one bad poll

    # ---- placement --------------------------------------------------------
    def _pick(self, exclude=()):
        """Least-inflight routable replica, random tie-break, preferring
        replicas not in `exclude` (the already-tried set); claims the
        breaker's half-open probe slot when applicable."""
        targets = self.target_versions()
        with self._lock:
            reps = list(self._replicas.values())
        cands = [r for r in reps if r.routable(targets)]
        fresh = [r for r in cands if r.name not in exclude]
        pool = fresh or cands  # same replica only when no alternative
        pool.sort(key=lambda r: (r.inflight, self._rng.random()))
        for rep in pool:
            if rep.breaker.allow():
                return rep
        return None

    # ---- one attempt ------------------------------------------------------
    def _send(self, rep, path, body, content_type, timeout_s, holder=None,
              trace_header=None):
        """One upstream HTTP exchange. `holder.conn` exposes the live
        connection so a hedging loser can be cancelled by closing it."""
        conn = http.client.HTTPConnection(rep.host, rep.port,
                                          timeout=timeout_s)
        if holder is not None:
            holder.conn = conn
        headers = {"Content-Type": content_type}
        if trace_header:
            headers[TRACE_HEADER] = trace_header
        try:
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            return (resp.status, data,
                    resp.getheader("Content-Type", "application/json"),
                    resp.getheader("Retry-After"))
        finally:
            conn.close()

    def _attempt_one(self, rep, path, body, content_type, timeout_s,
                     holder=None, cancelled=None, span=NULL_SPAN):
        """Send to one replica, folding the outcome into its breaker and
        latency EWMA. Returns (status, body, ctype) for any < 500 status;
        raises (retryably) otherwise. A cancelled hedge records nothing.
        The attempt span ends BEFORE the breaker sees the failure, so a
        breaker-transition flight-recorder bundle contains it."""
        span.tag(replica=rep.name, breaker=rep.breaker.state,
                 inflight=rep.inflight)
        rep.begin_request()
        t0 = time.perf_counter()
        try:
            status, data, ctype, retry_after = self._send(
                rep, path, body, content_type, timeout_s, holder,
                trace_header=span.header(),
            )
        except Exception as e:
            if cancelled is not None and cancelled.is_set():
                span.tag(cancelled=True).end()
                raise
            span.error(e).end()
            rep.record_failure(e)
            raise
        finally:
            rep.end_request()
        if status >= 500:
            err = UpstreamError(status, data, ctype, retry_after)
            span.tag(code=status).error(err).end()
            rep.record_failure(err)
            raise err
        span.tag(code=status).end()
        rep.record_success((time.perf_counter() - t0) * 1e3)
        return status, data, ctype

    # ---- hedging ----------------------------------------------------------
    def _hedge_delay_s(self):
        """p95 of recent fleet latency once the histogram has seen enough
        traffic; the configured default until then."""
        if self._h_latency.count >= self.hedge_after_observations:
            p95 = self._h_latency.percentile(95)
            if p95 and p95 > 0:
                return p95 / 1e3
        return self.hedge_delay_ms / 1e3

    def _attempt_hedged(self, path, body, content_type, tried, timeout_s,
                        parent_span=NULL_SPAN):
        """One (possibly hedged) attempt: primary now, a second replica if
        the primary is still silent after the hedge delay; first reply wins,
        the loser's connection is closed without a breaker penalty."""
        primary = self._pick(tried)
        if primary is None:
            raise NoReplicaAvailable("no routable replica")
        tried.add(primary.name)
        results = queue.Queue()
        cancelled = threading.Event()
        holders = []

        def run(rep, hedge_leg=False):
            holder = type("H", (), {"conn": None})()
            holders.append(holder)
            span = parent_span.child(
                "router.attempt", hedge=hedge_leg
            )
            try:
                results.put((rep, self._attempt_one(
                    rep, path, body, content_type, timeout_s,
                    holder=holder, cancelled=cancelled, span=span,
                ), None))
            except Exception as e:
                results.put((rep, None, e))

        threading.Thread(target=run, args=(primary,), daemon=True).start()
        outstanding = 1
        deadline = time.monotonic() + timeout_s
        first = None
        try:
            first = results.get(timeout=min(self._hedge_delay_s(), timeout_s))
        except queue.Empty:
            hedge = self._pick(tried)
            if hedge is not None:
                tried.add(hedge.name)
                self._m_hedges.inc(event="launched")
                # hedges are rare and diagnostic gold: exempt the whole
                # trace from OK-trace sampling
                parent_span.force_keep().event(
                    "hedge_launched", replica=hedge.name
                )
                threading.Thread(target=run, args=(hedge, True),
                                 daemon=True).start()
                outstanding += 1

        last_err = None
        got = [first] if first is not None else []
        while True:
            for rep, ok, err in got:
                outstanding -= 1
                if err is None:
                    cancelled.set()
                    for h in holders:  # cancel the loser mid-flight
                        conn = getattr(h, "conn", None)
                        if conn is not None:
                            try:
                                conn.close()
                            except Exception:
                                pass
                    if rep is not primary:
                        self._m_hedges.inc(event="won")
                        parent_span.event("hedge_won", replica=rep.name)
                    return ok
                last_err = err
            got = []
            if outstanding <= 0:
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                cancelled.set()
                raise TimeoutError(
                    "attempt timeout %.2fs with %d upstream(s) silent"
                    % (timeout_s, outstanding)
                )
            try:
                got = [results.get(timeout=remaining)]
            except queue.Empty:
                got = []
        raise last_err if last_err is not None else NoReplicaAvailable(
            "hedged attempt produced no result"
        )

    # ---- routing ----------------------------------------------------------
    def route(self, path, body, content_type="application/json",
              deadline_s=None, parent=None):
        """Route one POST. Returns (status, body bytes, content type) — the
        winning replica's reply, or a router-synthesized 503/504 after the
        deadline/budget/replicas are exhausted. `parent` (a Span or an
        X-Fleet-Trace header value) roots this request's trace; the root
        span records every attempt/hedge/backoff as child spans."""
        kind = "generate" if path.endswith(":generate") else "predict"
        span = _tracing.tracer().start_span(
            "router.request", parent=parent, kind=kind, path=path
        )
        t0 = time.monotonic()
        total = float(deadline_s or self.total_deadline_s)
        hard_deadline = t0 + total
        self._budget.on_request()
        tried = set()
        attempts = [0]

        def attempt():
            if attempts[0] > 0:
                if not self._budget.take():
                    self._m_budget_denied.inc()
                    span.event(
                        "retry_denied",
                        budget_tokens=round(self._budget.tokens, 2),
                    )
                    raise FatalError("fleet retry budget exhausted")
                self._m_retries.inc(kind=kind)
                # retry-budget spend, per Dapper log entry: how much of the
                # fleet's amplification headroom this request consumed
                span.event(
                    "retry", attempt=attempts[0],
                    budget_tokens=round(self._budget.tokens, 2),
                )
            attempts[0] += 1
            remaining = hard_deadline - time.monotonic()
            if remaining <= 0:
                raise FatalError("deadline exhausted before attempt")
            timeout_s = min(self.attempt_timeout_s, max(remaining, 0.05))
            if kind == "predict" and self.hedge_enabled:
                return self._attempt_hedged(
                    path, body, content_type, tried, timeout_s,
                    parent_span=span,
                )
            rep = self._pick(tried)
            if rep is None:
                raise NoReplicaAvailable("no routable replica")
            tried.add(rep.name)
            return self._attempt_one(
                rep, path, body, content_type, timeout_s,
                span=span.child("router.attempt", attempt=attempts[0]),
            )

        policy = self._retry_template.with_deadline(total)
        try:
            status, data, ctype = policy.call(attempt)
        except UpstreamError as e:
            # retries exhausted on a real upstream reply: pass it through
            status, data, ctype = e.status, e.body, e.content_type
        except FatalError as e:
            status, data, ctype = 503, json.dumps(
                {"error": str(e), "attempts": attempts[0]}
            ).encode(), "application/json"
        except DeadlineExceeded as e:
            status, data, ctype = 504, json.dumps(
                {"error": str(e), "attempts": e.attempts}
            ).encode(), "application/json"
        except NoReplicaAvailable as e:
            status, data, ctype = 503, json.dumps(
                {"error": str(e), "attempts": attempts[0]}
            ).encode(), "application/json"
        except (ConnectionError, TimeoutError, OSError, EOFError) as e:
            status, data, ctype = 503, json.dumps(
                {"error": repr(e), "attempts": attempts[0]}
            ).encode(), "application/json"
        self._m_requests.inc(kind=kind, code=str(status))
        self._h_latency.observe((time.monotonic() - t0) * 1e3)
        span.tag(code=status, attempts=attempts[0])
        span.end("ok" if status < 500 else "error")
        if status >= 500:
            # the router gave up on a client request — dump the black box
            # (span ring now includes this request's failed attempts)
            _flightrec.trigger(
                "router_5xx", code=status, path=path,
                attempts=attempts[0], trace=span.trace_id,
            )
        return status, data, ctype

    # ---- stats ------------------------------------------------------------
    def stats(self):
        targets = self.target_versions()
        with self._lock:
            reps = list(self._replicas.values())
        return {
            "replicas": {r.name: r.stats() for r in reps},
            "routable": sorted(
                r.name for r in reps if r.routable(targets)
            ),
            "target_versions": targets,
            "retry_budget_tokens": round(self._budget.tokens, 2),
            "hedge_delay_ms": round(self._hedge_delay_s() * 1e3, 3),
        }

    def _proxy_get(self, path):
        """GET proxied to one routable replica (metadata routes)."""
        rep = self._pick()
        if rep is None:
            return 503, json.dumps({"error": "no routable replica"}).encode()
        conn = http.client.HTTPConnection(rep.host, rep.port,
                                          timeout=self.attempt_timeout_s)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read()
        except Exception as e:
            return 503, json.dumps({"error": repr(e)}).encode()
        finally:
            conn.close()

    # ---- lifecycle --------------------------------------------------------
    def start(self):
        """Bind the front end + start the prober; returns the bound port."""
        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _reply(self, code, body, content_type="application/json",
                       trace=None):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                if trace:
                    # the trace id rides back to the client: "my request
                    # was slow" becomes a greppable span tree
                    self.send_header(TRACE_HEADER, trace)
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, code, obj):
                self._reply(code, json.dumps(obj).encode())

            def do_GET(self):
                try:
                    if self.path.startswith("/healthz"):
                        st = router.stats()
                        self._reply_json(200, {
                            "status": "ok",
                            "replicas": len(st["replicas"]),
                            "routable": len(st["routable"]),
                        })
                    elif self.path == "/fleet":
                        self._reply_json(200, router.stats())
                    elif self.path == "/fleet/metrics":
                        agg = router._aggregator
                        if agg is None:
                            self._reply_json(503, {
                                "error": "fleet metrics disabled "
                                         "(Router(fleet_metrics=True))",
                            })
                        else:
                            self._reply(
                                200, agg.metrics_text().encode(),
                                content_type="text/plain; version=0.0.4",
                            )
                    elif self.path == "/fleet/stats":
                        agg = router._aggregator
                        if agg is None:
                            self._reply_json(503, {
                                "error": "fleet metrics disabled "
                                         "(Router(fleet_metrics=True))",
                            })
                        else:
                            st = agg.stats()
                            if router._alert_engine is not None:
                                st["slo"] = router._alert_engine.stats()
                            self._reply_json(200, st)
                    elif self.path == "/metrics":
                        self._reply(
                            200, router._registry.to_prometheus().encode(),
                            content_type="text/plain; version=0.0.4",
                        )
                    elif self.path == "/v1/models" or (
                        self.path.startswith(PREDICT_PREFIX)
                        and ":" not in self.path
                    ):
                        code, body = router._proxy_get(self.path)
                        self._reply(code, body)
                    else:
                        self._reply_json(
                            404, {"error": "no route %s" % self.path}
                        )
                except Exception as e:
                    self._reply_json(500, {"error": repr(e)})

            def do_POST(self):
                try:
                    body = self.rfile.read(
                        int(self.headers.get("Content-Length", 0))
                    )
                    if self.path.startswith("/fleet/"):
                        self._reply_json(*router._admin(self.path, body))
                        return
                    if not (self.path.startswith(PREDICT_PREFIX)
                            and (self.path.endswith(":predict")
                                 or self.path.endswith(":generate"))):
                        self._reply_json(
                            404, {"error": "no route %s" % self.path}
                        )
                        return
                    deadline = self.headers.get("X-Fleet-Deadline-S")
                    # adopt the client's trace context when it sent one;
                    # route() opens the root span either way
                    span = _tracing.tracer().start_span(
                        "router.http", parent=self.headers.get(TRACE_HEADER),
                        path=self.path,
                    )
                    try:
                        status, data, ctype = router.route(
                            self.path, body,
                            self.headers.get("Content-Type",
                                             "application/json"),
                            deadline_s=float(deadline) if deadline else None,
                            parent=span,
                        )
                    except Exception:
                        span.end("error")
                        raise
                    span.tag(code=status).end(
                        "ok" if status < 500 else "error"
                    )
                    self._reply(status, data, content_type=ctype,
                                trace=span.header())
                except Exception as e:
                    self._reply_json(500, {"error": repr(e)})

        self._httpd = ThreadingHTTPServer((self.host, self._port), Handler)
        self._httpd.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-router", daemon=True
        )
        self._http_thread.start()
        self._probe_stop.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="fleet-prober", daemon=True
        )
        self._probe_thread.start()
        self._start_fleet_observability()
        return self._httpd.server_address[1]

    def _scrape_targets(self):
        with self._lock:
            return {name: rep.url for name, rep in self._replicas.items()}

    def _start_fleet_observability(self):
        """Fleet aggregator + SLO engine, only when asked for. The scrape
        loop pulls every replica's /metrics plus this router's own
        registry; the alert engine evaluates after each scrape."""
        if not (self.fleet_metrics or self._slos or self._sentinels):
            return
        from ..observability.aggregate import FleetAggregator

        self._aggregator = FleetAggregator(
            targets=self._scrape_targets,
            local_registry=self._registry,
            local_name="router",
            interval_s=self.scrape_interval_s,
            timeout_s=min(self.attempt_timeout_s, 2.0),
        )
        if self._slos or self._sentinels:
            from ..observability.slo import DEFAULT_RULES, AlertEngine

            self._alert_engine = AlertEngine(
                slos=self._slos,
                history=self._aggregator,
                rules=self._alert_rules or DEFAULT_RULES,
                registry=self._registry,
                out_path=self._alerts_path,
            )
            for s in self._sentinels:
                self._alert_engine.add_sentinel(s)
            self._aggregator.add_listener(self._alert_engine.on_snapshot)
        self._aggregator.start()

    @property
    def aggregator(self):
        return self._aggregator

    @property
    def alert_engine(self):
        return self._alert_engine

    def _admin(self, path, body):
        """POST /fleet/register|deregister|drain handlers."""
        try:
            doc = json.loads(body.decode() or "{}")
        except ValueError as e:
            return 400, {"error": "bad payload: %r" % e}
        name = doc.get("name")
        if not name:
            return 400, {"error": 'body needs {"name": ...}'}
        if path == "/fleet/register":
            url = doc.get("url")
            if not url:
                return 400, {"error": 'register needs {"name", "url"}'}
            self.register(name, url)
            return 200, {"registered": name}
        if path == "/fleet/deregister":
            return 200, {"deregistered": self.deregister(name)}
        if path == "/fleet/drain":
            ok = self.drain(name, wait_s=float(doc.get("wait_s", 10.0)))
            return 200, {"drained": ok}
        return 404, {"error": "no route %s" % path}

    @property
    def port(self):
        return self._httpd.server_address[1] if self._httpd else self._port

    @property
    def url(self):
        return "http://%s:%d" % (self.host, self.port)

    def stop(self):
        agg, self._aggregator = self._aggregator, None
        if agg is not None:
            agg.stop()
        self._alert_engine = None
        self._probe_stop.set()
        t, self._probe_thread = self._probe_thread, None
        if t is not None:
            t.join(5.0)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._http_thread.join(10.0)
            self._httpd = None
