"""Fault-tolerant serving fleet (PR 16, docs/fleet.md).

A `Router` HTTP front end spreads predict/generate traffic across N replica
ModelServers with active+passive health tracking, per-replica circuit
breakers, deadline-bounded failover retries under the fleet retry budget,
tail-latency hedging for idempotent predicts, graceful drain, and a
staleness gate tied to the PR 15 online-learning repository (a replica is
routable only once it has landed AND acked the published model version).
`ReplicaProcess` spawns replicas as real subprocesses so SIGKILL chaos
(bench.py fleet, tests/test_fleet.py) exercises true process death.
"""

from .breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker
from .health import Replica
from .replica import ReplicaProcess
from .router import NoReplicaAvailable, RetryBudget, Router, UpstreamError

__all__ = [
    "CircuitBreaker",
    "CLOSED",
    "HALF_OPEN",
    "OPEN",
    "NoReplicaAvailable",
    "Replica",
    "ReplicaProcess",
    "RetryBudget",
    "Router",
    "UpstreamError",
]
