"""Gradient clipping as graph rewrites (reference python/paddle/fluid/clip.py:
ErrorClipByValue, GradientClipByValue, GradientClipByNorm,
GradientClipByGlobalNorm, set_gradient_clip, append_gradient_clip_ops)."""

from .framework import default_main_program
from .layer_helper import LayerHelper

__all__ = [
    "ErrorClipByValue",
    "GradientClipByValue",
    "GradientClipByNorm",
    "GradientClipByGlobalNorm",
    "set_gradient_clip",
]


class BaseErrorClipAttr:
    def _append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        if min is None:
            min = -max
        self.max, self.min = float(max), float(min)

    def _append_clip_op(self, block, grad_name):
        block.append_op(
            type="clip",
            inputs={"X": [grad_name]},
            outputs={"Out": [grad_name]},
            attrs={"min": self.min, "max": self.max},
        )


def error_clip_callback(block, context):  # registered via Optimizer.backward
    pass


class BaseGradientClipAttr:
    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def _create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        if min is None:
            min = -max
        self.max, self.min = float(max), float(min)

    def _create_operators(self, param, grad):
        helper = LayerHelper("gradient_clip")
        helper.append_op(
            type="clip",
            inputs={"X": [grad.name]},
            outputs={"Out": [grad.name]},
            attrs={"min": self.min, "max": self.max},
        )
        return param, grad


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _create_operators(self, param, grad):
        helper = LayerHelper("gradient_clip")
        helper.append_op(
            type="clip_by_norm",
            inputs={"X": [grad.name]},
            outputs={"Out": [grad.name]},
            attrs={"max_norm": self.clip_norm},
        )
        return param, grad


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """scale = clip_norm / max(global_norm, clip_norm), applied to every grad
    (reference clip.py:GradientClipByGlobalNorm — built from square/reduce_sum/
    sum/sqrt/elementwise ops so it fuses into the step's XLA module)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process_context(self, context, param, grad):
        if self.group_name not in context:
            context[self.group_name] = []
            context[self.group_name + "_clip_norm"] = self.clip_norm
        from .layers import nn

        sq = nn.reduce_sum(_square(grad))
        context[self.group_name].append(sq)
        self.context = context

    def _create_operators(self, param, grad):
        from .layers import nn, ops, tensor

        group_scale_name = self.group_name + "_scale"
        if group_scale_name not in self.context:
            group_norm = tensor.sums(self.context[self.group_name])
            group_norm = ops.sqrt(group_norm)
            clip_var = tensor.fill_constant(
                shape=[1], dtype=group_norm.dtype, value=self.clip_norm
            )
            scale = nn.elementwise_div(
                x=clip_var, y=nn.elementwise_max(x=clip_var, y=group_norm)
            )
            self.context[group_scale_name] = scale
        helper = LayerHelper("gradient_clip")
        helper.append_op(
            type="elementwise_mul",
            inputs={"X": [grad.name], "Y": [self.context[group_scale_name].name]},
            outputs={"Out": [grad.name]},
            attrs={"axis": -1},
        )
        return param, grad


def _square(x):
    helper = LayerHelper("square")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="square", inputs={"X": [x.name]}, outputs={"Out": [out.name]})
    return out


_gradient_clip_attr = None


def set_gradient_clip(clip, param_list=None, program=None):
    """Global or per-param clip attr (reference clip.py:set_gradient_clip)."""
    global _gradient_clip_attr
    if param_list:
        for p in param_list:
            if isinstance(p, str):
                p = default_main_program().global_block().var(p)
            p.gradient_clip_attr = clip
    else:
        _gradient_clip_attr = clip


def append_gradient_clip_ops(param_grads):
    context = {}
    clips = []
    program = default_main_program()
    # SelectedRows (sparse) grads pass through unclipped: the clip ops are
    # dense rewrites, and norm-clipping a fixed-capacity values array with
    # duplicate rows would mis-measure the true gradient anyway (the
    # reference's ClipGradByGlobalNorm likewise ignored SelectedRows)
    dense = [
        pg
        for pg in param_grads
        if pg[1] is not None and not getattr(pg[1], "is_selected_rows", False)
    ]
    for p, g in dense:
        with program._optimized_guard([p, g]):
            clip_attr = getattr(p, "gradient_clip_attr", None) or _gradient_clip_attr
            if clip_attr is None:
                clip_attr = NullGradientClipAttr()
            clip_attr._process_context(context=context, param=p, grad=g)
            clips.append(clip_attr)

    res = []
    for (p, g), clip_attr in zip(dense, clips):
        with program._optimized_guard([p, g]):
            res.append(clip_attr._create_operators(param=p, grad=g))
    for p, g in param_grads:
        if g is None or getattr(g, "is_selected_rows", False):
            res.append((p, g))
    return res
