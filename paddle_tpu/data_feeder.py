"""DataFeeder: convert reader samples (tuples of numpy/lists) into feed dicts
(reference python/paddle/fluid/data_feeder.py). LoD (ragged) fields are padded
dense with a companion `<name>@LEN` length vector — the TPU-native stand-in
for LoDTensor (SURVEY.md §5.7: LoD → ragged/segment-id representations)."""

import numpy as np

from . import framework
from .framework import Variable

__all__ = ["DataFeeder"]


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_vars = []
        program = program or framework.default_main_program()
        for v in feed_list:
            if isinstance(v, str):
                v = program.global_block().var(v)
            self.feed_vars.append(v)
        self.place = place

    def feed(self, iterable):
        """iterable of sample tuples → {name: batch array} (+ @LEN for ragged
        fields)."""
        columns = [[] for _ in self.feed_vars]
        for sample in iterable:
            assert len(sample) == len(self.feed_vars), (
                "sample arity %d != feed arity %d" % (len(sample), len(self.feed_vars))
            )
            for c, val in zip(columns, sample):
                c.append(np.asarray(val))
        out = {}
        for var, col in zip(self.feed_vars, columns):
            if var.lod_level and var.lod_level > 0:
                lens = np.asarray([len(x) for x in col], dtype=np.int32)
                maxlen = max(int(lens.max()), 1)
                sample_shape = col[0].shape[1:] if col[0].ndim > 1 else ()
                batch = np.zeros(
                    (len(col), maxlen) + tuple(sample_shape),
                    dtype=np.dtype(var.dtype) if var.dtype != "bfloat16" else np.float32,
                )
                for i, x in enumerate(col):
                    batch[i, : len(x)] = x
                # fluid convention: ragged int fields are (..., 1) shaped
                if var.shape and batch.ndim < len(var.shape) + 1:
                    batch = batch[..., None]
                out[var.name] = batch
                out[var.name + "@LEN"] = lens
            else:
                batch = np.stack(col)
                want_rank = len(var.shape) if var.shape else batch.ndim
                # fluid convention: scalar-ish fields get a trailing unit dim
                if batch.ndim == want_rank - 1:
                    batch = batch[..., None]
                out[var.name] = batch
        return out

    def feed_parallel(self, iterable, num_places=None):
        """reference data_feeder.py feed_parallel — returns one merged feed
        (our ParallelExecutor takes the global batch and shards it)."""
        return self.feed(iterable)
