"""InferenceTranspiler: fold batch-norm into conv weights for serving.

DEPRECATED SHIM — the rewrite now lives in the pass framework as
passes/ports.py `fold_batch_norm` (run it via
`passes.apply_inplace(program, ["fold_batch_norm"], scope=scope)` or any
pipeline spec); this class is kept as the reference-compatible entry point
(python/paddle/fluid/transpiler/inference_transpiler.py) and delegates.

Reference analog + arithmetic (now in FoldBatchNormPass): conv+bn fusion
    W' = W * gamma / sqrt(var + eps)        (per output channel)
    b' = (b - mean) * gamma / sqrt(var + eps) + beta
for conv2d → batch_norm and conv2d → elementwise_add → batch_norm patterns;
the conv+relu/conv+elementwise_add MKLDNN fusions remain XLA's job
(documented no-ops).
"""

__all__ = ["InferenceTranspiler"]


class InferenceTranspiler:
    def transpile(self, program, place=None, scope=None):
        """Rewrite `program` in place; `scope` must hold the trained params
        (reference signature transpile(program, place, scope)). Deprecated:
        delegates to the `fold_batch_norm` pass."""
        from ..executor import global_scope
        from ..passes import apply_inplace

        scope = scope or global_scope()
        apply_inplace(program, ["fold_batch_norm"], scope=scope)
