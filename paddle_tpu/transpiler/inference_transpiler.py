"""InferenceTranspiler: fold batch-norm into conv weights for serving.

Reference analog: python/paddle/fluid/transpiler/inference_transpiler.py —
its two rewrites are conv+bn fusion (fuse_batch_norm) and conv+relu/
conv+elementwise_add fusion (MKLDNN-only). On TPU, elementwise fusion is XLA's
job (those passes are documented no-ops), but conv+bn folding is still a real
win for inference: it removes the bn op and its four state tensors entirely by
rewriting the conv weights in the scope —
    W' = W * gamma / sqrt(var + eps)        (per output channel)
    b' = (b - mean) * gamma / sqrt(var + eps) + beta
exactly the reference's _fuse_param arithmetic (inference_transpiler.py).
Patterns handled: conv2d → batch_norm and conv2d → elementwise_add →
batch_norm (bias as a separate add, which is how layers.conv2d builds it).
The bn op is replaced by / merged into an elementwise_add carrying b'.
"""

import numpy as np

from ..framework import Operator, OpRole

__all__ = ["InferenceTranspiler"]


class InferenceTranspiler:
    def transpile(self, program, place=None, scope=None):
        """Rewrite `program` in place; `scope` must hold the trained params
        (reference signature transpile(program, place, scope))."""
        from ..executor import global_scope

        scope = scope or global_scope()
        self._fuse_batch_norm(program, scope)

    # ------------------------------------------------------------------ #
    def _fuse_batch_norm(self, program, scope):
        block = program.global_block()
        i = 0
        while i < len(block.ops):
            trio = self._match(block, i)
            if trio is None:
                i += 1
                continue
            conv_op, add_op, bn_op = trio
            self._fold(block, scope, conv_op, add_op, bn_op)
            program._bump_version()
            # re-scan from the conv (list indices shifted)
            i = block.ops.index(conv_op)
            i += 1

    def _match(self, block, i):
        """Return (conv, add_or_None, bn) rooted at op i, else None."""
        ops = block.ops
        op = ops[i]
        if op.type not in ("conv2d", "depthwise_conv2d") or not op.output("Output"):
            return None
        out = op.output("Output")[0]
        users = [o for o in ops if out in o.input_arg_names]
        if len(users) != 1:
            return None
        nxt = users[0]
        add_op = None
        if nxt.type == "elementwise_add" and nxt.input("X") == [out]:
            add_out = nxt.output("Out")[0]
            users2 = [o for o in ops if add_out in o.input_arg_names]
            if len(users2) != 1:
                return None
            add_op, nxt = nxt, users2[0]
        if nxt.type == "batch_norm" and nxt.attrs.get("is_test", False):
            return (op, add_op, nxt)
        return None

    @staticmethod
    def _fold(block, scope, conv_op, add_op, bn_op):
        import jax.numpy as jnp

        w_name = conv_op.input("Filter")[0]
        gamma = np.asarray(scope.find_var(bn_op.input("Scale")[0]))
        beta = np.asarray(scope.find_var(bn_op.input("Bias")[0]))
        mean = np.asarray(scope.find_var(bn_op.input("Mean")[0]))
        var = np.asarray(scope.find_var(bn_op.input("Variance")[0]))
        eps = float(bn_op.attrs.get("epsilon", 1e-5))
        std_inv = gamma / np.sqrt(var + eps)

        w = np.asarray(scope.find_var(w_name), dtype=np.float32)
        # conv filter layout (out_c, in_c, kh, kw): scale per out channel
        w = w * std_inv.reshape((-1,) + (1,) * (w.ndim - 1))
        scope.set_var(w_name, jnp.asarray(w))

        bn_out = bn_op.output("Y")[0]
        if add_op is not None:
            # existing bias: b' = (b - mean) * std_inv + beta
            b_name = add_op.input("Y")[0]
            b = np.asarray(scope.find_var(b_name), dtype=np.float32)
            scope.set_var(b_name, jnp.asarray((b - mean) * std_inv + beta))
            add_op.outputs["Out"] = [bn_out]
        else:
            # no bias add: introduce one carrying the folded shift
            b_name = w_name + ".bn_bias"
            block.create_var(
                name=b_name, shape=(len(beta),), dtype="float32", persistable=True
            )
            scope.set_var(b_name, jnp.asarray(beta - mean * std_inv))
            conv_out = conv_op.output("Output")[0]
            idx = block.ops.index(bn_op)
            block.ops[idx] = Operator(
                block,
                "elementwise_add",
                inputs={"X": [conv_out], "Y": [b_name]},
                outputs={"Out": [bn_out]},
                attrs={"axis": 1, OpRole.OP_ROLE_KEY: OpRole.Forward},
            )
            return
        # drop the bn op (its output now produced by the add)
        block.ops.remove(bn_op)
