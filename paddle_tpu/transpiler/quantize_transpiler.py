"""QuantizeTranspiler: quantization-aware training + int8 freeze.

Reference analog: python/paddle/fluid/contrib/quantize/quantize_transpiler.py:81
— training_transpile inserts fake_quantize ops on the inputs of quantizable
ops (mul, conv2d, depthwise_conv2d) and fake_dequantize after them;
freeze_program converts weights to real int8 for serving. Gradient flow is
straight-through (quant_ops.py registers identity grads), matching the
reference's backward rewrite.

TPU-native note: simulated-quant values stay float on device (the MXU computes
in bf16/f32 regardless), so QAT here is about matching serving-time rounding,
and freeze packs int8 weights for the serving artifact.
"""

import numpy as np

from .. import framework
from ..framework import Operator, OpRole
from ..ops.quant_ops import _quant_levels

__all__ = ["QuantizeTranspiler"]

_QUANTIZABLE = ("mul", "conv2d", "depthwise_conv2d")
_QUANT_SLOTS = {"mul": ("X", "Y"), "conv2d": ("Input", "Filter"),
                "depthwise_conv2d": ("Input", "Filter")}


class QuantizeTranspiler:
    def __init__(
        self,
        weight_bits=8,
        activation_bits=8,
        activation_quantize_type="abs_max",
        weight_quantize_type="abs_max",
        window_size=10000,
    ):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.act_type = activation_quantize_type
        self.weight_type = weight_quantize_type
        self.window_size = window_size

    # ------------------------------------------------------------------ #
    def training_transpile(self, program=None, startup_program=None):
        """Insert fake quant/dequant around every quantizable op, in place."""
        program = program or framework.default_main_program()
        block = program.global_block()
        quantized = {}  # var name -> (quantized var, scale var)
        new_ops = []
        for op in block.ops:
            role = op.attrs.get(OpRole.OP_ROLE_KEY, OpRole.Forward)
            if op.type in _QUANTIZABLE and not (role & OpRole.Backward):
                scales = []
                for slot in _QUANT_SLOTS[op.type]:
                    names = op.input(slot)
                    if not names:
                        continue
                    name = names[0]
                    if name not in quantized:
                        q, s, qops = self._insert_quant(block, name)
                        quantized[name] = (q, s)
                        new_ops.extend(qops)
                    q, s = quantized[name]
                    op.inputs[slot] = [q]
                    scales.append(s)
                new_ops.append(op)
                # dequantize the output with the product of input scales
                out_slot = "Out" if op.type == "mul" else "Output"
                out = op.output(out_slot)[0]
                deq, dops = self._insert_dequant(block, out, scales)
                op.outputs[out_slot] = [out + ".quantized"]
                new_ops.extend(dops)
            else:
                new_ops.append(op)
        block.ops = new_ops
        program._bump_version()

    def _insert_quant(self, block, name):
        v = block._var_recursive(name)
        q = block.create_var(
            name=name + ".quantized", shape=v.shape, dtype=v.dtype
        )
        s = block.create_var(name=name + ".scale", shape=(1,), dtype="float32")
        op = Operator(
            block,
            "fake_quantize_abs_max",
            inputs={"X": [name]},
            outputs={"Out": [q.name], "OutScale": [s.name]},
            attrs={"bit_length": self.activation_bits,
                   OpRole.OP_ROLE_KEY: OpRole.Forward},
        )
        return q.name, s.name, [op]

    def _insert_dequant(self, block, out, scale_names):
        v = block._var_recursive(out)
        qout = block.create_var(
            name=out + ".quantized", shape=v.shape, dtype=v.dtype
        )
        ops = []
        src = qout.name
        # chain a dequant per input scale: x * (s1/r) * (s2/r) — the
        # reference folds the product the same way for mul/conv
        max_range = _quant_levels(self.activation_bits)
        for i, s in enumerate(scale_names):
            dst = out if i == len(scale_names) - 1 else block.create_var(
                name="%s.deq%d" % (out, i), shape=v.shape, dtype=v.dtype
            ).name
            ops.append(
                Operator(
                    block,
                    "fake_dequantize_max_abs",
                    inputs={"X": [src], "Scale": [s]},
                    outputs={"Out": [dst]},
                    attrs={"max_range": max_range,
                           OpRole.OP_ROLE_KEY: OpRole.Forward},
                )
            )
            src = dst
        return out, ops

    # ------------------------------------------------------------------ #
    def freeze_program(self, program, scope=None):
        """For serving: bake weight quantization into int8 arrays stored on
        the weight vars (reference freeze_program). The program keeps
        dequantize ops fed by constant per-weight scales."""
        from ..executor import global_scope

        scope = scope or global_scope()
        import jax.numpy as jnp

        block = program.global_block()
        levels = _quant_levels(self.weight_bits)
        frozen = {}
        keep_ops = []
        rename = {}  # old input name -> replacement
        for op in block.ops:
            if op.type == "fake_quantize_abs_max":
                src = op.input("X")[0]
                v = block.vars.get(src)
                if v is not None and isinstance(v, framework.Parameter):
                    w = np.asarray(scope.find_var(src), dtype=np.float32)
                    scale = float(np.max(np.abs(w))) or 1.0
                    qw = np.clip(
                        np.round(w / scale * levels), -levels, levels
                    ).astype(np.int8)
                    frozen[src] = (qw, scale)
                    # weight now holds the quantized levels as float (serving
                    # math identical to int8 × scale); scale becomes a frozen
                    # persistable const the dequant op reads
                    scope.set_var(src, jnp.asarray(qw.astype(np.float32)))
                    sname = src + ".scale.frozen"
                    block.create_var(
                        name=sname, shape=(1,), dtype="float32", persistable=True
                    )
                    scope.set_var(sname, jnp.asarray([scale], jnp.float32))
                    rename[op.output("Out")[0]] = src
                    rename[op.output("OutScale")[0]] = sname
                    continue  # drop the quantize op
            keep_ops.append(op)
        for op in keep_ops:
            for slot, names in op.inputs.items():
                op.inputs[slot] = [rename.get(n, n) for n in names]
        block.ops = keep_ops
        program._bump_version()
        program._quantized_weights = frozen  # int8 payloads for export
        return frozen

    # ------------------------------------------------------------------ #
    def convert_to_int8(self, program, scope=None):
        """Serving on real int8: after freeze_program, re-type the frozen
        weights to int8 in scope, swap activation quantize ops to the
        int8-emitting `quantize_abs_max`, and swap mul/conv2d over quantized
        operands to `int8_mul`/`int8_conv2d` (int8×int8→int32 on the MXU —
        measured 383 TOPS vs 192 bf16 TF/s on the bench chip). The reference's
        convert_to_int8 (contrib quantize_transpiler.py:236) stops at weight
        re-typing because its int8 kernels live in MKL-DNN; here the program
        itself carries the int8 compute. The fake_dequantize chain is
        unchanged: int8 ops emit f32 level-products with identical numerics.

        Deployment guidance (measured, bench chip): pays off on
        matmul-dominated serving (raw int8 matmul ≈ 2× bf16); does NOT pay on
        bandwidth-bound CNNs — ResNet-50 bs=128 inference measured 4.3k img/s
        int8 vs 6.7k bf16, because the per-layer activation quant/dequant
        passes add elementwise HBM traffic exceeding the conv speedup."""
        from ..executor import global_scope

        import jax.numpy as jnp

        scope = scope or global_scope()
        block = program.global_block()
        frozen = getattr(program, "_quantized_weights", None)
        if not frozen:
            raise ValueError("convert_to_int8 requires freeze_program first")

        for name, (qw, _scale) in frozen.items():
            scope.set_var(name, jnp.asarray(qw))  # int8 payload on device
            v = block.vars.get(name)
            if v is not None:
                v.dtype = "int8"

        _INT8 = {"mul": "int8_mul", "conv2d": "int8_conv2d",
                 "depthwise_conv2d": "int8_conv2d"}
        quantized_outs = set()
        for op in block.ops:
            if op.type == "fake_quantize_abs_max":
                op.type = "quantize_abs_max"
                quantized_outs.update(op.output("Out"))
                ov = block.vars.get(op.output("Out")[0])
                if ov is not None:
                    ov.dtype = "int8"
            elif op.type in _INT8:
                ins = [n for names in op.inputs.values() for n in names]
                if any(n in quantized_outs or n in frozen for n in ins):
                    op.type = _INT8[op.type]
        program._bump_version()
        return program
