"""Gradient merging / batch-merge transpile.

Reference analog: framework/ir/multi_batch_merge_pass.cc (repeats the
forward/backward sub-graph k times and averages gradients before one
optimizer step; driven by test_dist_mnist_batch_merge.py to train with an
effective batch k× the device batch).

TPU-first redesign: instead of cloning the fwd/bwd graph k times (k× the HLO,
k× the compile time), the program keeps ONE fwd/bwd and the optimizer tier is
made conditional: every step accumulates the gradient into a persistent
buffer; every k-th step the optimizer ops run on the averaged accumulator
(inside a conditional_block → XLA cond) and the buffers reset. Numerically
identical to the reference pass for linear optimizers over the k
micro-batches, with O(1) program size.
"""

import numpy as np

from .. import framework
from ..framework import OpRole
from .distribute_transpiler import OPTIMIZER_OP_TYPES

__all__ = ["gradient_merge_transpile"]


def gradient_merge_transpile(main_program, startup_program, k_steps, avg=True):
    """Rewrite main_program in place. Returns the accumulation counter var.

    Must run AFTER optimizer.minimize() (it rewrites the Optimize-role ops).
    """
    if k_steps < 1:
        raise ValueError("k_steps must be >= 1")
    block = main_program.global_block()
    sblock = startup_program.global_block()

    opt_idx = [
        i
        for i, op in enumerate(block.ops)
        if op.type in OPTIMIZER_OP_TYPES
        and int(op.attrs.get(OpRole.OP_ROLE_KEY, 0)) & int(OpRole.Optimize)
    ]
    if not opt_idx:
        raise ValueError("no optimizer ops found; call minimize() first")
    first_opt = opt_idx[0]

    def persistent_zero(name, shape, dtype):
        v = block.create_var(
            name=name, shape=shape, dtype=dtype, persistable=True
        )
        sblock.create_var(name=name, shape=shape, dtype=dtype, persistable=True)
        sblock.append_op(
            type="fill_constant",
            inputs={},
            outputs={"Out": [name]},
            attrs={"shape": list(shape), "dtype": dtype, "value": 0.0},
        )
        return v

    # step counter + "apply now" condition, computed before the optimizer tier
    step = persistent_zero("@GRAD_MERGE@.step", [1], "int64")
    cond_name = "@GRAD_MERGE@.cond"
    block.create_var(name=cond_name, shape=[1], dtype="bool")
    new_head = []

    def op_spec(type, inputs, outputs, attrs):
        attrs = dict(attrs)
        attrs[OpRole.OP_ROLE_KEY] = OpRole.Optimize
        return dict(type=type, inputs=inputs, outputs=outputs, attrs=attrs)

    new_head.append(
        op_spec(
            "increment",
            {"X": [step.name]},
            {"Out": [step.name]},
            {"step": 1.0},
        )
    )
    mod_name = "@GRAD_MERGE@.step_mod"
    block.create_var(name=mod_name, shape=[1], dtype="int64")
    kname = "@GRAD_MERGE@.k"
    block.create_var(name=kname, shape=[1], dtype="int64")
    new_head.append(
        op_spec(
            "fill_constant",
            {},
            {"Out": [kname]},
            {"shape": [1], "dtype": "int64", "value": float(k_steps)},
        )
    )
    new_head.append(
        op_spec(
            "elementwise_mod",
            {"X": [step.name], "Y": [kname]},
            {"Out": [mod_name]},
            {},
        )
    )
    zero_name = "@GRAD_MERGE@.zero"
    block.create_var(name=zero_name, shape=[1], dtype="int64")
    new_head.append(
        op_spec(
            "fill_constant",
            {},
            {"Out": [zero_name]},
            {"shape": [1], "dtype": "int64", "value": 0.0},
        )
    )
    new_head.append(
        op_spec(
            "equal",
            {"X": [mod_name], "Y": [zero_name]},
            {"Out": [cond_name]},
            {},
        )
    )

    # Every Optimize-role op from the first optimizer op onward moves into
    # the conditional sub-block — not just OPTIMIZER_OP_TYPES. Adam/Adamax
    # _finish_update emits `scale` ops advancing Beta{1,2}Pow after the
    # optimizer tier; leaving those outside would advance bias-correction
    # state every micro-step (k× too fast).
    moved_idx = [
        i
        for i, op in enumerate(block.ops)
        if i >= first_opt
        and int(op.attrs.get(OpRole.OP_ROLE_KEY, 0)) & int(OpRole.Optimize)
    ]
    moved_set = set(moved_idx)
    moved_ops = [block.ops[i] for i in moved_idx]
    opt_ops = [op for op in moved_ops if op.type in OPTIMIZER_OP_TYPES]
    grads = []
    accum_of = {}
    for op in opt_ops:
        for gname in op.inputs.get("Grad", []):
            if gname in accum_of:
                continue
            gvar = block._var_recursive(gname)
            aname = gname + "@MERGED"
            persistent_zero(aname, [d if d != -1 else 1 for d in (gvar.shape or [1])], gvar.dtype or "float32")
            accum_of[gname] = aname
            grads.append(gname)
            new_head.append(
                op_spec(
                    "sum",
                    {"X": [aname, gname]},
                    {"Out": [aname]},
                    {},
                )
            )

    # build the conditional optimizer sub-block
    sub = main_program._create_block()
    scale = 1.0 / k_steps if avg else 1.0
    written = []
    for op in moved_ops:
        new_inputs = {}
        for slot, names in op.inputs.items():
            if slot == "Grad" and op.type in OPTIMIZER_OP_TYPES:
                scaled = []
                for gname in names:
                    aname = accum_of[gname]
                    s_name = aname + ".scaled"
                    if not sub.has_var(s_name):
                        # one scale per accumulator even when several
                        # optimizer ops consume the same gradient
                        sub.create_var(name=s_name, shape=None, dtype=None)
                        sub.append_op(
                            type="scale",
                            inputs={"X": [aname]},
                            outputs={"Out": [s_name]},
                            attrs={"scale": scale},
                        )
                    scaled.append(s_name)
                new_inputs[slot] = scaled
            else:
                new_inputs[slot] = list(names)
        sub.append_op(
            type=op.type,
            inputs=new_inputs,
            outputs={k: list(v) for k, v in op.outputs.items()},
            attrs={
                k: v
                for k, v in op.attrs.items()
                if k != OpRole.OP_ROLE_KEY
            },
        )
        for names in op.outputs.values():
            written.extend(names)
    # reset accumulators inside the apply branch
    for gname in grads:
        aname = accum_of[gname]
        gvar = block._var_recursive(gname)
        sub.append_op(
            type="fill_zeros_like",
            inputs={"X": [aname]},
            outputs={"Out": [aname]},
            attrs={},
        )
        written.append(aname)
    main_program._rollback()

    written = sorted(set(written))
    # closure of names the sub-block reads from the outer scope; written
    # names must ride in X too — conditional_block takes their prior values
    # from the same env for the not-taken branch
    x_names = sorted(
        {
            n
            for op in sub.ops
            for n in op.input_arg_names
            if not sub.has_var(n)
        }
        | set(written)
    )
    cond_spec = op_spec(
        "conditional_block",
        {"Cond": [cond_name], "X": x_names},
        {"Out": written},
        {
            "sub_block": sub,
            "x_names": x_names,
            "written_names": written,
            "is_scalar_condition": True,
        },
    )

    # splice: [fwd+bwd ops] + new_head + [conditional apply] (+ any trailing
    # non-optimizer ops that followed the optimizer tier)
    # LRSched-role ops (per-param LR scale from _create_param_lr) sit
    # interleaved with the optimizer tier and produce the LearningRate vars
    # the moved optimizer ops read — they must run BEFORE the conditional.
    # Everything else non-Optimize stays after it.
    lr_ops, tail = [], []
    for i, op in enumerate(block.ops):
        if i < first_opt or i in moved_set:
            continue
        role = int(op.attrs.get(OpRole.OP_ROLE_KEY, 0))
        (lr_ops if role & OpRole.LRSched else tail).append(op)
    del block.ops[first_opt:]
    block.ops.extend(lr_ops)
    for spec in new_head:
        block.append_op(**spec)
    block.append_op(**cond_spec)
    block.ops.extend(tail)
    main_program._bump_version()
    return step
