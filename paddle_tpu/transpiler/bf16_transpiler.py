"""Bf16Transpiler: convert an inference program to bfloat16.

Reference analog: paddle/contrib/float16/float16_transpiler.py — rewrites an
inference ProgramDesc to fp16: casts weights, inserts cast ops at feed/fetch
boundaries, keeps blacklisted ops in fp32. The TPU redesign targets bfloat16
(the MXU's native type — no loss-scaling needed thanks to fp32-equal exponent
range), and is far simpler: var dtypes flip to bf16, scope weights are cast
once, and a blacklist keeps numerically-sensitive ops (softmax, cross_entropy,
batch/layer-norm statistics) computing in f32 via cast-in/cast-out — the same
mixed-precision recipe XLA's bf16 auto-promotion uses.
"""

import numpy as np

from ..framework import Operator, OpRole, is_float_dtype

__all__ = ["Bf16Transpiler", "Float16Transpiler"]

# ops whose math stays f32 (reference float16_transpiler black_list analog)
_DEFAULT_BLACKLIST = frozenset(
    [
        "softmax",
        "softmax_with_cross_entropy",
        "cross_entropy",
        "log_softmax",
        "batch_norm",
        "layer_norm",
        "mean",
        "accuracy",
        "auc",
        "top_k",
    ]
)


class Bf16Transpiler:
    def __init__(self, blacklist=None):
        self.blacklist = frozenset(blacklist) if blacklist is not None else _DEFAULT_BLACKLIST

    def transpile(self, program, place=None, scope=None):
        """In place: flip float32 vars to bfloat16, cast scope params, wrap
        blacklisted ops with casts. Feeds are auto-cast by the executor
        (feed dtype follows var dtype, executor.py _as_feed_array)."""
        import jax.numpy as jnp

        from ..executor import global_scope

        scope = scope or global_scope()
        block = program.global_block()

        flipped = set()
        for name, v in block.vars.items():
            if v.dtype == "float32":
                v.dtype = "bfloat16"
                flipped.add(name)
                val = scope.find_var(name)
                if val is not None and v.persistable:
                    scope.set_var(name, jnp.asarray(val, jnp.bfloat16))

        # blacklisted ops compute in f32: cast inputs up, outputs back down
        new_ops = []
        for op in block.ops:
            if op.type in self.blacklist:
                for slot, names in list(op.inputs.items()):
                    cast_names = []
                    for n in names:
                        if n in flipped:
                            f32 = n + ".f32"
                            if not block.has_var(f32):
                                v = block.var(n)
                                block.create_var(
                                    name=f32, shape=v.shape, dtype="float32"
                                )
                            new_ops.append(
                                Operator(
                                    block,
                                    "cast",
                                    inputs={"X": [n]},
                                    outputs={"Out": [f32]},
                                    attrs={
                                        "in_dtype": "bfloat16",
                                        "out_dtype": "float32",
                                        OpRole.OP_ROLE_KEY: OpRole.Forward,
                                    },
                                )
                            )
                            cast_names.append(f32)
                        else:
                            cast_names.append(n)
                    op.inputs[slot] = cast_names
                # the op computes in f32: route each flipped output through an
                # f32 temp, then cast back down so downstream ops see the bf16
                # value their var annotation promises (without this, f32
                # silently propagates through the rest of the network)
                post_casts = []
                for slot, names in list(op.outputs.items()):
                    out_names = []
                    for out in names:
                        if out in flipped:
                            f32 = out + ".f32out"
                            if not block.has_var(f32):
                                v = block.var(out)
                                block.create_var(
                                    name=f32, shape=v.shape, dtype="float32"
                                )
                            post_casts.append(
                                Operator(
                                    block,
                                    "cast",
                                    inputs={"X": [f32]},
                                    outputs={"Out": [out]},
                                    attrs={
                                        "in_dtype": "float32",
                                        "out_dtype": "bfloat16",
                                        OpRole.OP_ROLE_KEY: OpRole.Forward,
                                    },
                                )
                            )
                            out_names.append(f32)
                        else:
                            out_names.append(out)
                    op.outputs[slot] = out_names
                new_ops.append(op)
                new_ops.extend(post_casts)
                continue
            new_ops.append(op)
        block.ops = new_ops
        program._bump_version()
        return program


# fp16 never wins on TPU (no fast fp16 path; bf16 is native) — keep the
# reference's class name as an alias targeting bf16.
Float16Transpiler = Bf16Transpiler
