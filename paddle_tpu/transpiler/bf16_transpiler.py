"""Bf16Transpiler: convert a program to bfloat16 mixed precision.

Reference analog: paddle/contrib/float16/float16_transpiler.py — rewrites an
inference ProgramDesc to fp16: casts weights, inserts cast ops at feed/fetch
boundaries, keeps blacklisted ops in fp32. The TPU redesign targets bfloat16
(the MXU's native type — no loss-scaling needed thanks to fp32-equal exponent
range) and distinguishes two modes:

**Freeze mode** (no optimizer ops in the program — inference): the
reference's recipe. Var dtypes flip to bf16, scope weights are cast once,
and a blacklist keeps numerically-sensitive ops (softmax, cross_entropy,
batch/layer-norm statistics) computing in f32 via cast-in/cast-out.

**Train mode** (optimizer-role ops present): the standard TPU mixed-precision
recipe (master weights). Persistable vars — parameters, optimizer moments,
BN statistics, learning rate — KEEP float32; one `w@BF16` cast per step
feeds every forward/backward matmul; activations and gradients are bf16;
optimizer updates read/write the f32 masters (their lowerings compute in f32
and cast outputs back, ops/core_ops.py `_opt_f32`). Blacklisted ops AND
their `_grad` twins are f32 islands: inputs cast up, flipped outputs cast
back down — so e.g. softmax_with_cross_entropy's backward emits a bf16
logits-gradient instead of silently pushing f32 into every downstream
matmul. The round-4 per-HLO audit (PROFILE.md) measured f32-operand matmuls
at 81-131 TF/s vs 188 TF/s for bf16×bf16 on the bench chip — dtype
discipline on the backward path is worth ~25% of the whole train step.
"""

from ..framework import Operator, OpRole

__all__ = ["Bf16Transpiler", "Float16Transpiler"]

# ops whose math stays f32 (reference float16_transpiler black_list analog)
_DEFAULT_BLACKLIST = frozenset(
    [
        "softmax",
        "softmax_with_cross_entropy",
        "cross_entropy",
        "log_softmax",
        "batch_norm",
        "layer_norm",
        "mean",
        "accuracy",
        "auc",
        "top_k",
    ]
)

# train mode: gather-like ops consume the f32 master table directly (casting
# a whole embedding table to bf16 per step to gather a few rows would be
# pure waste); their outputs cast down like blacklist islands
_TRAIN_ISLANDS = frozenset(["lookup_table"])

# train mode: ops whose lowerings accumulate in f32 internally while keeping
# the big tensors in the input dtype (core_ops.py softmax_with_cross_entropy
# + its closed-form grad) — islanding them would only materialize f32 copies
# of bf16 [N, vocab] tensors in HBM for no numeric gain. layer_norm /
# batch_norm deliberately STAY islanded: un-islanding them let XLA duplicate
# their (recomputed) bodies into every consumer fusion, which measured
# SLOWER than the island casts (round-4 audit: +0.6 ms on each of 17
# per-layer dW+Adam fusions).
# exact-type member: lookup_table_grad stays bf16 (its explicit lowering
# scatters in the cotangent dtype and reads the master table for shape
# only) while the lookup_table FORWARD stays an island (it reads the f32
# master rows directly — casting the whole table down per step to gather a
# few rows would be pure waste)
_TRAIN_KEEP_BF16 = frozenset(["softmax_with_cross_entropy", "lookup_table_grad"])


def _role(op):
    try:
        return int(op.attrs.get(OpRole.OP_ROLE_KEY, 0))
    except (TypeError, ValueError):
        return 0


class Bf16Transpiler:
    def __init__(self, blacklist=None):
        self.blacklist = (
            frozenset(blacklist) if blacklist is not None else _DEFAULT_BLACKLIST
        )

    def transpile(self, program, place=None, scope=None):
        """In place. Train mode when the program carries optimizer-role ops,
        else freeze mode (see module docstring). Feeds are auto-cast by the
        executor (feed dtype follows var dtype, executor.py _as_feed_array)."""
        has_opt = any(
            _role(op) & OpRole.Optimize
            for blk in program.blocks
            for op in blk.ops
        )
        if has_opt:
            self._transpile_train(program)
        else:
            self._transpile_freeze(program, scope)
        program._bump_version()
        return program

    # -- shared -----------------------------------------------------------

    def _is_island(self, op_type, extra=frozenset(), keep=frozenset()):
        if op_type in keep:  # exact-type keeps override the base-name rule
            return False
        base = op_type[:-5] if op_type.endswith("_grad") else op_type
        return base not in keep and (base in self.blacklist or base in extra)

    def _wrap_islands(self, block, flipped, extra=frozenset(), keep=frozenset()):
        """Cast-wrap island ops in `block`: flipped inputs cast up to f32,
        flipped outputs routed through an f32 temp then cast back down (so
        downstream ops see the bf16 value their var annotation promises)."""
        new_ops = []
        for op in block.ops:
            if not self._is_island(op.type, extra, keep):
                new_ops.append(op)
                continue
            for slot, names in list(op.inputs.items()):
                cast_names = []
                for n in names:
                    if n in flipped:
                        f32 = n + ".f32"
                        if not block.has_var(f32):
                            # flipped var may live in an ancestor block
                            # (island op inside a while/cond sub-block)
                            v = block._var_recursive(n)
                            block.create_var(
                                name=f32, shape=v.shape, dtype="float32"
                            )
                        new_ops.append(
                            Operator(
                                block,
                                "cast",
                                inputs={"X": [n]},
                                outputs={"Out": [f32]},
                                attrs={
                                    "in_dtype": "bfloat16",
                                    "out_dtype": "float32",
                                    OpRole.OP_ROLE_KEY: _role(op),
                                },
                            )
                        )
                        cast_names.append(f32)
                    else:
                        cast_names.append(n)
                op.inputs[slot] = cast_names
            post_casts = []
            for slot, names in list(op.outputs.items()):
                out_names = []
                for out in names:
                    if out in flipped:
                        f32 = out + ".f32out"
                        if not block.has_var(f32):
                            v = block._var_recursive(out)
                            block.create_var(
                                name=f32, shape=v.shape, dtype="float32"
                            )
                        post_casts.append(
                            Operator(
                                block,
                                "cast",
                                inputs={"X": [f32]},
                                outputs={"Out": [out]},
                                attrs={
                                    "in_dtype": "float32",
                                    "out_dtype": "bfloat16",
                                    OpRole.OP_ROLE_KEY: _role(op),
                                },
                            )
                        )
                        out_names.append(f32)
                    else:
                        out_names.append(out)
                op.outputs[slot] = out_names
            new_ops.append(op)
            new_ops.extend(post_casts)
        block.ops = new_ops

    # -- freeze mode (inference) ------------------------------------------

    def _transpile_freeze(self, program, scope):
        import jax.numpy as jnp

        from ..executor import global_scope

        scope = scope or global_scope()
        block = program.global_block()

        flipped = set()
        for name, v in block.vars.items():
            if v.dtype == "float32":
                v.dtype = "bfloat16"
                flipped.add(name)
                val = scope.find_var(name)
                if val is not None and v.persistable:
                    scope.set_var(name, jnp.asarray(val, jnp.bfloat16))

        self._wrap_islands(block, flipped)

    # -- train mode (master weights) --------------------------------------

    def _transpile_train(self, program):
        # 1. activations + gradients flip to bf16; persistables (params,
        #    moments, BN stats, lr) keep f32 — they are the master state
        flipped = set()
        for blk in program.blocks:
            for name, v in blk.vars.items():
                if v.dtype == "float32" and not v.persistable:
                    v.dtype = "bfloat16"
                    flipped.add(name)
        # Optimize-role helper ops (regularizers, grad clip) appended under
        # _optimized_guard read the f32 masters directly; any output they
        # derive from an f32 operand is f32 at runtime, so its annotation
        # must stay f32 (f32 weight-decay math feeding the update is the
        # numerically-right thing — only the ANNOTATION needs fixing).
        # Fixpoint because their outputs chain (scale → sum).
        all_vars = {}
        for blk in program.blocks:
            for name, v in blk.vars.items():
                all_vars.setdefault(name, v)
        changed = True
        while changed:
            changed = False
            for blk in program.blocks:
                for op in blk.ops:
                    if not _role(op) & OpRole.Optimize:
                        continue
                    has_f32_in = any(
                        n in all_vars
                        and n not in flipped
                        and all_vars[n].dtype == "float32"
                        for ns in op.inputs.values()
                        for n in ns
                    )
                    if not has_f32_in:
                        continue
                    for ns in op.outputs.values():
                        for n in ns:
                            if n in flipped:
                                all_vars[n].dtype = "float32"
                                flipped.discard(n)
                                changed = True

        # attr-driven producers (fill_constant & friends) must emit the
        # flipped dtype too, or the value contradicts its var annotation
        # (e.g. the backward's f32 loss@GRAD seed into a bf16 var)
        for blk in program.blocks:
            for op in blk.ops:
                if str(op.attrs.get("dtype", "")) not in ("float32", "5"):
                    continue
                outs = [n for ns in op.outputs.values() for n in ns]
                if outs and all(n in flipped for n in outs):
                    op.attrs["dtype"] = "bfloat16"

        # 2. one bf16 cast per consumed master param per step: rewrite every
        #    compute op (not optimizer/LR-sched, not islands, not casts) to
        #    read `w@BF16`; the cast ops are prepended to the global block
        gblock = program.global_block()
        masters = {
            name
            for name, v in gblock.vars.items()
            if v.persistable and v.dtype == "float32"
        }
        used = []  # masters consumed by compute ops, in first-use order
        skip_roles = OpRole.Optimize | OpRole.LRSched
        for blk in program.blocks:
            for op in blk.ops:
                if _role(op) & skip_roles or op.type == "cast":
                    continue
                # islands cast masters up themselves; keep-set ops (BN/LN/CE)
                # are f32-native and read master Scale/Bias/stats directly
                if self._is_island(op.type, _TRAIN_ISLANDS | _TRAIN_KEEP_BF16):
                    continue
                for slot, names in list(op.inputs.items()):
                    rewritten = []
                    for n in names:
                        if n in masters:
                            if n not in used:
                                used.append(n)
                            rewritten.append(n + "@BF16")
                        else:
                            rewritten.append(n)
                    op.inputs[slot] = rewritten
        casts = []
        for n in used:
            v = gblock.var(n)
            cast_name = n + "@BF16"
            if not gblock.has_var(cast_name):
                gblock.create_var(name=cast_name, shape=v.shape, dtype="bfloat16")
                flipped.add(cast_name)
            casts.append(
                Operator(
                    gblock,
                    "cast",
                    inputs={"X": [n]},
                    outputs={"Out": [cast_name]},
                    attrs={
                        "in_dtype": "float32",
                        "out_dtype": "bfloat16",
                        OpRole.OP_ROLE_KEY: OpRole.Forward,
                    },
                )
            )
        gblock.ops = casts + gblock.ops

        # 3. islands (blacklist + gather-likes + their _grad twins, minus the
        #    internally-f32-accumulating keep set) compute in f32 and cast
        #    flipped outputs back down
        for blk in program.blocks:
            self._wrap_islands(blk, flipped, _TRAIN_ISLANDS, _TRAIN_KEEP_BF16)


# fp16 never wins on TPU (no fast fp16 path; bf16 is native) — keep the
# reference's class name as an alias targeting bf16.
Float16Transpiler = Bf16Transpiler
