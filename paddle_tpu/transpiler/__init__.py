"""Program→program rewrites (reference python/paddle/fluid/transpiler/):
DistributeTranspiler (pserver + collective modes), memory_optimize,
InferenceTranspiler, QuantizeTranspiler, Bf16Transpiler (float16 analog).
"""

from .bf16_transpiler import Bf16Transpiler, Float16Transpiler  # noqa: F401
from .distribute_transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
)
from .gradient_merge import gradient_merge_transpile  # noqa: F401
from .inference_transpiler import InferenceTranspiler  # noqa: F401
from .memory_optimization_transpiler import (  # noqa: F401
    memory_optimize,
    release_memory,
)
from .ps_dispatcher import HashName, PSDispatcher, RoundRobin  # noqa: F401
from .quantize_transpiler import QuantizeTranspiler  # noqa: F401
