"""memory_optimize: liveness-based in-place variable reuse on a Program.

DEPRECATED SHIM — the transform now lives in the pass framework as
passes/ports.py `memory_optimize` (run it via
`passes.apply_inplace(program, ["memory_optimize"], ...)` or any pipeline
spec); these functions are kept as the reference-compatible entry points
(python/paddle/fluid/transpiler/memory_optimization_transpiler.py:457) and
delegate.

TPU-native status (unchanged): inside one jitted block XLA's buffer
assignment already performs this reuse optimally, so renaming cannot shrink
device memory further — the transform is kept because (a) it is part of the
public transpiler API, (b) it reduces the number of distinct names the
executor tracks across feed/fetch and host-op segment boundaries, where
values DO materialize, and (c) its statistics (print_log=True) report the
same reuse plan the reference printed. Semantics are preserved: only
non-persistable, non-fetched, same-dtype same-size vars are merged.
"""

__all__ = ["memory_optimize", "release_memory"]


def memory_optimize(input_program, skip_opt_set=None, print_log=False, level=0):
    """Rewrite `input_program` in place, renaming dead intermediate vars onto
    compatible earlier ones. Returns the reuse mapping {new_name: old_name}.
    Deprecated: delegates to the `memory_optimize` pass."""
    from ..passes import apply_inplace

    results = apply_inplace(
        input_program,
        ["memory_optimize"],
        attrs={"skip_opt_set": skip_opt_set, "print_log": print_log},
    )
    return results["memory_optimize"]["mapping"]


def release_memory(input_program, skip_opt_set=None):
    """Reference release_memory inserts eager `delete_var` ops; under XLA,
    buffer lifetimes inside a jitted block end at last use automatically, so
    this is a documented no-op kept for API compatibility."""
    return None
