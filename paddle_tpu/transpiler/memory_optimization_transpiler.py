"""memory_optimize: liveness-based in-place variable reuse on a Program.

Reference analog: python/paddle/fluid/transpiler/memory_optimization_transpiler.py
(ControlFlowGraph liveness at :113, memory_optimize entry at :457): dataflow
liveness analysis over the op list, then renaming later vars onto dead earlier
vars of matching dtype/size so the C++ executor reuses their buffers.

TPU-native status: inside one jitted block XLA's buffer assignment already
performs this optimally, so renaming cannot shrink device memory further —
the transform is kept because (a) it is part of the public transpiler API,
(b) it reduces the number of distinct names the executor tracks across
feed/fetch and host-op segment boundaries, where values DO materialize, and
(c) its statistics (`memory_optimize(..., print_log=True)`) report the same
reuse plan the reference printed. Semantics are preserved: only
non-persistable, non-fetched, same-dtype same-size vars are merged.
"""

import numpy as np

from .. import framework

__all__ = ["memory_optimize", "release_memory"]

# ops whose outputs alias inputs or that the renamer must not touch
# (reference SUB_BLOCK_OPS + skip list)
_SKIP_OP_TYPES = frozenset(
    ["while", "conditional_block", "recurrent", "listen_and_serv"]
)


class _Liveness:
    """Backward liveness over the straight-line op list (the reference's
    ControlFlowGraph restricted to block 0, which is where it applies it)."""

    def __init__(self, block, protected):
        self.block = block
        self.protected = protected
        n = len(block.ops)
        self.live_after = [set() for _ in range(n)]
        live = set(protected)
        for i in range(n - 1, -1, -1):
            op = block.ops[i]
            self.live_after[i] = set(live)
            live -= set(op.output_arg_names)
            live |= set(op.input_arg_names)


def memory_optimize(input_program, skip_opt_set=None, print_log=False, level=0):
    """Rewrite `input_program` in place, renaming dead intermediate vars onto
    compatible earlier ones. Returns the reuse mapping {new_name: old_name}."""
    block = input_program.global_block()
    skip = set(skip_opt_set or ())
    protected = set(skip)
    for name, v in block.vars.items():
        if v.persistable or v.is_data or getattr(v, "stop_gradient", False):
            protected.add(name)
    # vars referenced by sub-block ops stay untouched (reference SUB_BLOCK_PAIR
    # handling): renaming across block boundaries is not worth the risk
    for blk in input_program.blocks[1:]:
        for op in blk.ops:
            protected.update(op.input_arg_names)
            protected.update(op.output_arg_names)
    for op in block.ops:
        if op.type in _SKIP_OP_TYPES:
            protected.update(op.input_arg_names)
            protected.update(op.output_arg_names)

    liveness = _Liveness(block, protected)
    free_pool = {}  # (dtype, shape) -> [buffer names free for reuse]
    mapping = {}  # original var name -> buffer name it now occupies
    occupants = {}  # buffer name -> set of original names mapped onto it

    def pool_key(v):
        # Exact dtype+shape match, with a dynamic (-1) dim allowed: two vars
        # whose static shapes are identical occupy equal-size buffers at
        # runtime even when the batch dim is symbolic (the reference compares
        # shapes the same way, memory_optimization_transpiler.py:150-163).
        if v.shape is None:
            return None
        return (v.dtype, tuple(v.shape))

    for i, op in enumerate(block.ops):
        # inputs were defined earlier — apply their renames
        for slot, names in op.inputs.items():
            op.inputs[slot] = [mapping.get(n, n) for n in names]
        # outputs defined here: try to place each onto a free dead buffer
        for out in op.output_arg_names:
            if out in protected or out in mapping or not block.has_var(out):
                continue
            key = pool_key(block.var(out))
            if key is None:
                continue
            candidates = free_pool.get(key)
            if candidates:
                buf = candidates.pop()
                mapping[out] = buf
                occupants.setdefault(buf, set()).add(out)
        for slot, names in op.outputs.items():
            op.outputs[slot] = [mapping.get(n, n) for n in names]
        # original vars whose live range ends here free their buffer
        live = liveness.live_after[i]
        for name in set(op.input_arg_names) | set(op.output_arg_names):
            # `name` is a buffer name; free only once every original mapped
            # onto it (and itself) is dead
            originals = occupants.get(name) or (name,)
            if name in live or any(o in live for o in originals):
                continue
            if name in protected or not block.has_var(name):
                continue
            key = pool_key(block.var(name))
            if key is None:
                continue
            lst = free_pool.setdefault(key, [])
            if name not in lst:
                lst.append(name)

    # drop now-unreferenced vars
    if mapping:
        used = set()
        for op in block.ops:
            used.update(op.input_arg_names)
            used.update(op.output_arg_names)
        for old in list(block.vars):
            if old in mapping and old not in used:
                del block.vars[old]
        input_program._bump_version()

    if print_log:
        saved = 0
        for new, old in mapping.items():
            v = block.vars.get(old) or block.vars.get(new)
            if v is None or v.shape is None:
                continue
            # product of known dims: per-sample bytes when batch dim is -1
            n = 1
            for d in v.shape:
                n *= d if d and d > 0 else 1
            saved += n * np.dtype(
                "float32" if v.dtype == "bfloat16" else v.dtype
            ).itemsize
        print(
            "memory_optimize: reused %d buffers (~%.1f KB/sample host-visible)"
            % (len(mapping), saved / 1024.0)
        )
    return mapping


def release_memory(input_program, skip_opt_set=None):
    """Reference release_memory inserts eager `delete_var` ops; under XLA,
    buffer lifetimes inside a jitted block end at last use automatically, so
    this is a documented no-op kept for API compatibility."""
    return None
