"""DistributeTranspiler: rewrite a single-process program into trainer and
parameter-shard ("pserver") programs for multi-node training.

Reference analog: python/paddle/fluid/transpiler/distribute_transpiler.py:148
(algorithm described at :16-31): slice each param/grad into blocks
(`slice_var_up`), dispatch blocks to pserver endpoints (ps_dispatcher), insert
split → send → send_barrier → recv → fetch_barrier → concat into the trainer
program, and emit per-pserver programs whose listen_and_serv op runs that
shard's optimizer sub-blocks (reference get_pserver_program:646,
get_trainer_program:527).

TPU-native redesign notes:
- A "pserver" here is a host-side parameter-shard owner process speaking the
  framework's socket RPC (paddle_tpu/distributed/rpc.py — the gRPC
  grpc_serde.cc analog); its optimize blocks execute through the same XLA
  executor as everything else, so the shard update itself runs on the
  accelerator.
- `config.mode == "collective"` is the reference's NCCL2 mode
  (gen_nccl_id_op.cc:31-110): no program rewriting at all — the program is
  annotated with (num_trainers, trainer_id) and gradients are all-reduced by
  GSPMD over the multi-host mesh (parallel/multihost.py) instead of NCCL
  rings; this is the preferred TPU path, pserver mode exists for parity and
  for CPU-host parameter sharding of giant embeddings.
- Per-param Optimize-role ops that are NOT optimizer updates (gradient clip,
  weight decay) stay on the trainer before the send, instead of moving to the
  pserver: behaviorally identical for per-param transforms and required for
  global-norm clipping, which needs all grads in one place.
- Distributed lookup tables (`lookup_table` with is_distributed=True) are
  rewritten to the mesh-sharded `distributed_lookup_table` op
  (parallel/sharded_embedding.py) rather than RPC prefetch
  (distributed/parameter_prefetch.cc:26).

DEPRECATION (PR 8): for embedding-scale models, prefer
`paddle_tpu.embedding.EmbeddingEngine` /
`fluid.layers.distributed_embedding` over pserver mode. The engine
row-shards the table over the mesh `ep` axis with SelectedRows-style sparse
gradients and per-row optimizer updates inside the compiled SPMD step —
no pserver processes, no RPC, sharded checkpoints included
(docs/embedding.md). Pserver mode remains for reference parity and
CPU-host sharding of tables too large for the pod's aggregate HBM.
"""

from .. import framework
from ..framework import OpRole
from .ps_dispatcher import RoundRobin

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig"]

# the 12 optimizer update ops (reference operators/optimizers/, SURVEY.md §2.5)
OPTIMIZER_OP_TYPES = frozenset(
    [
        "sgd",
        "momentum",
        "lars_momentum",
        "adam",
        "adamax",
        "adagrad",
        "decayed_adagrad",
        "proximal_adagrad",
        "adadelta",
        "rmsprop",
        "ftrl",
        "proximal_gd",
    ]
)

RPC_OP_ROLE_ATTR = OpRole.RPC


class DistributeTranspilerConfig:
    """Reference distribute_transpiler.py:126-139.

    slice_var_up: split large params into blocks balanced across endpoints.
    split_method: a PSDispatcher subclass.
    min_block_size: do not produce blocks smaller than this many elements
      (reference uses 8192 to keep splits worthwhile).
    mode: "pserver" (default) or "collective" (reference NCCL2 mode).
    """

    slice_var_up = True
    split_method = RoundRobin
    min_block_size = 8192
    mode = "pserver"
    # pserver-side gradient merge (sync mode): accumulate k rounds of
    # trainer-summed grads, apply the optimizer every k-th round on the
    # (averaged, if gradient_merge_avg) accumulator — the reference's
    # multi_batch_merge_pass composed with pserver sharding
    # (test_dist_mnist_batch_merge.py semantics).
    gradient_merge_k = 0
    gradient_merge_avg = True


class VarBlock:
    """One dim-0 slice of a variable: rows [begin, begin+rows)."""

    def __init__(self, varname, block_id, begin, rows, orig_shape, dtype, sliced):
        self.varname = varname
        self.block_id = block_id
        self.begin = begin
        self.rows = rows
        self.orig_shape = tuple(orig_shape)
        self.dtype = dtype
        self.sliced = sliced

    def name(self):
        if not self.sliced:
            return self.varname
        return "%s.block%d" % (self.varname, self.block_id)

    @property
    def shape(self):
        if not self.sliced:
            return self.orig_shape
        return (self.rows,) + self.orig_shape[1:]

    def __repr__(self):
        return "VarBlock(%s, shape=%s)" % (self.name(), self.shape)


def slice_variable(var, slice_count, min_block_size):
    """Split `var` along dim 0 into at most slice_count whole-row blocks, each
    of at least min_block_size elements (reference slice_variable/
    distribute_transpiler.py:1073 `_slice_var_up` semantics: block count
    bounded by both endpoint count and min block size)."""
    shape = tuple(var.shape)
    if not shape or shape[0] <= 1:
        return [VarBlock(var.name, 0, 0, shape[0] if shape else 1, shape, var.dtype, False)]
    numel = 1
    for d in shape:
        numel *= d
    row_elems = numel // shape[0]
    max_by_size = max(1, numel // max(min_block_size, 1))
    n = min(slice_count, max_by_size, shape[0])
    if n <= 1:
        return [VarBlock(var.name, 0, 0, shape[0], shape, var.dtype, False)]
    base, rem = divmod(shape[0], n)
    blocks, begin = [], 0
    for i in range(n):
        rows = base + (1 if i < rem else 0)
        blocks.append(VarBlock(var.name, i, begin, rows, shape, var.dtype, True))
        begin += rows
    return blocks


class DistributeTranspiler:
    """Reference distribute_transpiler.py:148. Usage:

        t = DistributeTranspiler(config)
        t.transpile(trainer_id, program=main, pservers="h1:6174,h2:6174",
                    trainers=2, sync_mode=True)
        trainer_prog = t.get_trainer_program()
        pserver_prog = t.get_pserver_program("h1:6174")
        pserver_startup = t.get_startup_program("h1:6174", pserver_prog)
    """

    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()

    # ------------------------------------------------------------------ #
    def transpile(
        self,
        trainer_id,
        program=None,
        pservers="127.0.0.1:6174",
        trainers=1,
        sync_mode=True,
        startup_program=None,
        current_endpoint="",
    ):
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = sync_mode
        if int(getattr(self.config, "gradient_merge_k", 0) or 0) > 1 and not sync_mode:
            raise ValueError(
                "gradient_merge_k > 1 requires sync_mode=True: the merge "
                "window is defined by sync rounds (async applies each grad "
                "as it arrives, so a silent no-merge would train at the "
                "wrong effective batch size)"
            )
        self.origin_program = program or framework.default_main_program()
        self.startup_program = (
            startup_program or framework.default_startup_program()
        )
        self.pserver_endpoints = [e.strip() for e in pservers.split(",") if e.strip()]

        self._rewrite_dist_lookup_tables(self.origin_program)

        if self.config.mode in ("collective", "nccl2"):
            # NCCL2-analog: gradients all-reduce over the multi-host mesh; the
            # program itself is untouched (SURVEY.md §5.8).
            self.origin_program._num_trainers = trainers
            self.origin_program._trainer_id = trainer_id
            self.trainer_program = self.origin_program
            return

        main = self.origin_program
        block = main.global_block()

        # 1. collect (param, grad) pairs from optimizer update ops, preserving
        #    op order (the reference keys on op_role_var the same way).
        self.param_grad_pairs = []
        opt_op_indices = []
        self.lr_ops = []
        for i, op in enumerate(block.ops):
            role = op.attrs.get(OpRole.OP_ROLE_KEY, OpRole.Forward)
            if op.type in OPTIMIZER_OP_TYPES and role & OpRole.Optimize:
                pg = op.attrs.get(OpRole.OP_ROLE_VAR_KEY) or []
                if len(pg) < 2:
                    # an update op we can't attribute to a (param, grad) pair
                    # cannot be placed on a pserver shard; keeping it would
                    # misalign the pair<->op zip below and apply the wrong
                    # update rule to every later param
                    raise ValueError(
                        "optimizer op %r lacks the (param, grad) op_role_var "
                        "attr; build it via optimizer.minimize / "
                        "_optimized_guard so the transpiler can place it"
                        % op.type
                    )
                self.param_grad_pairs.append((pg[0], pg[1]))
                opt_op_indices.append(i)
            elif role == OpRole.LRSched:
                self.lr_ops.append(op)
        self.opt_ops = [block.ops[i] for i in opt_op_indices]
        if not self.param_grad_pairs:
            raise ValueError(
                "no optimizer ops with op_role_var found; run "
                "optimizer.minimize(loss) before transpiling"
            )

        # 2. slice params/grads into blocks and dispatch to endpoints
        dispatcher = self.config.split_method(self.pserver_endpoints)
        slice_count = len(self.pserver_endpoints) if self.config.slice_var_up else 1
        self.param_blocks = {}  # param name -> [VarBlock]
        self.grad_blocks = {}  # grad name -> [VarBlock]
        self.ep_of_block = {}  # block name -> endpoint
        # ep -> {"params": [(pblock, gblock, opt_op)], }
        self.param_grad_ep_mapping = {
            ep: {"params": [], "grads": []} for ep in self.pserver_endpoints
        }
        for (pname, gname), opt_op in zip(self.param_grad_pairs, self.opt_ops):
            pvar = block.var(pname)
            pblocks = slice_variable(pvar, slice_count, self.config.min_block_size)
            gblocks = [
                VarBlock(gname, b.block_id, b.begin, b.rows, b.orig_shape, b.dtype, b.sliced)
                for b in pblocks
            ]
            self.param_blocks[pname] = pblocks
            self.grad_blocks[gname] = gblocks
            eps = dispatcher.dispatch(pblocks)
            for pb, gb, ep in zip(pblocks, gblocks, eps):
                self.ep_of_block[pb.name()] = ep
                self.ep_of_block[gb.name()] = ep
                self.param_grad_ep_mapping[ep]["params"].append((pb, gb, opt_op))
                self.param_grad_ep_mapping[ep]["grads"].append(gb)

        # 3. rewrite the trainer program
        self._build_trainer_program(block, opt_op_indices)

    # ------------------------------------------------------------------ #
    def _rewrite_dist_lookup_tables(self, program):
        """lookup_table(is_distributed=True) → mesh-sharded
        distributed_lookup_table (replaces the reference's RPC prefetch path,
        distribute_transpiler.py _update_dist_lookup_table_vars)."""
        from ..parallel import shard_parameter

        for blk in program.blocks:
            for op in blk.ops:
                if op.type == "lookup_table" and op.attrs.get("is_distributed"):
                    op.type = "distributed_lookup_table"
                    op.attrs = {
                        "axis_name": "ep",
                        OpRole.OP_ROLE_KEY: op.attrs.get(
                            OpRole.OP_ROLE_KEY, OpRole.Forward
                        ),
                    }
                    w = blk._var_recursive(op.input("W")[0])
                    shard_parameter(w, ("ep", None))

    def _build_trainer_program(self, block, opt_op_indices):
        """Delete optimizer + LR ops; append split/send/barriers/recv/concat
        (reference get_trainer_program:527 + _insert_split_op/_append_send_op)."""
        drop = set(opt_op_indices) | {
            i
            for i, op in enumerate(block.ops)
            if op.attrs.get(OpRole.OP_ROLE_KEY) == OpRole.LRSched
        }
        block.ops = [op for i, op in enumerate(block.ops) if i not in drop]

        rpc_attrs = {OpRole.OP_ROLE_KEY: RPC_OP_ROLE_ATTR}
        eps = self.pserver_endpoints

        # split each sliced grad, then one send op per grad
        for gname, gblocks in self.grad_blocks.items():
            if gblocks[0].sliced:
                for gb in gblocks:
                    block.create_var(
                        name=gb.name(), shape=gb.shape, dtype=gb.dtype
                    )
                block.append_op(
                    type="split",
                    inputs={"X": [gname]},
                    outputs={"Out": [gb.name() for gb in gblocks]},
                    attrs={
                        "axis": 0,
                        "sections": [gb.rows for gb in gblocks],
                        OpRole.OP_ROLE_KEY: OpRole.Dist,
                    },
                )
            block.append_op(
                type="send",
                inputs={"X": [gb.name() for gb in gblocks]},
                outputs={},
                attrs=dict(
                    rpc_attrs,
                    epmap=[self.ep_of_block[gb.name()] for gb in gblocks],
                    sync_mode=self.sync_mode,
                    trainer_id=self.trainer_id,
                ),
            )
        if self.sync_mode:
            block.append_op(
                type="send_barrier",
                inputs={},
                outputs={},
                attrs=dict(rpc_attrs, endpoints=eps, trainer_id=self.trainer_id),
            )
        # recv updated param blocks, then concat the sliced ones back
        for pname, pblocks in self.param_blocks.items():
            for pb in pblocks:
                if pb.sliced:
                    block.create_var(name=pb.name(), shape=pb.shape, dtype=pb.dtype)
            block.append_op(
                type="recv",
                inputs={},
                outputs={"Out": [pb.name() for pb in pblocks]},
                attrs=dict(
                    rpc_attrs,
                    epmap=[self.ep_of_block[pb.name()] for pb in pblocks],
                    trainer_id=self.trainer_id,
                ),
            )
        if self.sync_mode:
            block.append_op(
                type="fetch_barrier",
                inputs={},
                outputs={},
                attrs=dict(rpc_attrs, endpoints=eps, trainer_id=self.trainer_id),
            )
        for pname, pblocks in self.param_blocks.items():
            if pblocks[0].sliced:
                block.append_op(
                    type="concat",
                    inputs={"X": [pb.name() for pb in pblocks]},
                    outputs={"Out": [pname]},
                    attrs={"axis": 0, OpRole.OP_ROLE_KEY: OpRole.Dist},
                )
        self.trainer_program = self.origin_program

    def get_trainer_program(self):
        return self.trainer_program

    # ------------------------------------------------------------------ #
    def _sliced_state_name(self, state_name, pb):
        return "%s.block%d" % (state_name, pb.block_id) if pb.sliced else state_name

    def get_pserver_program(self, endpoint):
        """Program for one parameter-shard owner: a listen_and_serv op whose
        sub-blocks hold this shard's optimizer updates (reference
        get_pserver_program:646; sync loop listen_and_serv_op.cc:106-176)."""
        assigned = self.param_grad_ep_mapping[endpoint]["params"]
        prog = framework.Program()
        g0 = prog.global_block()
        origin_block = self.origin_program.global_block()

        lr_block_idx = -1
        if self.lr_ops:
            lr_block = prog._create_block(parent_idx=0)
            for op in self.lr_ops:
                for name in op.input_arg_names + op.output_arg_names:
                    if not g0.has_var(name) and origin_block.has_var_recursive(name):
                        ov = origin_block._var_recursive(name)
                        g0.create_var(
                            name=name,
                            shape=ov.shape,
                            dtype=ov.dtype,
                            persistable=True,
                        )
                lr_block.ops.append(
                    framework.Operator(
                        lr_block, op.type, op.inputs, op.outputs, dict(op.attrs)
                    )
                )
            lr_block_idx = lr_block.idx
            prog.current_block_idx = 0

        optimize_blocks = []
        grad_to_block_id = []
        for pb, gb, opt_op in assigned:
            sub = prog._create_block(parent_idx=0)
            prog.current_block_idx = 0
            # remap the opt op's vars to this shard's slices
            pname, gname = pb.varname, gb.varname
            inputs, outputs = {}, {}
            for slot, names in opt_op.inputs.items():
                inputs[slot] = [self._shard_var_name(prog, origin_block, n, pb, pname, gname, gb) for n in names]
            for slot, names in opt_op.outputs.items():
                outputs[slot] = [self._shard_var_name(prog, origin_block, n, pb, pname, gname, gb) for n in names]
            attrs = dict(opt_op.attrs)
            attrs[OpRole.OP_ROLE_KEY] = OpRole.Optimize
            sub.ops.append(
                framework.Operator(sub, opt_op.type, inputs, outputs, attrs)
            )
            optimize_blocks.append(sub)
            grad_to_block_id.append("%s:%d" % (gb.name(), sub.idx))

        g0.append_op(
            type="listen_and_serv",
            inputs={},
            outputs={},
            attrs={
                "endpoint": endpoint,
                "sync_mode": self.sync_mode,
                "Fanin": self.trainer_num,
                "optimize_blocks": [b.idx for b in optimize_blocks],
                "grad_to_block_id": grad_to_block_id,
                "lr_decay_block_id": lr_block_idx,
                "gradient_merge_k": int(
                    getattr(self.config, "gradient_merge_k", 0) or 0
                ),
                "gradient_merge_avg": bool(
                    getattr(self.config, "gradient_merge_avg", True)
                ),
                OpRole.OP_ROLE_KEY: RPC_OP_ROLE_ATTR,
            },
        )
        prog._ps_endpoint = endpoint
        return prog

    def _shard_var_name(self, prog, origin_block, name, pb, pname, gname, gb):
        """Map an optimizer-op var name to its pserver shard var, creating the
        var in the pserver program: param/grad → .blockN slices; same-shaped
        optimizer state (moments) sliced likewise; scalars (lr, beta pows)
        carried whole."""
        g0 = prog.global_block()
        ov = origin_block._var_recursive(name) if origin_block.has_var_recursive(name) else None
        if name == pname:
            new, shape, persistable = pb.name(), pb.shape, True
        elif name == gname:
            new, shape, persistable = gb.name(), gb.shape, False
        elif (
            ov is not None
            and pb.sliced
            and ov.shape == pb.orig_shape
            and ov.persistable
        ):
            new = self._sliced_state_name(name, pb)
            shape, persistable = pb.shape, True
        else:
            new = name
            shape = ov.shape if ov is not None else None
            persistable = ov.persistable if ov is not None else True
        if not g0.has_var(new):
            v = g0.create_var(
                name=new,
                shape=shape,
                dtype=ov.dtype if ov is not None else "float32",
                persistable=persistable,
            )
            if ov is not None and name == pname:
                v.is_parameter_shard = True
        return new

    def get_startup_program(self, endpoint, pserver_program=None):
        """Init ops for this endpoint's shards. Initializers are re-emitted
        with the sliced shape (documented deviation from the reference, which
        slices the initialized full tensor: fan-in-dependent initializers see
        the shard shape; distribution equivalence holds for the constant /
        uniform / normal initializers optimizers actually use on state)."""
        prog = framework.Program()
        blk = prog.global_block()
        origin_startup = self.startup_program.global_block()

        # map: output var name -> its init op in the original startup program
        init_of = {}
        for op in origin_startup.ops:
            for out in op.output_arg_names:
                init_of[out] = op

        if pserver_program is None:
            pserver_program = self.get_pserver_program(endpoint)
        done = set()
        for tname, pv in pserver_program.global_block().vars.items():
            if not pv.persistable or tname in done:
                continue
            done.add(tname)
            base = tname.split(".block")[0]
            src = init_of.get(base)
            if src is None:
                continue  # e.g. recv-only buffers; values arrive via RPC
            shape = tuple(pv.shape) if pv.shape is not None else None
            attrs = dict(src.attrs)
            if "shape" in attrs and shape is not None:
                attrs["shape"] = list(shape)
            blk.create_var(
                name=tname, shape=shape, dtype=pv.dtype, persistable=True
            )
            blk.append_op(
                type=src.type,
                inputs=src.inputs,
                outputs={
                    slot: [tname if n == base else n for n in names]
                    for slot, names in src.outputs.items()
                },
                attrs=attrs,
            )
        return prog
