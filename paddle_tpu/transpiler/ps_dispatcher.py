"""Parameter-shard dispatchers: assign sliced variable blocks to endpoints.

Reference analog: python/paddle/fluid/transpiler/ps_dispatcher.py (PSDispatcher,
RoundRobin, HashName). Endpoints here name parameter-shard owners — on TPU a
"pserver" is the host process owning a shard of the parameter/optimizer state
(see distribute_transpiler.py) rather than a gRPC daemon, but the dispatch
policy layer is identical.

DEPRECATION (PR 8): embedding tables no longer need endpoint dispatch at
all — `paddle_tpu.embedding.EmbeddingEngine` row-shards them over the mesh
`ep` axis (GSPMD placement, docs/embedding.md), which supersedes HashName/
RoundRobin placement for the distributed-lookup-table use case. These
dispatchers remain for pserver-mode parameter sharding.
"""

__all__ = ["PSDispatcher", "RoundRobin", "HashName"]


class PSDispatcher:
    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class HashName(PSDispatcher):
    """Hash(var name) % #endpoints (reference ps_dispatcher.py:HashName)."""

    def _hash_block(self, block_str, total):
        return hash(block_str) % total

    def dispatch(self, varlist):
        eplist = []
        for var in varlist:
            server_id = self._hash_block(var.name(), len(self._eps))
            eplist.append(self._eps[server_id])
        return eplist


class RoundRobin(PSDispatcher):
    """Cycle through endpoints (reference ps_dispatcher.py:RoundRobin)."""

    def dispatch(self, varlist):
        eplist = []
        for _ in varlist:
            eplist.append(self._eps[self._step])
            self._step = (self._step + 1) % len(self._eps)
        return eplist
