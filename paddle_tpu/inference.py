"""Inference deployment: Predictor API + ahead-of-time compiled export.

Reference analog: paddle/fluid/inference/ (§2.9 of SURVEY.md) —
`PaddlePredictor`/`AnalysisPredictor` (api/analysis_predictor.cc) load a
saved inference program, run the analysis/fusion pass pipeline, and serve
Run() through the NaiveExecutor, optionally capturing subgraphs into
TensorRT engines.

TPU-first redesign: the analysis pipeline's job (fuse, place, capture
subgraphs for a faster runtime) IS XLA compilation here, so:
- `Predictor` = load_inference_model + a compile-once, shape-keyed serve
  loop (the AnalysisPredictor role; InferenceTranspiler covers the
  program-level rewrites the reference ran before compilation).
- `export_compiled`/`load_compiled` = jax.export round-trip of the fully
  compiled StableHLO artifact — the "inference library" deliverable the
  reference built with fluid_lib_dist/TensorRT engines: the serving side
  needs no Python model code, just the artifact.
"""

import os

import numpy as np

from . import framework, io
from .executor import Executor, Scope, scope_guard

__all__ = ["Predictor", "export_compiled", "load_compiled"]


class Predictor:
    """Load-and-serve (reference CreatePaddlePredictor → Run). Feeds are a
    dict name->array; returns numpy arrays for the model's fetch targets."""

    def __init__(self, model_dir, place=None, params_filename=None):
        self.scope = Scope()
        self.exe = Executor(place)
        with scope_guard(self.scope):
            program, feed_names, fetch_vars = io.load_inference_model(
                model_dir, self.exe, params_filename=params_filename
            )
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = [v.name for v in fetch_vars]

    def run(self, feed):
        if isinstance(feed, (list, tuple)):
            feed = dict(zip(self.feed_names, feed))
        missing = [n for n in self.feed_names if n not in feed]
        if missing:
            raise ValueError("missing feeds: %s" % missing)
        with scope_guard(self.scope):
            outs = self.exe.run(
                self.program, feed=feed, fetch_list=self.fetch_names
            )
        return [np.asarray(o) for o in outs]

    # reference PaddlePredictor names
    def get_input_names(self):
        return list(self.feed_names)

    def get_output_names(self):
        return list(self.fetch_names)


def export_compiled(model_dir, example_feed, out_path, place=None, params_filename=None):
    """AOT-compile the inference program for the example feed shapes and
    serialize the compiled artifact (StableHLO via jax.export) together with
    the parameters — deployable without the model-building code."""
    import jax
    from jax import export as jax_export
    import jax.numpy as jnp

    pred = Predictor(model_dir, place, params_filename=params_filename)
    with scope_guard(pred.scope):
        from .executor import _CompiledBlock

        feed = {
            k: np.asarray(v) for k, v in zip(pred.feed_names, example_feed)
        } if isinstance(example_feed, (list, tuple)) else {
            k: np.asarray(v) for k, v in example_feed.items()
        }
        block = pred.program.global_block()
        compiled = _CompiledBlock(
            pred.program, block, list(feed.keys()), pred.fetch_names, pred.scope
        )
        ro = {n: pred.scope.vars[n] for n in compiled.ro_names}
        mut = {n: pred.scope.vars[n] for n in compiled.mut_names}
        rng_key = pred.scope.rng_key

        def serve(feeds, ro_, mut_):
            # compiled.fn is the un-jitted lowering: (feeds, ro, mut, key) ->
            # (fetches, new_mut, created, key); inference serves fetches only
            fetches, _, _, _ = compiled.fn(feeds, ro_, mut_, rng_key)
            return fetches

        exported = jax_export.export(jax.jit(serve))(
            {k: jnp.asarray(v) for k, v in feed.items()}, ro, mut
        )
        blob = exported.serialize()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    np.savez(
        out_path,
        __stablehlo__=np.frombuffer(blob, np.uint8),
        __feed_names__=np.array(list(feed.keys())),
        __fetch_names__=np.array(pred.fetch_names),
        **{"ro:" + k: np.asarray(v) for k, v in ro.items()},
        **{"mut:" + k: np.asarray(v) for k, v in mut.items()},
    )
    return out_path


class _CompiledPredictor:
    def __init__(self, exported, feed_names, fetch_names, ro, mut):
        self._exported = exported
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        self._ro = ro
        self._mut = mut

    def run(self, feed):
        import jax.numpy as jnp

        if isinstance(feed, (list, tuple)):
            feed = dict(zip(self.feed_names, feed))
        feeds = {k: jnp.asarray(feed[k]) for k in self.feed_names}
        outs = self._exported.call(feeds, self._ro, self._mut)
        return [np.asarray(o) for o in outs]


def load_compiled(path):
    """Deserialize an export_compiled artifact; serving needs only this file
    (the reference's fluid_lib_dist/TRT-engine deployment analog)."""
    from jax import export as jax_export
    import jax.numpy as jnp

    data = np.load(path if path.endswith(".npz") else path + ".npz")
    exported = jax_export.deserialize(data["__stablehlo__"].tobytes())
    feed_names = [str(s) for s in data["__feed_names__"]]
    fetch_names = [str(s) for s in data["__fetch_names__"]]
    ro = {
        k[3:]: jnp.asarray(data[k]) for k in data.files if k.startswith("ro:")
    }
    mut = {
        k[4:]: jnp.asarray(data[k]) for k in data.files if k.startswith("mut:")
    }
    return _CompiledPredictor(exported, feed_names, fetch_names, ro, mut)
