"""Inference deployment: Predictor API + ahead-of-time compiled export.

Reference analog: paddle/fluid/inference/ (§2.9 of SURVEY.md) —
`PaddlePredictor`/`AnalysisPredictor` (api/analysis_predictor.cc) load a
saved inference program, run the analysis/fusion pass pipeline, and serve
Run() through the NaiveExecutor, optionally capturing subgraphs into
TensorRT engines.

TPU-first redesign: the analysis pipeline's job (fuse, place, capture
subgraphs for a faster runtime) IS XLA compilation here, so:
- `Predictor` = load_inference_model + a compile-once, shape-keyed serve
  loop (the AnalysisPredictor role; InferenceTranspiler covers the
  program-level rewrites the reference ran before compilation).
- `export_compiled`/`load_compiled` = jax.export round-trip of the fully
  compiled StableHLO artifact — the "inference library" deliverable the
  reference built with fluid_lib_dist/TensorRT engines: the serving side
  needs no Python model code, just the artifact.
"""

import numpy as np

from . import framework, io
from .executor import Executor, Scope, scope_guard

__all__ = ["Predictor", "export_compiled", "load_compiled"]


class Predictor:
    """Load-and-serve (reference CreatePaddlePredictor → Run). Feeds are a
    dict name->array; returns numpy arrays for the model's fetch targets."""

    def __init__(self, model_dir, place=None, params_filename=None):
        self.scope = Scope()
        self.exe = Executor(place)
        with scope_guard(self.scope):
            program, feed_names, fetch_vars = io.load_inference_model(
                model_dir, self.exe, params_filename=params_filename
            )
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = [v.name for v in fetch_vars]

    def run(self, feed):
        if isinstance(feed, (list, tuple)):
            feed = dict(zip(self.feed_names, feed))
        missing = [n for n in self.feed_names if n not in feed]
        if missing:
            raise ValueError("missing feeds: %s" % missing)
        unknown = sorted(set(feed) - set(self.feed_names))
        if unknown:
            # a typo'd feed name silently dropped into exe.run would serve
            # garbage from the default-initialized var instead
            raise ValueError(
                "unknown feeds: %s (model takes %s)" % (unknown, self.feed_names)
            )
        with scope_guard(self.scope):
            outs = self.exe.run(
                self.program, feed=feed, fetch_list=self.fetch_names
            )
        return [np.asarray(o) for o in outs]

    # reference PaddlePredictor names
    def get_input_names(self):
        return list(self.feed_names)

    def get_output_names(self):
        return list(self.fetch_names)


def export_compiled(model_dir, example_feed, out_path, place=None, params_filename=None):
    """AOT-compile the inference program for the example feed shapes and
    serialize the compiled artifact (StableHLO via jax.export) together with
    the parameters — deployable without the model-building code. Returns the
    path ACTUALLY written (np.savez appends `.npz` when out_path lacks it).

    The lowering is executor.aot_serve_lowering and the artifact format is
    serving/compile_cache.py's — the same pieces the ServingEngine builds
    its bucketed variants from; this is the single-shape offline flavor."""
    import jax
    from jax import export as jax_export
    import jax.numpy as jnp

    from .executor import aot_serve_lowering
    from .serving import compile_cache as _cc

    pred = Predictor(model_dir, place, params_filename=params_filename)
    with scope_guard(pred.scope):
        feed = {
            k: np.asarray(v) for k, v in zip(pred.feed_names, example_feed)
        } if isinstance(example_feed, (list, tuple)) else {
            k: np.asarray(v) for k, v in example_feed.items()
        }
        serve, ro, mut = aot_serve_lowering(
            pred.program, list(feed.keys()), pred.fetch_names, pred.scope
        )
        exported = jax_export.export(jax.jit(serve))(
            {k: jnp.asarray(v) for k, v in feed.items()}, ro, mut
        )
        blob = exported.serialize()
    return _cc.write_artifact(
        out_path, blob, list(feed.keys()), pred.fetch_names, ro, mut
    )


class _CompiledPredictor:
    def __init__(self, exported, feed_names, fetch_names, ro, mut):
        self._exported = exported
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        self._ro = ro
        self._mut = mut

    def run(self, feed):
        import jax.numpy as jnp

        if isinstance(feed, (list, tuple)):
            feed = dict(zip(self.feed_names, feed))
        feeds = {k: jnp.asarray(feed[k]) for k in self.feed_names}
        outs = self._exported.call(feeds, self._ro, self._mut)
        return [np.asarray(o) for o in outs]


def load_compiled(path):
    """Deserialize an export_compiled artifact; serving needs only this file
    (the reference's fluid_lib_dist/TRT-engine deployment analog)."""
    from .serving import compile_cache as _cc

    d = _cc.read_artifact(path)
    return _CompiledPredictor(
        d["exported"], d["feed_names"], d["fetch_names"], d["ro"], d["mut"]
    )
