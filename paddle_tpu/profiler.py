"""Host-side event profiler + device trace hooks.

Reference analog: platform/profiler.{h,cc} (RecordEvent RAII pairs wrapping
every op run, EnableProfiler/DisableProfiler aggregation tables, sorted
summaries) + platform/device_tracer (CUPTI kernel timeline, correlated and
exported via tools/timeline.py into chrome://tracing) + python/paddle/fluid/
profiler.py:221 (the `with profiler.profiler(...)` context manager).

TPU-first redesign: the per-op interpreter is gone — blocks run as whole XLA
modules — so host events are per *phase* (program prepare/compile, XLA
segment runs, host RPC ops, feed/fetch), and the device-side story is XLA's
own profiler (`xla_trace` wraps jax.profiler.start_trace; view in
TensorBoard/xprof), replacing CUPTI. The aggregation-table surface
(start/stop/reset, sorted_key, chrome-trace export via tools/timeline.py) is
kept API-compatible.
"""

import contextlib
import json
import os
import threading
import time

__all__ = [
    "RecordEvent",
    "start_profiler",
    "stop_profiler",
    "reset_profiler",
    "profiler",
    "is_profiling",
    "xla_trace",
    "device_op_profile",
]

_state = {"on": False, "mode": "All"}
_events = []  # (name, start_s, end_s, thread_id)
_events_lock = threading.Lock()
_tls = threading.local()


def is_profiling():
    return _state["on"]


class RecordEvent:
    """RAII event (reference platform/profiler.h:66). Nesting is recorded via
    name stacking, like the reference's pushed event pairs."""

    def __init__(self, name):
        self.name = name
        self._start = None
        self._pushed = False

    def __enter__(self):
        if _state["on"]:
            stack = getattr(_tls, "stack", None)
            if stack is None:
                stack = _tls.stack = []
            stack.append(self.name)
            self._pushed = True
            self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        # pop whenever we pushed — profiling may have been stopped by another
        # thread mid-event, and a leaked stack entry would prefix every event
        # of the next session
        if self._pushed:
            end = time.perf_counter()
            stack = _tls.stack
            full = "/".join(stack)
            stack.pop()
            self._pushed = False
            if _state["on"]:
                with _events_lock:
                    _events.append((full, self._start, end, threading.get_ident()))
        return False


def reset_profiler():
    with _events_lock:
        _events.clear()


def start_profiler(state="All"):
    """state in {CPU, GPU, TPU, All} — kept for API parity; host events are
    recorded regardless, device tracing is xla_trace's job."""
    _state["mode"] = state
    _state["on"] = True


def _aggregate():
    table = {}
    with _events_lock:
        snapshot = list(_events)
    for name, start, end, _tid in snapshot:
        row = table.setdefault(name, [0, 0.0, float("inf"), 0.0])
        dt = (end - start) * 1000.0
        row[0] += 1
        row[1] += dt
        row[2] = min(row[2], dt)
        row[3] = max(row[3], dt)
    return table, snapshot


_SORT_KEYS = {
    None: lambda kv: 0,
    "default": lambda kv: 0,
    "calls": lambda kv: -kv[1][0],
    "total": lambda kv: -kv[1][1],
    "max": lambda kv: -kv[1][3],
    "min": lambda kv: -kv[1][2],
    "ave": lambda kv: -(kv[1][1] / kv[1][0]),
}


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    """Print the aggregation table (reference DisableProfiler's summary) and
    dump raw events for tools/timeline.py."""
    _state["on"] = False
    table, snapshot = _aggregate()
    rows = sorted(table.items(), key=_SORT_KEYS.get(sorted_key, _SORT_KEYS[None]))
    header = "%-50s %8s %12s %12s %12s %12s" % (
        "Event", "Calls", "Total(ms)", "Min(ms)", "Max(ms)", "Ave(ms)",
    )
    lines = ["------------------------->    Profiling Report    <-------------------------", header]
    for name, (calls, total, mn, mx) in rows:
        lines.append(
            "%-50s %8d %12.4f %12.4f %12.4f %12.4f"
            % (name[:50], calls, total, mn, mx, total / calls)
        )
    print("\n".join(lines))
    if profile_path:
        with open(profile_path, "w") as f:
            json.dump(
                {
                    "events": [
                        {"name": n, "start": s, "end": e, "tid": t}
                        for n, s, e, t in snapshot
                    ]
                },
                f,
            )
    return table


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    """`with profiler.profiler('All', 'total'):` (reference profiler.py:221)."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def xla_trace(log_dir):
    """Device-side trace via XLA's profiler (the CUPTI device_tracer analog):
    writes a TensorBoard/xprof trace with per-HLO timing on TPU."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def cuda_profiler(*args, **kwargs):
    """API-compat shim for reference profiler.cuda_profiler (nvprof control);
    on TPU use xla_trace instead."""
    yield


def _hlo_op_attribution(hlo_text):
    """instruction name -> (op type, output var name or None), parsed from
    the compiled HLO's op_name metadata. registry.lower_ops emits
    '.../<op type>/out=<first output>/...' nested scopes, so the first
    non-wrapper segment is the op type and the segment after it (when it is
    an 'out=' tag) names the op INSTANCE; sub-block ops attribute to their
    enclosing control-flow op."""
    import re

    mapping = {}
    for m in re.finditer(r'%([\w.\-]+) = [^\n]*op_name="([^"]+)"', hlo_text):
        path = m.group(2).split("/")
        key = None
        out = None
        for i, seg in enumerate(path):
            # skip jit/transform wrappers, arg-pytree paths like
            # "feeds['img']" / "mut_state['w_0']" (donation copies — those
            # group under their HLO opcode instead), and the
            # fusion-group wrapper the fuse_elemwise_act pass adds (its
            # member ops carry their own type segments one level deeper)
            if (
                seg.startswith("jit(")
                or seg.startswith("transpose(")
                or seg.startswith("fusion_group=")
                or "[" in seg
            ):
                continue
            # a Pallas kernel-substitution scope ("pallas_kernel=
            # <family>.<gid>", registry._lower_pallas_run) replaces its
            # member ops' HLO wholesale: attribute to a "pallas:<family>"
            # row with the group id as the instance
            if seg.startswith("pallas_kernel="):
                tag = seg[len("pallas_kernel="):]
                fam, _, gid = tag.partition(".")
                key = "pallas:" + fam
                out = gid or None
                break
            key = seg
            if i + 1 < len(path) and path[i + 1].startswith("out="):
                out = path[i + 1][len("out="):]
            break
        if key:
            mapping[m.group(1)] = (key, out)
    return mapping


def _hlo_op_map(hlo_text):
    """instruction name -> framework op type (the type-level view of
    _hlo_op_attribution, kept as device_op_profile's correlation key)."""
    return {
        instr: typ for instr, (typ, _out) in _hlo_op_attribution(hlo_text).items()
    }


def _merge_device_plane_events(planes, events, aux=None):
    """Fold one xplane's device planes into the shared `events` table
    ({instr_name: [count, total_ms, min_ms, max_ms]}). Separated from the
    file loop so synthetic plane data can drive it in tests.

    `aux` (optional dict) additionally collects XLA cost-analysis stats the
    trace carries per instruction — {instr_name: {"flops": f, "bytes": b}} —
    without changing the 4-element row shape existing callers (mfu_audit,
    device_op_profile) depend on."""
    for plane in planes:
        if "TPU" not in plane.name and "GPU" not in plane.name:
            continue
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            for ev in line.events:
                name = ev.name.lstrip("%").split(" ")[0]
                dur_ms = None
                flops = nbytes = None
                for k, v in ev.stats or []:
                    if k == "device_duration_ps":
                        dur_ms = float(v) / 1e9
                    elif k == "flops":
                        flops = float(v)
                    elif k in ("bytes accessed", "bytes_accessed"):
                        nbytes = float(v)
                if dur_ms is None:
                    continue
                row = events.setdefault(name, [0, 0.0, float("inf"), 0.0])
                row[0] += 1
                row[1] += dur_ms
                row[2] = min(row[2], dur_ms)
                row[3] = max(row[3], dur_ms)
                if aux is not None and (flops is not None or nbytes is not None):
                    # cost analysis is per-instruction, not per-execution:
                    # keep the max seen, don't accumulate over repeats
                    a = aux.setdefault(name, {"flops": 0.0, "bytes": 0.0})
                    if flops is not None:
                        a["flops"] = max(a["flops"], flops)
                    if nbytes is not None:
                        a["bytes"] = max(a["bytes"], nbytes)
    return events


def device_instr_events(log_dir, aux=None):
    """Per-HLO-instruction device timings from an xla_trace log dir:
    {instr_name: [count, total_ms, min_ms, max_ms]}. Shared base for
    device_op_profile and tools/mfu_audit.py. Pass a dict as `aux` to also
    collect per-instruction XLA cost-analysis stats when the trace carries
    them (see _merge_device_plane_events).

    ALL xplane.pb files under the dir are merged — a trace session writes one
    per host (multi-host run) and a restarted/repeated trace leaves several;
    reading only the newest silently dropped every other host's kernels."""
    import glob as _glob

    paths = sorted(
        _glob.glob(os.path.join(log_dir, "**", "*.xplane.pb"), recursive=True)
    )
    if not paths:
        raise FileNotFoundError("no xplane.pb under %r — run xla_trace first" % log_dir)
    # module-attr access (not `from ... import`) so the name resolves at call
    # time — older jax builds lack ProfileData, and tests substitute it
    import jax.profiler as _jprof

    profile_data = _jprof.ProfileData
    events = {}
    for path in paths:
        _merge_device_plane_events(profile_data.from_file(path).planes, events, aux=aux)
    return events


def device_op_profile(log_dir, hlo_text=None, print_table=True):
    """Fold an xla_trace's per-HLO device timings back onto framework op
    types (ROADMAP 10; reference analog: device_tracer.cc correlating CUPTI
    kernels to RecordEvent annotations into the same profiler table).

    `log_dir` is the directory a profiler.xla_trace wrote. With `hlo_text`
    (from Executor.compiled_hlo()) each HLO instruction is attributed to the
    framework op whose lowering emitted it; without it, instructions
    aggregate by HLO opcode. Returns {key: [count, total_ms, min_ms, max_ms]}
    in stop_profiler's table shape; prints the same report format."""
    mapping = _hlo_op_map(hlo_text) if hlo_text else {}
    table = {}
    try:
        events = device_instr_events(log_dir)
    except AttributeError:
        # jaxlib without jax.profiler.ProfileData (e.g. the CPU test
        # backend's build): no device plane to aggregate — degrade to an
        # empty table as documented; mfu_audit keeps the loud failure
        events = {}
    for name, (count, total, mn, mx) in events.items():
        key = mapping.get(name)
        if key is None:
            # strip SSA suffix then retry, else group by HLO opcode
            key = mapping.get(name.split(".")[0])
        if key is None:
            key = "hlo:" + name.split(".")[0]
        row = table.setdefault(key, [0, 0.0, float("inf"), 0.0])
        row[0] += count
        row[1] += total
        row[2] = min(row[2], mn)
        row[3] = max(row[3], mx)
    if print_table and table:
        rows = sorted(table.items(), key=lambda kv: -kv[1][1])
        lines = [
            "------------------->    Device Profiling Report (XLA)    <-------------------",
            "%-50s %8s %12s %12s %12s %12s"
            % ("Op", "Kernels", "Total(ms)", "Min(ms)", "Max(ms)", "Ave(ms)"),
        ]
        for name, (calls, total, mn, mx) in rows:
            lines.append(
                "%-50s %8d %12.4f %12.4f %12.4f %12.4f"
                % (name[:50], calls, total, mn, mx, total / calls)
            )
        print("\n".join(lines))
    return table
