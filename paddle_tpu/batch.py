"""paddle.batch equivalent (reference python/paddle/batch.py): group a sample
reader into a batch reader."""

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
