"""PassManager: ordered pass pipelines over the Graph IR (reference
framework/ir/pass.cc Pass::Apply + BuildStrategy::Apply).

`PassManager(pipeline).apply(program, ...)` returns a NEW transformed
Program; `apply_cached` memoizes that per (program uid/version, pipeline,
scope, feed/fetch) so the executors' single choke point (executor.py
`_apply_pass_pipeline`) hands the SAME transformed Program object to every
run call — keeping the executable cache hot. `apply_inplace` rewrites the
caller's Program (the deprecated transpiler shims' contract).

Per pass, the manager:
- re-verifies graph invariants (Graph.verify — def-before-use, block
  linkage, foreign-block attrs);
- records wall-time and op-count telemetry through the PR 4 observability
  registry (`passes/*` gauges+counters, surfaced by tools/monitor.py);
- with FLAGS_pass_debug_dir set, dumps before/after graphviz via
  debugger.draw_block_graphviz plus a textual op diff per pass into
  `<dir>/<NN>_<pass>_{before,after}.dot` and `<NN>_<pass>_ops.diff`.

Pipeline presets (BuildStrategy.pass_pipeline / FLAGS_pass_pipeline /
aot_serve_lowering):
- training_default: constant_fold, dead_op_eliminate, fuse_elemwise_act,
  inplace_donation_plan — bit-parity-safe on training blocks (stochastic
  ops are never touched, so the RNG stream is preserved).
- inference: constant_fold, dead_op_eliminate, fuse_elemwise_act — the
  serving path's default (aot_serve_lowering); fold_batch_norm is NOT in
  it because that pass rewrites parameter values in the scope — opt in
  explicitly (or via the InferenceTranspiler shim).
- training_fused: training_default plus the Pallas kernel-substitution
  taggers (fuse_gemm_epilogue, fuse_layer_norm, fuse_optimizer) — tagged
  chains lower to hand-tuned kernels (ops/pallas_kernels.py) instead of
  per-op XLA; fused-vs-unfused parity is within bf16 rounding (one
  rounding per fused chain instead of one per op), bit-identical where
  the chain's math was already f32 (the multi-tensor Adam update).
- inference_int8: the calibrated-int8 serving pipeline (passes/quant.py) —
  calibrate records activation ranges from representative feeds riding
  ctx.attrs["calibrate"], quantize_serving freezes weights to int8 and
  bakes static activation scales (like fold_batch_norm it mutates scope
  values, hence opt-in: ServingEngine(precision="int8") is the caller),
  and fuse_quant_gemm tags the int8 chains for the one-kernel Pallas
  lowering. int8 and native variants of the same model coexist in one
  persistent compile cache (variant_key takes a precision geometry).
"""

import difflib
import os
import time

from .graph import Graph
from .pass_base import Pass, PassContext, get_pass

__all__ = [
    "PassManager",
    "PRESETS",
    "apply_cached",
    "apply_inplace",
    "resolve_pipeline",
]

PRESETS = {
    "training_default": (
        "constant_fold",
        "dead_op_eliminate",
        "fuse_elemwise_act",
        "inplace_donation_plan",
    ),
    "inference": (
        "constant_fold",
        "dead_op_eliminate",
        "fuse_elemwise_act",
    ),
    "training_fused": (
        "constant_fold",
        "dead_op_eliminate",
        "fuse_elemwise_act",
        "fuse_gemm_epilogue",
        "fuse_layer_norm",
        "fuse_optimizer",
        "inplace_donation_plan",
    ),
    "inference_int8": (
        "constant_fold",
        "dead_op_eliminate",
        "calibrate",
        "quantize_serving",
        "fuse_quant_gemm",
        "fuse_elemwise_act",
    ),
}

_OFF = ("", "off", "none")


def resolve_pipeline(pipeline):
    """Normalize a pipeline spec to a tuple of pass names. Accepts a preset
    name, a comma-separated string, an iterable of names/Pass instances, or
    an off-spec (None/""/"off"/"none") -> ()."""
    if pipeline is None:
        return ()
    if isinstance(pipeline, str):
        spec = pipeline.strip()
        if spec.lower() in _OFF:
            return ()
        if spec in PRESETS:
            return tuple(PRESETS[spec])
        return tuple(s.strip() for s in spec.split(",") if s.strip())
    out = []
    for item in pipeline:
        if isinstance(item, Pass):
            out.append(item.name or type(item).__name__)
        else:
            out.append(str(item))
    return tuple(out)


def _metrics():
    from ..observability import registry as _registry

    reg = _registry.default_registry()
    return {
        "applied": reg.counter(
            "passes/applied", "pass applications, labeled by pass"
        ),
        "wall_ms": reg.gauge(
            "passes/wall_ms", "last wall time of one pass application (ms)"
        ),
        "ops_before": reg.gauge(
            "passes/ops_before", "program op count entering the pass"
        ),
        "ops_after": reg.gauge(
            "passes/ops_after", "program op count leaving the pass"
        ),
        "ops_removed": reg.counter(
            "passes/ops_removed", "ops eliminated across all applications"
        ),
        "fusion_groups": reg.counter(
            "passes/fusion_groups", "groups formed by the fuse_* passes"
        ),
        "pipelines": reg.counter(
            "passes/pipelines", "full pipeline applications, labeled by name"
        ),
    }


class PassManager:
    """Runs an ordered pipeline of registered passes over a Program."""

    def __init__(self, pipeline):
        self._spec = resolve_pipeline(pipeline)
        self.passes = [
            p if isinstance(p, Pass) else get_pass(p)
            for p in (
                pipeline
                if not isinstance(pipeline, str) and pipeline is not None
                else self._spec
            )
        ]

    @property
    def pass_names(self):
        return tuple(p.name or type(p).__name__ for p in self.passes)

    def apply(self, program, scope=None, feed_names=(), fetch_names=(),
              attrs=None):
        """Run the pipeline; returns a NEW transformed Program carrying a
        `_pass_results` dict (per-pass payloads) and, when the pipeline
        included inplace_donation_plan, a `_donation_plan` the executor
        cross-checks at lowering."""
        graph = Graph(program)
        ctx = PassContext(
            scope=scope, feed_names=feed_names, fetch_names=fetch_names,
            attrs=attrs,
        )
        self.apply_to_graph(graph, ctx)
        out = graph.to_program()
        out._pass_results = dict(ctx.results)
        plan = ctx.results.get("inplace_donation_plan")
        if plan is not None:
            out._donation_plan = plan
        return out

    def apply_to_graph(self, graph, ctx):
        """The core loop: verify → (dump, time, apply, verify, dump, diff,
        telemetry) per pass. Mutates `graph`; returns ctx.results."""
        from .. import flags as _flags

        from ..analysis import verify_graph

        debug_dir = _flags.get_flags("pass_debug_dir")["pass_debug_dir"]
        m = _metrics()
        graph.verify()
        # FLAGS_static_verify stage 0: structural fluidlint over the pristine
        # graph, so pre-existing defects are attributed to the program, not
        # to whichever pass happens to run first
        verify_graph(graph, ctx, stage="0")
        # "+" not "," — snapshot label strings are comma-joined pairs, so a
        # comma inside a value would be ambiguous to every label consumer
        pipeline_label = "+".join(self.pass_names)
        for i, p in enumerate(self.passes):
            name = p.name or type(p).__name__
            ops_before = graph.num_ops()
            before_repr = None
            if debug_dir:
                before_repr = self._dump(graph, debug_dir, i, name, "before")
            t0 = time.perf_counter()
            p.apply(graph, ctx)
            graph.refresh()
            graph.verify()  # per-pass invariant re-verification
            # a failure here names the pass that broke capture/fetch/donation
            verify_graph(graph, ctx, stage=name)
            wall_ms = (time.perf_counter() - t0) * 1000.0
            ops_after = graph.num_ops()
            m["applied"].inc(**{"pass": name})
            m["wall_ms"].set(wall_ms, **{"pass": name})
            m["ops_before"].set(ops_before, **{"pass": name})
            m["ops_after"].set(ops_after, **{"pass": name})
            if ops_before > ops_after:
                m["ops_removed"].inc(ops_before - ops_after, **{"pass": name})
            groups = (ctx.results.get(name) or {}).get("groups")
            if groups:
                m["fusion_groups"].inc(groups)
            if debug_dir:
                after_repr = self._dump(graph, debug_dir, i, name, "after")
                self._dump_diff(
                    debug_dir, i, name, before_repr, after_repr
                )
        m["pipelines"].inc(pipeline=pipeline_label or "<empty>")
        return ctx.results

    @staticmethod
    def _dump(graph, debug_dir, i, name, stage):
        """graphviz snapshot of block 0 + op repr list for the textual diff."""
        from .. import debugger

        os.makedirs(debug_dir, exist_ok=True)
        path = os.path.join(
            debug_dir, "%02d_%s_%s.dot" % (i, name, stage)
        )
        try:
            debugger.draw_block_graphviz(
                graph.program.global_block(), path=path
            )
        except Exception as e:  # a dump must never kill the pipeline
            with open(path, "w") as f:
                f.write("// draw_block_graphviz failed: %r\n" % (e,))
        return [
            "[b%d] %s" % (blk.idx, op)
            for blk in graph.program.blocks
            for op in blk.ops
        ]

    @staticmethod
    def _dump_diff(debug_dir, i, name, before_repr, after_repr):
        path = os.path.join(debug_dir, "%02d_%s_ops.diff" % (i, name))
        lines = difflib.unified_diff(
            before_repr or [], after_repr or [],
            fromfile="%s/before" % name, tofile="%s/after" % name,
            lineterm="",
        )
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")


# ---------------------------------------------------------------------------
# executor-facing entry points
# ---------------------------------------------------------------------------

_APPLIED_CACHE = {}  # memo key -> transformed Program
_APPLIED_CACHE_CAP = 64


def apply_cached(program, pipeline, scope=None, feed_names=(),
                 fetch_names=()):
    """Memoized PassManager.apply: same (program uid+version, pipeline,
    scope, feeds, fetches) → the SAME transformed Program object, so the
    executors' executable caches (keyed on the transformed program's
    uid/version) stay hot across run calls."""
    spec = resolve_pipeline(pipeline)
    if not spec:
        return program
    key = (
        program._uid,
        program._version,
        spec,
        getattr(scope, "_uid", None),
        tuple(sorted(feed_names)),
        tuple(fetch_names),
    )
    hit = _APPLIED_CACHE.get(key)
    if hit is not None:
        return hit
    out = PassManager(spec).apply(
        program, scope=scope, feed_names=feed_names, fetch_names=fetch_names
    )
    if len(_APPLIED_CACHE) >= _APPLIED_CACHE_CAP:
        _APPLIED_CACHE.pop(next(iter(_APPLIED_CACHE)))
    _APPLIED_CACHE[key] = out
    return out


def apply_inplace(program, pipeline, scope=None, feed_names=(),
                  fetch_names=(), attrs=None):
    """Run a pipeline and write the result back into `program` (in-place
    contract of the deprecated transpiler entry points). Returns the
    ctx.results dict."""
    mgr = PassManager(pipeline)
    graph = Graph(program)
    ctx = PassContext(
        scope=scope, feed_names=feed_names, fetch_names=fetch_names,
        attrs=attrs,
    )
    mgr.apply_to_graph(graph, ctx)
    graph.write_to(program)
    return ctx.results
