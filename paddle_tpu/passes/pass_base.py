"""Pass base class + string-keyed PassRegistry (reference framework/ir/pass.h
REGISTER_PASS; registry shape mirrors ops/registry.py).

A Pass is a named Program→Program rewrite expressed over the Graph IR
(passes/graph.py). Passes mutate the graph's shadow program and record any
caller-facing payload (reuse mappings, fold counts, the donation plan) into
`ctx.results[pass_name]`; the PassManager re-verifies graph invariants and
emits telemetry after each one.
"""

__all__ = [
    "Pass",
    "PassContext",
    "register_pass",
    "get_pass",
    "registered_passes",
    "PASSES",
]

PASSES = {}  # name -> Pass subclass (string-keyed, like ops/registry.OPS)


class PassContext:
    """Everything a pass may consult beyond the graph itself.

    scope: executor Scope holding parameter values (None for purely
    structural pipelines — passes needing values must degrade to no-ops).
    feed_names / fetch_names: the run's external inputs and requested
    outputs — the reachability roots (a fetched var must survive every
    pass, ISSUE'd explicitly for constant_fold).
    attrs: free-form per-invocation knobs (e.g. memory_optimize's
    skip_opt_set). results: per-pass payloads, keyed by pass name.
    """

    def __init__(self, scope=None, feed_names=(), fetch_names=(), attrs=None):
        self.scope = scope
        self.feed_names = tuple(feed_names)
        self.fetch_names = tuple(fetch_names)
        self.attrs = dict(attrs or {})
        self.results = {}


class Pass:
    """Base class. Subclasses set `name` via @register_pass and implement
    apply(graph, ctx) mutating the graph in place (return value ignored)."""

    name = None

    def apply(self, graph, ctx):
        raise NotImplementedError(
            "pass %r does not implement apply()" % type(self).__name__
        )

    def __repr__(self):
        return "<Pass %s>" % (self.name or type(self).__name__)


def register_pass(name):
    """Class decorator: `@register_pass("constant_fold")` — same idiom as
    ops/registry.register. Re-registration raises (a silent shadow would make
    pipeline behavior depend on import order)."""

    def deco(cls):
        if name in PASSES and PASSES[name] is not cls:
            raise ValueError("pass %r already registered" % name)
        cls.name = name
        PASSES[name] = cls
        return cls

    return deco


def get_pass(name):
    """Instantiate a registered pass by name."""
    _ensure_builtin()
    cls = PASSES.get(name)
    if cls is None:
        raise KeyError(
            "unknown pass %r (registered: %s)" % (name, registered_passes())
        )
    return cls()


def registered_passes():
    _ensure_builtin()
    return sorted(PASSES)


def _ensure_builtin():
    # the built-in battery self-registers on import; lazy so `import
    # paddle_tpu.passes.pass_base` alone never drags jax-heavy modules in
    from . import builtin, ports, quant  # noqa: F401
