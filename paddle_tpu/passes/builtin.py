"""The initial pass battery (reference framework/ir/*_pass.cc equivalents).

Every pass here preserves the RNG stream of the lowered block: stochastic ops
are never folded, eliminated, or reordered, because registry.lower_ops splits
the scope key once per surviving stochastic op in program order — removing or
moving one would silently change every later op's randomness and break the
pipeline-on/off bit-parity contract (tests/test_passes.py).
"""

from ..framework import Block
from .pass_base import Pass, register_pass

__all__ = [
    "ConstantFoldPass",
    "DeadOpEliminatePass",
    "FuseAttentionPass",
    "FuseElemwiseActPass",
    "FuseGemmEpiloguePass",
    "FuseLayerNormPass",
    "FuseOptimizerPass",
    "InplaceDonationPlanPass",
]


def _prune_orphan_vars(graph, keep):
    """Drop block-0 var declarations no remaining op references (never
    persistables, data vars, or anything in `keep`)."""
    block = graph.program.global_block()
    used = set()
    for blk in graph.program.blocks:
        for op in blk.ops:
            used.update(op.input_arg_names)
            used.update(op.output_arg_names)
    dropped = 0
    for name in list(block.vars):
        v = block.vars[name]
        if name in used or name in keep or v.persistable or v.is_data:
            continue
        del block.vars[name]
        dropped += 1
    if dropped:
        graph.program._bump_version()
    return dropped


@register_pass("constant_fold")
class ConstantFoldPass(Pass):
    """Evaluate ops whose inputs are all persistable constants and replace
    them with their value, stored into the scope (reference
    constant_folding_pass.cc). "Constant" means: in the scope, never written
    by any op of the program, and not fed. Ops are skipped when they are
    stochastic, host-side, control-flow, write persistable/fetched/fed names,
    names a sub-block reads, names that already hold a scope value, or names
    with more than one writer — every case where baking the value in would
    change observable behavior."""

    def apply(self, graph, ctx):
        import jax
        import jax.numpy as jnp

        from ..ops import registry

        result = {"folded": 0, "stored": []}
        ctx.results[self.name] = result
        scope = ctx.scope
        if scope is None:
            return
        block = graph.program.global_block()
        fed = set(ctx.feed_names)
        fetched = set(ctx.fetch_names)
        sub_used = graph.subblock_reachable_names()

        writer_count = {}
        for blk in graph.program.blocks:
            for op in blk.ops:
                for n in op.output_arg_names:
                    writer_count[n] = writer_count.get(n, 0) + 1

        const_vals = {}  # folded-away outputs, usable by later folds

        def const_value(name):
            if name in const_vals:
                return const_vals[name]
            if name in fed or writer_count.get(name, 0) > 0:
                return None
            val = scope.find_var(name)
            if val is None:
                return None
            v = block.vars.get(name)
            if v is not None and not v.persistable:
                return None
            return jnp.asarray(val)

        lower_ctx = registry.LowerCtx(jax.random.key(0), is_test=True)
        kept = []
        for op in block.ops:
            opdef = (
                registry.get(op.type)
                if registry.is_registered(op.type)
                else None
            )
            out_names = [
                n for n in op.output_arg_names
                if n != registry.EMPTY_VAR_NAME
            ]
            foldable = (
                opdef is not None
                and opdef.lower is not None
                and not opdef.skip_exec
                and not opdef.is_host
                and not opdef.stochastic
                and not any(
                    isinstance(v, Block) for v in op.attrs.values()
                )
                and out_names
                and all(
                    writer_count.get(n, 0) == 1
                    and n not in fetched
                    and n not in fed
                    and n not in sub_used
                    and scope.find_var(n) is None
                    and not (
                        block.vars.get(n) is not None
                        and block.vars[n].persistable
                    )
                    for n in out_names
                )
            )
            env = {}
            if foldable:
                for n in op.input_arg_names:
                    if n == registry.EMPTY_VAR_NAME:
                        continue
                    val = const_value(n)
                    if val is None:
                        foldable = False
                        break
                    env[n] = val
            if not foldable:
                kept.append(op)
                continue
            try:
                registry.lower_ops(lower_ctx, [op], env)
            except Exception:
                kept.append(op)  # lowering refused eager aval — not a constant
                continue
            ok = True
            for n in out_names:
                if env.get(n) is None:
                    ok = False
                    break
            if not ok:
                kept.append(op)
                continue
            for n in out_names:
                const_vals[n] = env[n]
            # decrement so a later op consuming only this (now writer-less)
            # name sees it as a constant
            for n in out_names:
                writer_count[n] -= 1
            result["folded"] += 1
        if not result["folded"]:
            return
        block.ops = kept
        # materialize folded values the surviving ops still read: downstream
        # consumers get them from the scope as read-only state (values of
        # fully folded-through chains never need to exist at run time)
        still_read = set()
        for blk in graph.program.blocks:
            for op in blk.ops:
                still_read.update(op.input_arg_names)
        for n, val in const_vals.items():
            if n not in still_read:
                continue
            scope.set_var(n, val)
            result["stored"].append(n)
        result["stored"].sort()
        graph.program._bump_version()
        graph.refresh()
        _prune_orphan_vars(graph, keep=set(result["stored"]) | fed | fetched)


@register_pass("dead_op_eliminate")
class DeadOpEliminatePass(Pass):
    """Remove ops whose outputs are unfetched and unconsumed (reference
    graph_to_program 'garbage' ops / Program._prune, but fetch- AND
    persistable-root aware: an op that writes persistable state — an
    optimizer update, a running-stat write — is a root even when nothing
    fetches it, as are host/control-flow/stochastic/unregistered ops)."""

    def apply(self, graph, ctx):
        from ..ops import registry

        block = graph.program.global_block()
        fed = set(ctx.feed_names)
        needed = set(ctx.fetch_names) | graph.subblock_reachable_names()
        kept = []
        for op in reversed(block.ops):
            opdef = (
                registry.get(op.type)
                if registry.is_registered(op.type)
                else None
            )
            keep = (
                opdef is None
                or opdef.skip_exec
                or opdef.is_host
                or opdef.stochastic
                or any(isinstance(v, Block) for v in op.attrs.values())
                or not op.output_arg_names
                or any(n in needed for n in op.output_arg_names)
            )
            if not keep:
                for n in op.output_arg_names:
                    v = block.vars.get(n)
                    if v is not None and v.persistable:
                        keep = True
                        break
            if keep:
                kept.append(op)
                needed.update(
                    n for n in op.input_arg_names
                    if n != registry.EMPTY_VAR_NAME
                )
        removed = len(block.ops) - len(kept)
        ctx.results[self.name] = {"removed": removed}
        if not removed:
            return
        block.ops = list(reversed(kept))
        graph.program._bump_version()
        graph.refresh()
        _prune_orphan_vars(graph, keep=needed | fed)


# producer -> (consumer add) -> activation chains the tagger groups; the
# attr itself is defined in ops/registry.py because lower_ops reads it
_FUSE_PRODUCERS = ("matmul", "mul", "conv2d", "depthwise_conv2d")
_FUSE_ACTS = (
    "relu", "relu6", "gelu", "tanh", "sigmoid", "swish", "leaky_relu",
)


@register_pass("fuse_elemwise_act")
class FuseElemwiseActPass(Pass):
    """Tag contiguous matmul/conv → elementwise_add [→ activation] chains
    with a shared `fusion_group` attr (reference fuse_elewise_add_act_pass).
    registry.lower_ops lowers each tagged run inside ONE enclosing
    jax.named_scope, so the XLA fusion heuristics see the chain as a unit
    and the profiler attributes its HLO to the group. Purely additive —
    op semantics, order, and count are untouched."""

    def apply(self, graph, ctx):
        from ..ops.registry import FUSION_GROUP_ATTR

        ops = graph.program.global_block().ops
        groups = 0
        tagged = 0
        i = 0
        while i < len(ops):
            op = ops[i]
            if op.type not in _FUSE_PRODUCERS or FUSION_GROUP_ATTR in op.attrs:
                i += 1
                continue
            chain = self._chain_at(graph, ops, i)
            if chain is None:
                i += 1
                continue
            gid = "fg%d" % groups
            for member in chain:
                member.attrs[FUSION_GROUP_ATTR] = gid
                tagged += 1
            groups += 1
            i += len(chain)
        ctx.results[self.name] = {"groups": groups, "ops_tagged": tagged}
        if groups:
            graph.program._bump_version()

    @staticmethod
    def _chain_at(graph, ops, i):
        def next_consumes(op, j):
            """ops[j+1] iff it directly consumes op's first output. Other
            consumers (grad ops re-reading the forward intermediate) don't
            disqualify: the tag only wraps lowering in a named_scope, it
            never rewrites def-use."""
            if j + 1 >= len(ops):
                return None
            out = op.output_arg_names[0] if op.output_arg_names else None
            if out is None:
                return None
            nxt = ops[j + 1]
            if out not in nxt.input_arg_names:
                return None
            return nxt

        add = next_consumes(ops[i], i)
        if add is None or add.type != "elementwise_add":
            return None
        chain = [ops[i], add]
        act = next_consumes(add, i + 1)
        if act is not None and act.type in _FUSE_ACTS:
            chain.append(act)
        return chain


# chains the kernel-substitution taggers hand to Pallas. These passes only
# TAG: every shape/dtype/attr decision is re-validated at trace time by the
# @register_fused lowering (ops/pallas_kernels.py), which declines back to
# the per-op path — so tagging can be optimistic without risking semantics.
_PALLAS_GEMM_PRODUCERS = ("mul", "matmul")
_PALLAS_GEMM_ACTS = ("relu", "gelu", "tanh", "sigmoid")


def _pallas_free(op):
    from ..ops.registry import PALLAS_GROUP_ATTR

    return PALLAS_GROUP_ATTR not in op.attrs


def _tag_run(run, gid, family):
    from ..ops.registry import PALLAS_GROUP_ATTR, PALLAS_KERNEL_ATTR

    for member in run:
        member.attrs[PALLAS_GROUP_ATTR] = gid
        member.attrs[PALLAS_KERNEL_ATTR] = family


@register_pass("fuse_gemm_epilogue")
class FuseGemmEpiloguePass(Pass):
    """Tag mul|matmul → elementwise_add [→ act] chains for the fused Pallas
    GEMM epilogue (ops/pallas_kernels.py `gemm_epilogue`): bias add and
    activation computed on the f32 MXU accumulator with ONE rounding to the
    output dtype. Unlike fuse_elemwise_act (a named-scope hint this pass
    happily coexists with — Pallas tags take precedence in lower_ops), the
    wiring check here is strict slot equality, because the fused lowering
    replaces the ops' math rather than just scoping it."""

    def apply(self, graph, ctx):
        ops = graph.program.global_block().ops
        groups = 0
        tagged = 0
        i = 0
        while i < len(ops):
            op = ops[i]
            if op.type not in _PALLAS_GEMM_PRODUCERS or not _pallas_free(op):
                i += 1
                continue
            chain = self._chain_at(ops, i)
            if chain is None:
                i += 1
                continue
            _tag_run(chain, "gemm%d" % groups, "gemm_epilogue")
            tagged += len(chain)
            groups += 1
            i += len(chain)
        ctx.results[self.name] = {"groups": groups, "ops_tagged": tagged}
        if groups:
            graph.program._bump_version()

    @staticmethod
    def _chain_at(ops, i):
        prod = ops[i]
        if i + 1 >= len(ops) or not prod.output_arg_names:
            return None
        add = ops[i + 1]
        if (
            add.type != "elementwise_add"
            or not _pallas_free(add)
            or add.input("X") != [prod.output("Out")[0]]
        ):
            return None
        chain = [prod, add]
        if i + 2 < len(ops):
            act = ops[i + 2]
            if (
                act.type in _PALLAS_GEMM_ACTS
                and _pallas_free(act)
                and act.input("X") == [add.output("Out")[0]]
            ):
                chain.append(act)
        return chain


def _causal_neg_mask(arr, t):
    """True iff arr is the additive causal mask idiom: exactly 0 on and
    below the diagonal, <= -1e8 strictly above (np.triu(full(-1e9), k=1))."""
    import numpy as np

    arr = np.asarray(arr, dtype=np.float64)
    if arr.shape != (t, t):
        return False
    lower = np.tril(np.ones((t, t), dtype=bool))
    return bool(np.all(arr[lower] == 0.0) and np.all(arr[~lower] <= -1e8))


@register_pass("fuse_attention")
class FuseAttentionPass(Pass):
    """SUBSTITUTE the unfused causal-attention score chain

        matmul(Q, K, transpose_Y, alpha) -> elementwise_add(. , triu -1e9)
        -> softmax -> matmul(. , V)

    with ONE flash_attention op (ops/pallas_kernels.py) — unlike the
    taggers above this rewrites def-use, deleting the [b, h, t, t] score
    materialization from the program; the op's own lowering still declines
    to the dense reference off-TPU (flash_path_taken), so substitution
    never changes where the math can run. Conservative by construction:

    - the additive mask must be STATICALLY the causal idiom — an
      assign_value op whose payload is 0 on/below the diagonal and <= -1e8
      above (the -1e9 triu the dense blocks emit), or a scope constant with
      the same values (constant_fold may have folded the assign);
    - every replaced intermediate (raw scores, masked scores, probs, mask)
      must have no consumer outside the chain and must not be fetched —
      a program reading attention probabilities (or their grads: backward
      ops consume them) keeps the unfused form;
    - any op between softmax and the context matmul — dropout above all —
      breaks adjacency and declines: stochastic ops are never removed or
      reordered (the RNG-stream contract in the module docstring).

    Fused-vs-unfused parity is within one online-softmax rounding, NOT
    bit-identical: the chain's -1e9 additive mask leaks ~e^-1e9 probability
    mass where the kernel's where-mask drops it exactly."""

    def apply(self, graph, ctx):
        from ..framework import Operator, OpRole
        from ..ops.pallas_kernels import flash_path_taken

        block = graph.program.global_block()
        fetched = set(ctx.fetch_names)
        fused = 0
        changed = True
        while changed:
            changed = False
            ops = block.ops
            readers = {}
            for op in ops:
                for n in op.input_arg_names:
                    readers.setdefault(n, []).append(op)
            for i, op in enumerate(ops):
                chain = self._chain_at(block, ops, i, readers, fetched, ctx)
                if chain is None:
                    continue
                members, q, k, v, out, sm_scale, t = chain
                attrs = {
                    "causal": True,
                    "sm_scale": float(sm_scale),
                    OpRole.OP_ROLE_KEY: OpRole.Forward,
                }
                outputs = {"Out": [out]}
                if flash_path_taken(t, t, causal=True):
                    # mirror layers.flash_attention: declare the logsumexp
                    # residual exactly when the lowering takes the kernel
                    lse = block.create_var(
                        name=out + ".lse", shape=None, dtype="float32"
                    )
                    lse.stop_gradient = True
                    outputs["Lse"] = [lse.name]
                fa = Operator(
                    block,
                    "flash_attention",
                    inputs={"Q": [q], "K": [k], "V": [v]},
                    outputs=outputs,
                    attrs=attrs,
                )
                drop = set(id(m) for m in members)
                idx = ops.index(members[0])
                block.ops = [o for o in ops if id(o) not in drop]
                block.ops.insert(idx, fa)
                fused += 1
                changed = True
                graph.program._bump_version()
                graph.refresh()
                break
        ctx.results[self.name] = {"fused": fused}
        if fused:
            _prune_orphan_vars(graph, keep=fetched | set(ctx.feed_names))

    @staticmethod
    def _chain_at(block, ops, i, readers, fetched, ctx):
        """(members, q, k, v, out_name, sm_scale, t) or None."""
        import numpy as np

        mm1 = ops[i]
        if (
            mm1.type != "matmul"
            or not mm1.attrs.get("transpose_Y", False)
            or mm1.attrs.get("transpose_X", False)
            or not mm1.output("Out")
        ):
            return None
        j = i + 1
        mask_op = None
        if j < len(ops) and ops[j].type == "assign_value":
            mask_op = ops[j]
            j += 1
        if j + 2 > len(ops) - 1:
            return None
        add, sm, mm2 = ops[j], ops[j + 1], ops[j + 2]
        s0 = mm1.output("Out")[0]
        if (
            add.type != "elementwise_add"
            or sm.type != "softmax"
            or mm2.type != "matmul"
            or add.input("X") != [s0]
            or sm.input("X") != [add.output("Out")[0]]
            or mm2.input("X") != [sm.output("Out")[0]]
            or mm2.attrs.get("transpose_X", False)
            or mm2.attrs.get("transpose_Y", False)
            or float(mm2.attrs.get("alpha", 1.0)) != 1.0
        ):
            return None
        # q/k/v must be rank-4 (b, h, t, d) — the flash op contract — with a
        # static time extent to validate the mask against
        q_name, k_name = mm1.input("X")[0], mm1.input("Y")[0]
        v_name = mm2.input("Y")[0]
        shapes = []
        for n in (q_name, k_name, v_name):
            try:
                vv = block._var_recursive(n)
            except KeyError:
                return None
            if vv.shape is None or len(vv.shape) != 4:
                return None
            shapes.append(tuple(vv.shape))
        t = shapes[0][2]
        if not isinstance(t, int) or t <= 0 or shapes[1][2] != t:
            return None
        # the mask must be statically the causal triu(-1e9) idiom
        mask_name = add.input("Y")[0]
        if mask_op is not None:
            if mask_op.output("Out") != [mask_name]:
                return None
            vals = np.asarray(mask_op.attrs.get("values", ()))
            shp = [int(s) for s in mask_op.attrs.get("shape", ())]
            if shp != [t, t] or not _causal_neg_mask(vals.reshape(shp), t):
                return None
        else:
            val = ctx.scope.find_var(mask_name) if ctx.scope else None
            if val is None or not _causal_neg_mask(np.asarray(val), t):
                return None
        # replaced intermediates must die with the chain: no outside
        # consumers (grad ops included), nothing fetched
        members = [mm1] + ([mask_op] if mask_op is not None else []) + [
            add, sm, mm2
        ]
        inside = set(id(m) for m in members)
        dying = [s0, add.output("Out")[0], sm.output("Out")[0]]
        if mask_op is not None:
            dying.append(mask_name)
        for n in dying:
            if n in fetched:
                return None
            if any(id(r) not in inside for r in readers.get(n, ())):
                return None
        return (
            members, q_name, k_name, v_name, mm2.output("Out")[0],
            float(mm1.attrs.get("alpha", 1.0)), t,
        )


@register_pass("fuse_layer_norm")
class FuseLayerNormPass(Pass):
    """Tag [elementwise_add →] layer_norm chains for the fused Pallas
    layer_norm(+residual) forward (`layer_norm` family: residual add in the
    input dtype, one-pass Welford stats and normalization in f32), and every
    layer_norm_grad as a singleton for the explicit backward kernel
    (`layer_norm_grad` family). Grad ops never inherit forward tags —
    backward.py copies attrs at build time, before any pass runs — so the
    backward must be tagged here explicitly."""

    def apply(self, graph, ctx):
        ops = graph.program.global_block().ops
        groups = 0
        tagged = 0
        i = 0
        while i < len(ops):
            op = ops[i]
            if not _pallas_free(op):
                i += 1
                continue
            if op.type == "layer_norm_grad":
                _tag_run([op], "lng%d" % groups, "layer_norm_grad")
                groups += 1
                tagged += 1
                i += 1
                continue
            if (
                op.type == "elementwise_add"
                and i + 1 < len(ops)
                and ops[i + 1].type == "layer_norm"
                and _pallas_free(ops[i + 1])
                and ops[i + 1].input("X") == [op.output("Out")[0]]
            ):
                _tag_run([op, ops[i + 1]], "ln%d" % groups, "layer_norm")
                groups += 1
                tagged += 2
                i += 2
                continue
            if op.type == "layer_norm":
                _tag_run([op], "ln%d" % groups, "layer_norm")
                groups += 1
                tagged += 1
            i += 1
        ctx.results[self.name] = {"groups": groups, "ops_tagged": tagged}
        if groups:
            graph.program._bump_version()


@register_pass("fuse_optimizer")
class FuseOptimizerPass(Pass):
    """Tag maximal contiguous runs (≥ 2) of dense adam ops sharing
    (beta1, beta2, epsilon, LearningRate input) for the fused multi-tensor
    Adam kernel (`multi_adam` family): every param group flattened into
    chunk-padded slabs and updated by ONE kernel, f32 master math rounded to
    the storage dtypes. AdamOptimizer emits exactly this shape — one adam
    per param back to back, beta-pow scale ops appended after the run."""

    def apply(self, graph, ctx):
        ops = graph.program.global_block().ops
        groups = 0
        tagged = 0
        i = 0
        while i < len(ops):
            op = ops[i]
            if op.type != "adam" or not _pallas_free(op):
                i += 1
                continue
            key = self._group_key(op)
            j = i + 1
            while (
                j < len(ops)
                and ops[j].type == "adam"
                and _pallas_free(ops[j])
                and self._group_key(ops[j]) == key
            ):
                j += 1
            run = ops[i:j]
            if len(run) >= 2:
                _tag_run(run, "madam%d" % groups, "multi_adam")
                groups += 1
                tagged += len(run)
            i = j
        ctx.results[self.name] = {"groups": groups, "ops_tagged": tagged}
        if groups:
            graph.program._bump_version()

    @staticmethod
    def _group_key(op):
        return (
            op.attrs.get("beta1", 0.9),
            op.attrs.get("beta2", 0.999),
            op.attrs.get("epsilon", 1e-8),
            op.input("LearningRate")[0],
        )


@register_pass("inplace_donation_plan")
class InplaceDonationPlanPass(Pass):
    """Compute the block's donation/aliasing split — which scope tensors the
    block rewrites (donated into the jit, updated in place on device) vs
    reads only — as a pass over the graph instead of ad-hoc executor logic
    (reference memory/inplace_op_pass + build_strategy memory planning).
    The plan rides the emitted program (`program._donation_plan`);
    executor._CompiledBlock cross-checks its own classification against it
    and raises on divergence, making the plan the verified source of truth
    at the lowering seam."""

    def apply(self, graph, ctx):
        from ..ops import registry

        scope = ctx.scope
        fed = set(ctx.feed_names)
        plan = {
            "feed": sorted(fed),
            "fetch": list(ctx.fetch_names),
            "mut": [],
            "ro": [],
            "unknown": [],
            "scope_uid": getattr(scope, "_uid", None),
        }
        ctx.results[self.name] = plan
        block = graph.program.global_block()
        if scope is None or not all(
            registry.is_registered(op.type) for op in block.ops
        ):
            plan["unknown"] = ["<unanalyzable>"]
            return
        ops = [
            op for op in block.ops if not registry.get(op.type).skip_exec
        ]
        produced, state, unknown = set(), set(), set()
        for op in ops:
            for name in op.input_arg_names:
                if (
                    name == registry.EMPTY_VAR_NAME
                    or name in fed
                    or name in produced
                    or name in state
                    or name in unknown
                ):
                    continue
                if scope.find_var(name) is not None:
                    state.add(name)
                else:
                    unknown.add(name)
            produced.update(
                n for n in op.output_arg_names
                if n != registry.EMPTY_VAR_NAME
            )
        for name in ctx.fetch_names:
            if name not in fed and name not in produced and name not in state:
                if scope.find_var(name) is not None:
                    state.add(name)
                else:
                    unknown.add(name)
        written = set()
        for op in ops:
            written.update(
                n for n in op.output_arg_names
                if n != registry.EMPTY_VAR_NAME
            )
        plan["mut"] = sorted(state & written)
        plan["ro"] = sorted(state - written)
        plan["unknown"] = sorted(unknown)
