"""Graph-level optimization pass framework (reference framework/ir/:
ir::Graph + Pass + PassRegistry + BuildStrategy::Apply — PAPER.md rows
L2/L3; docs/passes.md).

Program → Graph(program) → [Pass, Pass, ...] → Program, with a lossless
round-trip, per-pass invariant verification, telemetry, and flag-gated
debug dumps. Both executors and the serving engine apply pipelines at one
choke point before lowering (executor._apply_pass_pipeline); presets live
in manager.PRESETS and are selected via BuildStrategy.pass_pipeline,
FLAGS_pass_pipeline, or aot_serve_lowering's default "inference".
"""

from .graph import Graph, GraphVerifyError, OpNode, VarNode, clone_program
from .manager import (
    PRESETS,
    PassManager,
    apply_cached,
    apply_inplace,
    resolve_pipeline,
)
from .pass_base import (
    PASSES,
    Pass,
    PassContext,
    get_pass,
    register_pass,
    registered_passes,
)
from . import builtin, ports  # noqa: F401  (self-registering pass battery)

__all__ = [
    "Graph",
    "GraphVerifyError",
    "OpNode",
    "VarNode",
    "clone_program",
    "Pass",
    "PassContext",
    "PassManager",
    "PASSES",
    "PRESETS",
    "apply_cached",
    "apply_inplace",
    "get_pass",
    "register_pass",
    "registered_passes",
    "resolve_pipeline",
]
