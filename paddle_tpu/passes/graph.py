"""Graph IR over a fluid Program (reference framework/ir/graph.h).

The reference converts a ProgramDesc into an `ir::Graph` of OpNodes and
VarNodes with def-use edges, runs `Pass`es over it, and converts back
(graph_to_program_pass). This module is the same seam for the TPU port:

- `Graph(program)` deep-copies the Program into a private *shadow* program
  (passes never mutate the caller's object) and indexes every block into
  `OpNode`/`VarNode` structures with producer/consumer edges, including
  sub-block awareness: a control-flow op (while/cond/recurrent) counts as a
  consumer of every parent-block variable its sub-block tree references, so
  reachability passes (dead-op elimination, constant folding) are naturally
  conservative across block boundaries.
- Passes mutate the shadow program through the node API (or directly — the
  shadow's blocks are ordinary framework.Block objects, so transpiler-style
  rewrite code ports verbatim) and call `refresh()` to recompute edges.
- `to_program()` emits an independent Program; `write_to(program)` replaces
  a caller program's blocks in place (the deprecated-transpiler-shim path).

The round-trip `Graph(p).to_program()` is LOSSLESS: bit-identical
`Program.to_dict()` (tests/test_passes.py proves it for every model in
paddle_tpu/models). Losslessness is why the clone below exists instead of
reusing `Program.clone()` — that one drops dynamic annotations such as
`sharding_spec` (parallel.shard_parameter / embedding tables) which the
executor's state-sharding consults after passes ran.
"""

import copy

from .. import framework
from ..framework import Block, Operator, Parameter, Variable

__all__ = ["Graph", "GraphVerifyError", "OpNode", "VarNode", "clone_program"]

# var attributes outside Variable.__init__'s signature that must survive a
# pass pipeline (set with plain attribute assignment elsewhere in the tree)
_DYNAMIC_VAR_ATTRS = ("sharding_spec",)


def _clone_var(block, v):
    if isinstance(v, Parameter):
        nv = Parameter(
            block,
            shape=v.shape,
            dtype=v.dtype,
            name=v.name,
            stop_gradient=v.stop_gradient,  # batch_norm stats: True
            trainable=v.trainable,
            optimize_attr=copy.copy(v.optimize_attr),
            regularizer=v.regularizer,
            gradient_clip_attr=v.gradient_clip_attr,
            do_model_average=v.do_model_average,
        )
    else:
        nv = Variable(
            block,
            name=v.name,
            shape=v.shape,
            dtype=v.dtype,
            type=v.type,
            lod_level=v.lod_level,
            persistable=v.persistable,
            stop_gradient=v.stop_gradient,
            is_data=v.is_data,
        )
    for attr in _DYNAMIC_VAR_ATTRS:
        val = getattr(v, attr, None)
        if val is not None:
            setattr(nv, attr, val)
    return nv


def clone_program(src):
    """Deep copy preserving var insertion order, sub-block links, op attrs
    (Block references remapped), random_seed, _is_test, and the dynamic var
    annotations Program.clone drops."""
    p = framework.Program()
    p.random_seed = src.random_seed
    p._is_test = getattr(src, "_is_test", False)
    p.blocks = [Block(p, blk.idx, blk.parent_idx) for blk in src.blocks]
    for blk, nb in zip(src.blocks, p.blocks):
        for name, v in blk.vars.items():
            nb.vars[name] = _clone_var(nb, v)
        for op in blk.ops:
            attrs = {}
            for k, val in op.attrs.items():
                if isinstance(val, Block):
                    attrs[k] = p.blocks[val.idx]
                else:
                    attrs[k] = copy.copy(val)
            nop = Operator(
                nb, op.type, inputs=op.inputs, outputs=op.outputs, attrs=attrs
            )
            nb.ops.append(nop)
    p._bump_version()
    return p


class VarNode:
    """One variable name within a block: `var` is the declared Variable (None
    for names referenced by ops but declared in no block — they resolve via
    the executor scope at run time), `producers`/`consumers` are OpNodes."""

    __slots__ = ("name", "block_idx", "var", "producers", "consumers")

    def __init__(self, name, block_idx, var):
        self.name = name
        self.block_idx = block_idx
        self.var = var
        self.producers = []
        self.consumers = []

    @property
    def persistable(self):
        return bool(self.var is not None and self.var.persistable)

    def __repr__(self):
        return "VarNode(%s@%d, %d->%d)" % (
            self.name, self.block_idx, len(self.producers), len(self.consumers)
        )


class OpNode:
    """One op within a block. `op` is the shadow program's Operator; edits to
    its inputs/outputs/attrs are picked up by Graph.refresh()."""

    __slots__ = ("op", "block_idx", "index", "inputs", "outputs", "sub_blocks")

    def __init__(self, op, block_idx, index):
        self.op = op
        self.block_idx = block_idx
        self.index = index
        self.inputs = []  # [VarNode] read, flat, deduped, slot order
        self.outputs = []  # [VarNode] written
        self.sub_blocks = [
            v.idx for v in op.attrs.values() if isinstance(v, Block)
        ]

    @property
    def type(self):
        return self.op.type

    @property
    def attrs(self):
        return self.op.attrs

    def __repr__(self):
        return "OpNode(%s@%d[%d])" % (self.type, self.block_idx, self.index)


class GraphVerifyError(RuntimeError):
    """An invariant of the Program/Graph structure was broken by a pass."""


class Graph:
    def __init__(self, program):
        self.program = clone_program(program)
        self._blocks = []  # per block: {"ops": [OpNode], "vars": {name: VarNode}}
        self.refresh()

    # ------------------------------------------------------------------ #
    # index construction
    # ------------------------------------------------------------------ #
    def refresh(self):
        """Recompute node lists and def-use edges from the shadow program.
        Cheap (one walk over ops); call after structural mutation."""
        from ..ops.registry import EMPTY_VAR_NAME

        self._blocks = []
        for blk in self.program.blocks:
            vars_ = {
                name: VarNode(name, blk.idx, v) for name, v in blk.vars.items()
            }
            self._blocks.append({"ops": [], "vars": vars_})

        def resolve(name, block_idx, create_in):
            """VarNode for `name` seen from block `block_idx`: the declaring
            block's node if any ancestor declares it, else a synthetic node
            in `create_in` (scope-resolved names, e.g. grad accumulators)."""
            idx = block_idx
            while idx >= 0:
                node = self._blocks[idx]["vars"].get(name)
                if node is not None:
                    return node
                idx = self.program.blocks[idx].parent_idx
            node = VarNode(name, create_in, None)
            self._blocks[create_in]["vars"][name] = node
            return node

        for blk in self.program.blocks:
            nodes = self._blocks[blk.idx]["ops"]
            for i, op in enumerate(blk.ops):
                node = OpNode(op, blk.idx, i)
                seen_in, seen_out = set(), set()
                for name in op.input_arg_names:
                    if name == EMPTY_VAR_NAME or name in seen_in:
                        continue
                    seen_in.add(name)
                    vn = resolve(name, blk.idx, blk.idx)
                    node.inputs.append(vn)
                    vn.consumers.append(node)
                for name in op.output_arg_names:
                    if name == EMPTY_VAR_NAME or name in seen_out:
                        continue
                    seen_out.add(name)
                    vn = resolve(name, blk.idx, blk.idx)
                    node.outputs.append(vn)
                    vn.producers.append(node)
                nodes.append(node)

        # sub-block awareness: a control-flow op consumes every parent-scope
        # var its sub-block tree touches (reference graph.cc resolves these
        # through the same parent chain)
        for blk_nodes in self._blocks:
            for node in blk_nodes["ops"]:
                for sub_idx in node.sub_blocks:
                    for name in self._names_in_block_tree(sub_idx):
                        vn = self._find_declared(name, node.block_idx)
                        if vn is not None and node not in vn.consumers:
                            vn.consumers.append(node)
                            node.inputs.append(vn)

    def _names_in_block_tree(self, block_idx):
        names = set()
        stack = [block_idx]
        while stack:
            idx = stack.pop()
            for op in self.program.blocks[idx].ops:
                names.update(op.input_arg_names)
                names.update(op.output_arg_names)
                stack.extend(
                    v.idx for v in op.attrs.values() if isinstance(v, Block)
                )
        return names

    def _find_declared(self, name, block_idx):
        idx = block_idx
        while idx >= 0:
            node = self._blocks[idx]["vars"].get(name)
            if node is not None:
                return node
            idx = self.program.blocks[idx].parent_idx
        return None

    # ------------------------------------------------------------------ #
    # queries
    # ------------------------------------------------------------------ #
    def op_nodes(self, block_idx=0):
        return list(self._blocks[block_idx]["ops"])

    def all_op_nodes(self):
        return [n for b in self._blocks for n in b["ops"]]

    def var_node(self, name, block_idx=0):
        return self._find_declared(name, block_idx)

    def num_ops(self):
        return sum(len(blk.ops) for blk in self.program.blocks)

    def subblock_reachable_names(self):
        """Names referenced anywhere below block 0 — off-limits for renaming
        or removal decisions made from block 0's local view."""
        names = set()
        for blk in self.program.blocks[1:]:
            for op in blk.ops:
                names.update(op.input_arg_names)
                names.update(op.output_arg_names)
        return names

    # ------------------------------------------------------------------ #
    # mutation
    # ------------------------------------------------------------------ #
    def remove_op(self, op_node):
        blk = self.program.blocks[op_node.block_idx]
        blk.ops.remove(op_node.op)
        self.program._bump_version()

    def insert_op(self, index, op, block_idx=0):
        blk = self.program.blocks[block_idx]
        if op.block is not blk:
            raise GraphVerifyError(
                "op %r belongs to a different block/program" % op.type
            )
        blk.ops.insert(index, op)
        self.program._bump_version()

    # ------------------------------------------------------------------ #
    # verification (per-pass, PassManager re-runs after every pass)
    # ------------------------------------------------------------------ #
    def verify(self):
        """Structural invariants. Raises GraphVerifyError naming the breakage;
        returns a stats dict when sound."""
        from ..ops.registry import EMPTY_VAR_NAME

        prog = self.program
        for blk in prog.blocks:
            if blk.program is not prog:
                raise GraphVerifyError(
                    "block %d is not bound to the graph's program" % blk.idx
                )
            if blk.idx != 0:
                if not (0 <= blk.parent_idx < blk.idx):
                    raise GraphVerifyError(
                        "block %d has invalid parent_idx %d"
                        % (blk.idx, blk.parent_idx)
                    )
            for name, v in blk.vars.items():
                if v.name != name:
                    raise GraphVerifyError(
                        "var registered as %r but named %r in block %d"
                        % (name, v.name, blk.idx)
                    )
            for op in blk.ops:
                if not isinstance(op, Operator):
                    raise GraphVerifyError(
                        "non-Operator %r in block %d ops" % (op, blk.idx)
                    )
                for val in op.attrs.values():
                    if isinstance(val, Block) and val.program is not prog:
                        raise GraphVerifyError(
                            "op %s references a Block of a foreign program"
                            % op.type
                        )

        # def-before-use inside each block: a non-persistable, non-data var
        # whose producers ALL sit strictly after one of its consumers means a
        # pass reordered a producer past its reader — the straight-line
        # lowering would read a value that does not exist yet. Names with no
        # producer at all are fine (they resolve via feed or scope, e.g. the
        # stored outputs of constant folding).
        undeclared = 0
        for blk in prog.blocks:
            writes = {}  # name -> [op indices writing it]
            for i, op in enumerate(blk.ops):
                for name in op.output_arg_names:
                    writes.setdefault(name, []).append(i)
            for i, op in enumerate(blk.ops):
                for name in op.input_arg_names:
                    if name == EMPTY_VAR_NAME:
                        continue
                    vn = self._find_declared(name, blk.idx)
                    if vn is None or vn.var is None:
                        undeclared += 1
                        continue
                    if vn.persistable or vn.var.is_data:
                        continue
                    if self._blocks[blk.idx]["vars"].get(name) is None:
                        # declared in an ancestor block: the value exists at
                        # block entry (loop-carried state written at the tail
                        # of a while body reads its previous iteration), so
                        # intra-block write order proves nothing
                        continue
                    idxs = writes.get(name)
                    if idxs and min(idxs) > i:
                        raise GraphVerifyError(
                            "op %d (%s) in block %d reads %r before its first "
                            "producer (op %d) ran"
                            % (i, op.type, blk.idx, name, min(idxs))
                        )
        return {"ops": self.num_ops(), "undeclared": undeclared}

    # ------------------------------------------------------------------ #
    # emission
    # ------------------------------------------------------------------ #
    def to_program(self):
        """Independent Program snapshot of the graph's current state."""
        return clone_program(self.program)

    def write_to(self, program):
        """Replace `program`'s blocks with this graph's state IN PLACE —
        the compatibility path for the deprecated transpiler entry points
        whose contract is in-place mutation."""
        fresh = clone_program(self.program)
        program.blocks = fresh.blocks
        for blk in program.blocks:
            blk.program = program
        program.current_block_idx = 0
        program._bump_version()
        return program
