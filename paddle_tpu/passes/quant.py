"""Calibrated int8 serving as a pass pipeline (the "inference_int8" preset).

Three stages close the loop the QuantizeTranspiler port (ports.py
quantize_training) leaves open for serving:

- ``calibrate`` — an analysis client: runs representative feeds through the
  program CONCRETELY (registry.lower_ops, the same machinery the executors
  jit) and records each float tensor's observed absmax (or a percentile of
  |x|) across all feeds. The static facts from analysis/dataflow.py gate
  what gets recorded — only vars the analyzer proves to be floating-point
  tensors carry a range, so opaque/control-flow/host values never acquire
  bogus scales. Feeds ride ``ctx.attrs["calibrate"]``.
- ``quantize_serving`` — bakes the ranges in: weights freeze to int8 levels
  in the scope with a ``.scale.frozen`` const (the QuantizeTranspiler freeze
  idiom), calibrated activations gain a static-scale ``quantize_static`` op
  (no hot-path reduction — the whole point of calibration), ``mul`` swaps to
  ``int8_mul`` (int8×int8→i32 on the MXU), and the chained
  ``fake_dequantize_max_abs`` pair restores f32 with per-tensor scales.
- ``fuse_quant_gemm`` — tags the resulting int8_mul → dequant ×2
  [→ add [→ act]] chains for the fused Pallas lowering
  (ops/pallas_kernels.py ``gemm_int8``): the dequant multiplies collapse
  into the kernel's epilogue scale, so the calibrated layer runs as one
  kernel with one rounding. Tag-only, decline-safe (the PR 11 contract).

Per the measured deployment guidance (ops/quant_ops.py): int8 pays on
matmul-dominated serving, NOT on bandwidth-bound CNNs — so only ``mul``
(the fc producer) quantizes here; conv stays f32.
"""

import numpy as np

from ..framework import Operator, OpRole
from .pass_base import Pass, register_pass

__all__ = ["CalibratePass", "QuantizeServingPass", "FuseQuantGemmPass"]


@register_pass("calibrate")
class CalibratePass(Pass):
    """Record per-var activation ranges from representative feeds.

    ctx.attrs["calibrate"] = {
        "feeds": [ {feed name: array}, ... ],   # required to do anything
        "percentile": 99.9,                     # optional; default absmax
    }

    The result — {"ranges": {var name: float}, "feeds_run": n, "skipped":
    [...]} — lands in ctx.results["calibrate"] (consumed by
    quantize_serving later in the same pipeline) and is stamped onto the
    program as ``_calibration_ranges`` for callers. Degrades to a no-op
    without feeds or a scope (the PassContext contract)."""

    def apply(self, graph, ctx):
        import jax.numpy as jnp

        from ..ops import registry

        result = {"ranges": {}, "feeds_run": 0, "skipped": []}
        ctx.results[self.name] = result
        spec = dict(ctx.attrs.get("calibrate") or {})
        feeds = spec.get("feeds") or ()
        scope = ctx.scope
        if not feeds or scope is None:
            return

        # static facts gate the recording: only vars the dataflow analyzer
        # proves are floating tensors get a range (an int id feed, an opaque
        # control-flow value, a host-op output never acquire a scale)
        from ..analysis import analyze_program

        report = analyze_program(
            graph, feed_names=ctx.feed_names, fetch_names=ctx.fetch_names,
            scope=scope, mode="inference",
        )
        floaty = set()
        for name, fact in report.facts.items():
            if fact.kind != "tensor" or fact.dtype is None:
                continue
            if jnp.issubdtype(jnp.dtype(fact.dtype), jnp.floating):
                floaty.add(name)

        pct = spec.get("percentile")
        block = graph.program.global_block()
        ranges = {}
        import jax

        for feed in feeds:
            env = {n: jnp.asarray(v) for n, v in dict(feed).items()}
            lower_ctx = registry.LowerCtx(jax.random.key(0), is_test=True)
            for op in block.ops:
                opdef = (
                    registry.get(op.type)
                    if registry.is_registered(op.type)
                    else None
                )
                if opdef is None or opdef.skip_exec or opdef.is_host:
                    continue
                ready = True
                for n in op.input_arg_names:
                    if n == registry.EMPTY_VAR_NAME or n in env:
                        continue
                    val = scope.find_var(n)
                    if val is None:
                        ready = False
                        break
                    env[n] = jnp.asarray(val)
                if not ready:
                    result["skipped"].append(op.type)
                    continue
                try:
                    registry.lower_ops(lower_ctx, [op], env)
                except Exception:
                    result["skipped"].append(op.type)
                    continue
            for name, val in env.items():
                if name not in floaty or not hasattr(val, "dtype"):
                    continue
                a = jnp.abs(val.astype(jnp.float32))
                obs = (
                    jnp.percentile(a.ravel(), float(pct))
                    if pct is not None
                    else jnp.max(a)
                )
                obs = float(obs)
                if obs > ranges.get(name, 0.0):
                    ranges[name] = obs
            result["feeds_run"] += 1
        result["ranges"] = ranges
        result["skipped"] = sorted(set(result["skipped"]))
        graph.program._calibration_ranges = dict(ranges)


@register_pass("quantize_serving")
class QuantizeServingPass(Pass):
    """Bake calibrated scales into an int8 serving program (the static-scale
    sibling of ports.py quantize_training, fused with the transpiler's
    freeze/convert stages): per mul op whose weight lives in the scope and
    whose activation carries a calibrated range —

        x -> quantize_static(x, x.calib.scale) -> int8_mul(xq, Wq)
          -> fake_dequantize(s_act) -> fake_dequantize(W.scale.frozen) -> out

    The weight is re-typed int8 IN THE SCOPE (like fold_batch_norm this pass
    mutates parameter values, so it is preset-only-by-opt-in via
    inference_int8, never a default training pipeline member). Ranges come
    from ctx.results["calibrate"] (same pipeline) or
    ctx.attrs["quant_ranges"]. No scope / no ranges -> no-op."""

    def apply(self, graph, ctx):
        import jax.numpy as jnp

        from ..ops.quant_ops import _quant_levels

        result = {"quantized": 0, "weights_frozen": []}
        ctx.results[self.name] = result
        scope = ctx.scope
        ranges = dict(
            (ctx.results.get("calibrate") or {}).get("ranges")
            or ctx.attrs.get("quant_ranges")
            or {}
        )
        if scope is None or not ranges:
            return
        bits = int(
            dict(ctx.attrs.get("quantize") or {}).get("activation_bits", 8)
        )
        levels = _quant_levels(bits)
        block = graph.program.global_block()
        frozen = {}  # weight name -> scale const name
        quantized_acts = {}  # activation name -> (q var, scale const name)
        new_ops = []
        for op in block.ops:
            if op.type != "mul" or not op.output("Out"):
                new_ops.append(op)
                continue
            x_name = op.input("X")[0]
            w_name = op.input("Y")[0]
            w_val = scope.find_var(w_name)
            x_range = ranges.get(x_name)
            wv = block.vars.get(w_name)
            if (
                w_val is None
                or not x_range
                or wv is None
                or not wv.persistable
                or str(wv.dtype) not in ("float32", "float64", "bfloat16")
            ):
                new_ops.append(op)
                continue
            if w_name not in frozen:
                w = np.asarray(w_val, dtype=np.float32)
                w_scale = float(np.max(np.abs(w))) or 1.0
                qw = np.clip(
                    np.round(w / w_scale * levels), -levels, levels
                ).astype(np.int8)
                scope.set_var(w_name, jnp.asarray(qw))
                wv.dtype = "int8"
                sname = w_name + ".scale.frozen"
                block.create_var(
                    name=sname, shape=(1,), dtype="float32", persistable=True
                )
                scope.set_var(sname, jnp.asarray([w_scale], jnp.float32))
                frozen[w_name] = sname
                result["weights_frozen"].append(w_name)
            if x_name not in quantized_acts:
                a_sname = x_name + ".calib.scale"
                block.create_var(
                    name=a_sname, shape=(1,), dtype="float32",
                    persistable=True,
                )
                scope.set_var(
                    a_sname, jnp.asarray([float(x_range) or 1.0], jnp.float32)
                )
                xv = block._var_recursive(x_name)
                q = block.create_var(
                    name=x_name + ".q", shape=xv.shape, dtype="int8"
                )
                new_ops.append(
                    Operator(
                        block,
                        "quantize_static",
                        inputs={"X": [x_name], "Scale": [a_sname]},
                        outputs={"Out": [q.name]},
                        attrs={
                            "bit_length": bits,
                            OpRole.OP_ROLE_KEY: OpRole.Forward,
                        },
                    )
                )
                quantized_acts[x_name] = (q.name, a_sname)
            q_name, a_sname = quantized_acts[x_name]
            op.type = "int8_mul"
            op.inputs["X"] = [q_name]
            out = op.output("Out")[0]
            lvl = block.create_var(
                name=out + ".lvl", shape=block._var_recursive(out).shape,
                dtype="float32",
            )
            op.outputs["Out"] = [lvl.name]
            new_ops.append(op)
            # chained per-tensor dequant, the QuantizeTranspiler idiom:
            # out = lvl * (s_act/levels) * (s_w/levels)
            src = lvl.name
            for i, s in enumerate((a_sname, frozen[w_name])):
                dst = out if i == 1 else block.create_var(
                    name="%s.deq0" % out,
                    shape=block._var_recursive(out).shape,
                    dtype="float32",
                ).name
                new_ops.append(
                    Operator(
                        block,
                        "fake_dequantize_max_abs",
                        inputs={"X": [src], "Scale": [s]},
                        outputs={"Out": [dst]},
                        attrs={
                            "max_range": levels,
                            OpRole.OP_ROLE_KEY: OpRole.Forward,
                        },
                    )
                )
                src = dst
            result["quantized"] += 1
        if result["quantized"]:
            block.ops = new_ops
            graph.program._bump_version()
            graph.refresh()


@register_pass("fuse_quant_gemm")
class FuseQuantGemmPass(Pass):
    """Tag int8_mul → fake_dequantize ×2 [→ elementwise_add [→ act]] chains
    for the fused Pallas quant GEMM (ops/pallas_kernels.py ``gemm_int8``):
    dequant collapses into the kernel epilogue's combined scale. Strict slot
    equality like fuse_gemm_epilogue — the lowering replaces math, not just
    scoping — and every shape/dtype decision re-validates at trace time
    (decline falls back per-op)."""

    def apply(self, graph, ctx):
        from .builtin import _pallas_free, _tag_run

        ops = graph.program.global_block().ops
        groups = 0
        tagged = 0
        i = 0
        while i < len(ops):
            op = ops[i]
            if op.type != "int8_mul" or not _pallas_free(op):
                i += 1
                continue
            chain = self._chain_at(ops, i)
            if chain is None:
                i += 1
                continue
            _tag_run(chain, "qgemm%d" % groups, "gemm_int8")
            tagged += len(chain)
            groups += 1
            i += len(chain)
        ctx.results[self.name] = {"groups": groups, "ops_tagged": tagged}
        if groups:
            graph.program._bump_version()

    @staticmethod
    def _chain_at(ops, i):
        from .builtin import _PALLAS_GEMM_ACTS, _pallas_free

        prod = ops[i]
        if i + 2 >= len(ops) or not prod.output_arg_names:
            return None
        d1, d2 = ops[i + 1], ops[i + 2]
        if (
            d1.type != "fake_dequantize_max_abs"
            or d2.type != "fake_dequantize_max_abs"
            or not _pallas_free(d1)
            or not _pallas_free(d2)
            or d1.input("X") != [prod.output("Out")[0]]
            or d2.input("X") != [d1.output("Out")[0]]
        ):
            return None
        chain = [prod, d1, d2]
        if i + 3 < len(ops):
            add = ops[i + 3]
            if (
                add.type == "elementwise_add"
                and _pallas_free(add)
                and add.input("X") == [d2.output("Out")[0]]
            ):
                chain.append(add)
                if i + 4 < len(ops):
                    act = ops[i + 4]
                    if (
                        act.type in _PALLAS_GEMM_ACTS
                        and _pallas_free(act)
                        and act.input("X") == [add.output("Out")[0]]
                    ):
                        chain.append(act)
        return chain
