"""Transpiler rewrites ported onto the pass framework.

The vestigial one-off rewriters under transpiler/ — InferenceTranspiler's
conv+bn fold, memory_optimize's liveness-based buffer renaming, and the
QuantizeTranspiler training rewrite — live here as registered passes; the
old entry points are thin deprecated shims over these (transpiler/
inference_transpiler.py, transpiler/memory_optimization_transpiler.py).
"""

import numpy as np

from ..framework import Operator, OpRole
from .pass_base import Pass, register_pass

__all__ = ["FoldBatchNormPass", "MemoryOptimizePass", "QuantizeTrainingPass"]


@register_pass("fold_batch_norm")
class FoldBatchNormPass(Pass):
    """Fold inference-mode batch_norm into the preceding conv's weights
    (reference inference_transpiler.py fuse_batch_norm):

        W' = W * gamma / sqrt(var + eps)        (per output channel)
        b' = (b - mean) * gamma / sqrt(var + eps) + beta

    Patterns: conv2d → batch_norm and conv2d → elementwise_add → batch_norm.
    Rewrites the conv weights IN THE SCOPE (ctx.scope required — no-op
    without one) and drops the bn op and its four state reads. Not part of
    the default presets exactly because of that scope mutation: it is the
    InferenceTranspiler shim's delegate and an opt-in pipeline member."""

    def apply(self, graph, ctx):
        scope = ctx.scope
        result = {"folded": 0}
        ctx.results[self.name] = result
        if scope is None:
            return
        block = graph.program.global_block()
        i = 0
        while i < len(block.ops):
            trio = self._match(block, i)
            if trio is None:
                i += 1
                continue
            conv_op, add_op, bn_op = trio
            self._fold(block, scope, conv_op, add_op, bn_op)
            result["folded"] += 1
            graph.program._bump_version()
            i = block.ops.index(conv_op) + 1  # indices shifted; rescan
        if result["folded"]:
            graph.refresh()

    @staticmethod
    def _match(block, i):
        """(conv, add_or_None, bn) rooted at op i, else None."""
        ops = block.ops
        op = ops[i]
        if op.type not in ("conv2d", "depthwise_conv2d") or not op.output(
            "Output"
        ):
            return None
        out = op.output("Output")[0]
        users = [o for o in ops if out in o.input_arg_names]
        if len(users) != 1:
            return None
        nxt = users[0]
        add_op = None
        if nxt.type == "elementwise_add" and nxt.input("X") == [out]:
            add_out = nxt.output("Out")[0]
            users2 = [o for o in ops if add_out in o.input_arg_names]
            if len(users2) != 1:
                return None
            add_op, nxt = nxt, users2[0]
        if nxt.type == "batch_norm" and nxt.attrs.get("is_test", False):
            return (op, add_op, nxt)
        return None

    @staticmethod
    def _fold(block, scope, conv_op, add_op, bn_op):
        import jax.numpy as jnp

        w_name = conv_op.input("Filter")[0]
        gamma = np.asarray(scope.find_var(bn_op.input("Scale")[0]))
        beta = np.asarray(scope.find_var(bn_op.input("Bias")[0]))
        mean = np.asarray(scope.find_var(bn_op.input("Mean")[0]))
        var = np.asarray(scope.find_var(bn_op.input("Variance")[0]))
        eps = float(bn_op.attrs.get("epsilon", 1e-5))
        std_inv = gamma / np.sqrt(var + eps)

        w = np.asarray(scope.find_var(w_name), dtype=np.float32)
        # conv filter layout (out_c, in_c, kh, kw): scale per out channel
        w = w * std_inv.reshape((-1,) + (1,) * (w.ndim - 1))
        scope.set_var(w_name, jnp.asarray(w))

        bn_out = bn_op.output("Y")[0]
        if add_op is not None:
            # existing bias: b' = (b - mean) * std_inv + beta
            b_name = add_op.input("Y")[0]
            b = np.asarray(scope.find_var(b_name), dtype=np.float32)
            scope.set_var(b_name, jnp.asarray((b - mean) * std_inv + beta))
            add_op.outputs["Out"] = [bn_out]
        else:
            # no bias add: introduce one carrying the folded shift
            b_name = w_name + ".bn_bias"
            block.create_var(
                name=b_name,
                shape=(len(beta),),
                dtype="float32",
                persistable=True,
            )
            scope.set_var(b_name, jnp.asarray(beta - mean * std_inv))
            conv_out = conv_op.output("Output")[0]
            idx = block.ops.index(bn_op)
            block.ops[idx] = Operator(
                block,
                "elementwise_add",
                inputs={"X": [conv_out], "Y": [b_name]},
                outputs={"Out": [bn_out]},
                attrs={"axis": 1, OpRole.OP_ROLE_KEY: OpRole.Forward},
            )
            return
        # drop the bn op (its output now produced by the add)
        block.ops.remove(bn_op)


# ops whose outputs alias inputs or that the renamer must not touch
# (reference SUB_BLOCK_OPS + skip list)
_SKIP_OP_TYPES = frozenset(
    ["while", "conditional_block", "recurrent", "listen_and_serv"]
)


class _Liveness:
    """Backward liveness over the straight-line op list (the reference's
    ControlFlowGraph restricted to block 0, which is where it applies it)."""

    def __init__(self, block, protected):
        self.block = block
        self.protected = protected
        n = len(block.ops)
        self.live_after = [set() for _ in range(n)]
        live = set(protected)
        for i in range(n - 1, -1, -1):
            op = block.ops[i]
            self.live_after[i] = set(live)
            live -= set(op.output_arg_names)
            live |= set(op.input_arg_names)


@register_pass("memory_optimize")
class MemoryOptimizePass(Pass):
    """Liveness-based buffer renaming (reference
    memory_optimization_transpiler.py ControlFlowGraph :113 / entry :457):
    later intermediates are renamed onto dead earlier vars of identical
    dtype+shape so values materializing at feed/fetch and host-op segment
    boundaries reuse names. Inside one jitted block XLA's buffer assignment
    already does this optimally — see the shim module docstring for why the
    transform is kept. Knobs ride ctx.attrs: `skip_opt_set` (iterable of
    protected names), `print_log` (report the reuse plan). The mapping
    {renamed_var: buffer_it_now_occupies} lands in ctx.results."""

    def apply(self, graph, ctx):
        block = graph.program.global_block()
        skip = set(ctx.attrs.get("skip_opt_set") or ())
        print_log = bool(ctx.attrs.get("print_log", False))
        protected = set(skip) | set(ctx.fetch_names) | set(ctx.feed_names)
        for name, v in block.vars.items():
            if v.persistable or v.is_data or getattr(v, "stop_gradient", False):
                protected.add(name)
        # vars referenced by sub-block ops stay untouched (reference
        # SUB_BLOCK_PAIR handling): renaming across block boundaries is not
        # worth the risk
        protected |= graph.subblock_reachable_names()
        for op in block.ops:
            if op.type in _SKIP_OP_TYPES:
                protected.update(op.input_arg_names)
                protected.update(op.output_arg_names)

        liveness = _Liveness(block, protected)
        free_pool = {}  # (dtype, shape) -> [buffer names free for reuse]
        mapping = {}  # original var name -> buffer name it now occupies
        occupants = {}  # buffer name -> set of original names mapped onto it

        def pool_key(v):
            # Exact dtype+shape match, with a dynamic (-1) dim allowed: two
            # vars whose static shapes are identical occupy equal-size
            # buffers at runtime even when the batch dim is symbolic (the
            # reference compares shapes the same way,
            # memory_optimization_transpiler.py:150-163).
            if v.shape is None:
                return None
            return (v.dtype, tuple(v.shape))

        for i, op in enumerate(block.ops):
            # inputs were defined earlier — apply their renames
            for slot, names in op.inputs.items():
                op.inputs[slot] = [mapping.get(n, n) for n in names]
            # outputs defined here: try to place each onto a free dead buffer
            for out in op.output_arg_names:
                if out in protected or out in mapping or not block.has_var(out):
                    continue
                key = pool_key(block.var(out))
                if key is None:
                    continue
                candidates = free_pool.get(key)
                if candidates:
                    buf = candidates.pop()
                    mapping[out] = buf
                    occupants.setdefault(buf, set()).add(out)
            for slot, names in op.outputs.items():
                op.outputs[slot] = [mapping.get(n, n) for n in names]
            # original vars whose live range ends here free their buffer
            live = liveness.live_after[i]
            for name in set(op.input_arg_names) | set(op.output_arg_names):
                # `name` is a buffer name; free only once every original
                # mapped onto it (and itself) is dead
                originals = occupants.get(name) or (name,)
                if name in live or any(o in live for o in originals):
                    continue
                if name in protected or not block.has_var(name):
                    continue
                key = pool_key(block.var(name))
                if key is None:
                    continue
                lst = free_pool.setdefault(key, [])
                if name not in lst:
                    lst.append(name)

        # drop now-unreferenced vars
        if mapping:
            used = set()
            for op in block.ops:
                used.update(op.input_arg_names)
                used.update(op.output_arg_names)
            for old in list(block.vars):
                if old in mapping and old not in used:
                    del block.vars[old]
            graph.program._bump_version()
            graph.refresh()

        if print_log:
            saved = 0
            for new, old in mapping.items():
                v = block.vars.get(old) or block.vars.get(new)
                if v is None or v.shape is None:
                    continue
                # product of known dims: per-sample bytes when batch dim is -1
                n = 1
                for d in v.shape:
                    n *= d if d and d > 0 else 1
                saved += n * np.dtype(
                    "float32" if v.dtype == "bfloat16" else v.dtype
                ).itemsize
            print(
                "memory_optimize: reused %d buffers (~%.1f KB/sample "
                "host-visible)" % (len(mapping), saved / 1024.0)
            )
        ctx.results[self.name] = {"mapping": mapping, "reused": len(mapping)}


@register_pass("quantize_training")
class QuantizeTrainingPass(Pass):
    """Quantization-aware-training rewrite as a pass: inserts fake
    quant/dequant pairs around every quantizable op (delegates to
    transpiler.quantize_transpiler.QuantizeTranspiler.training_transpile,
    which stays the public API for the freeze/int8-convert stages).
    Constructor knobs ride ctx.attrs["quantize"] (weight_bits,
    activation_bits, *_quantize_type, window_size)."""

    def apply(self, graph, ctx):
        from ..transpiler.quantize_transpiler import QuantizeTranspiler

        before = graph.num_ops()
        qt = QuantizeTranspiler(**dict(ctx.attrs.get("quantize") or {}))
        qt.training_transpile(program=graph.program)
        graph.refresh()
        ctx.results[self.name] = {"ops_inserted": graph.num_ops() - before}
