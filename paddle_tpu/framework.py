"""Core graph IR: Program / Block / Operator / Variable / Parameter.

This is the define-then-run program representation, API-compatible with the
reference's Python frontend (/root/reference/python/paddle/fluid/framework.py:
Program:1466, Block:964, Operator:521, Variable:216, Parameter:2060,
program_guard:2212). Unlike the reference — where these objects shadow C++
protobuf `OpDesc`/`VarDesc` (framework.proto) that a C++ per-op executor
interprets — here the Program IS the source of truth, and the executor lowers a
whole block into a single XLA computation via JAX (see executor.py). Ops carry
string-keyed input/output slots and attribute dicts exactly like OpDesc, so
programs serialize to the same structural schema (see Program.to_dict).

TPU-first notes:
- shapes are static; -1 is allowed only in the leading (batch) dim of data vars
  and is resolved at feed time (shape-keyed executable cache).
- there is no Scope here: variables are names; values live in executor scopes.
"""

import contextlib
import copy
import itertools

import numpy as np

from . import unique_name

__all__ = [
    "Program",
    "Block",
    "Operator",
    "Variable",
    "Parameter",
    "default_main_program",
    "default_startup_program",
    "switch_main_program",
    "switch_startup_program",
    "program_guard",
    "name_scope",
    "device_guard",
    "grad_var_name",
    "convert_np_dtype",
]

GRAD_VAR_SUFFIX = "@GRAD"
ZERO_VAR_SUFFIX = "@ZERO"


def grad_var_name(var_name):
    """Gradient variable naming convention (reference framework.py:grad_var_name)."""
    return var_name + GRAD_VAR_SUFFIX


class VarType:
    """Variable kinds, mirroring framework.proto VarType (reference
    framework.proto:101-146, 17 kinds). Only the ones meaningful on TPU are
    kept; LOD_TENSOR covers dense (ragged handled via explicit seq-len vars)."""

    LOD_TENSOR = "lod_tensor"
    SELECTED_ROWS = "selected_rows"  # sparse (rows, values) gradient pairs
    LOD_TENSOR_ARRAY = "lod_tensor_array"
    STEP_SCOPES = "step_scopes"
    READER = "reader"
    RAW = "raw"


class OpRole:
    """Op role attr used by backward/optimizer/multi-device passes (reference
    op_proto_maker.h OpRole). Stored on every op as attr `op_role`."""

    # Bitmask values match reference op_proto_maker.h (kRPC = 0x0004,
    # kDist = 0x0008) so role tests like `role & Optimize` never match
    # RPC/Dist-role ops.
    Forward = 0
    Backward = 1
    Optimize = 2
    RPC = 4
    Dist = 8
    LRSched = 16
    Loss = 256

    OP_ROLE_KEY = "op_role"
    OP_ROLE_VAR_KEY = "op_role_var"


# Explicit pipeline-stage pin (reference fluid.device_guard("gpu:2") inside
# the pipeline optimizer era). Stored on every op appended under an active
# device_guard; the ParallelExecutor pp partitioner treats it as an override
# of the analytic balanced cut (parallel/partition.py).
PIPELINE_STAGE_ATTR = "__pipeline_stage__"

_device_guard_stack = []


@contextlib.contextmanager
def device_guard(device=None):
    """Pin ops appended inside to a pipeline stage (reference fluid
    device_guard). Accepted spellings: "pp:<k>" / "gpu:<k>" / "stage:<k>"
    (the reference pins pipeline sections to devices; here the mesh owns
    placement, so the integer is a pp STAGE index). device=None/"cpu"
    clears the pin for the region (host-side data ops in the reference)."""
    stage = None
    if device is not None and device != "cpu":
        dev = str(device)
        if ":" not in dev:
            raise ValueError(
                "device_guard expects 'pp:<stage>' (or reference-style "
                "'gpu:<stage>'), got %r" % (device,)
            )
        prefix, _, idx = dev.partition(":")
        if prefix not in ("pp", "gpu", "stage"):
            raise ValueError("unknown device_guard prefix %r" % prefix)
        stage = int(idx)
        if stage < 0:
            raise ValueError("pipeline stage must be >= 0, got %d" % stage)
    _device_guard_stack.append(stage)
    try:
        yield
    finally:
        _device_guard_stack.pop()


def _current_pipeline_stage():
    return _device_guard_stack[-1] if _device_guard_stack else None


# TPU-first canonicalization: no fast f64/i64 path on TPU, so (like JAX's
# default dtype canonicalization) wide types narrow at the framework boundary.
_np_to_canonical = {
    "float64": "float32",
    "float32": "float32",
    "float16": "float16",
    "bfloat16": "bfloat16",
    "int64": "int32",
    "int32": "int32",
    "int16": "int16",
    "int8": "int8",
    "uint8": "uint8",
    "bool": "bool",
}

# framework.proto VarType.Type enum values (reference framework.proto:91-100)
# accepted for compatibility with fluid scripts passing core.VarDesc dtypes.
_proto_dtype_to_name = {
    0: "bool",
    1: "int16",
    2: "int32",
    3: "int64",
    4: "float16",
    5: "float32",
    6: "float64",
    8: "int8",
    20: "uint8",
    22: "bfloat16",
}


def convert_np_dtype(dtype):
    """Normalize a dtype spec (np.dtype / str / jnp dtype / proto enum int) to
    a canonical string."""
    if dtype is None:
        return None
    if isinstance(dtype, int):
        dtype = _proto_dtype_to_name[dtype]
    name = getattr(dtype, "name", None)
    if name is None:
        try:
            name = np.dtype(dtype).name
        except TypeError:
            name = str(dtype)
    if name == "bfloat16" or "bfloat16" in str(dtype):
        return "bfloat16"
    if name not in _np_to_canonical:
        raise ValueError("unsupported dtype: %r" % (dtype,))
    return _np_to_canonical[name]


def is_float_dtype(dtype):
    return dtype in ("float64", "float32", "float16", "bfloat16")


class Variable:
    """A named tensor in a Block (reference framework.py:216). Holds static
    metadata only — shape, dtype, persistable, stop_gradient, lod_level —
    values live in an executor Scope at run time."""

    def __init__(
        self,
        block,
        name=None,
        shape=None,
        dtype="float32",
        type=VarType.LOD_TENSOR,
        lod_level=0,
        persistable=False,
        stop_gradient=False,
        is_data=False,
        initializer=None,
        **kwargs,
    ):
        self.block = block
        if name is None:
            name = unique_name.generate("_generated_var")
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = convert_np_dtype(dtype) if dtype is not None else None
        self.type = type
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        # set by layers.io.data for feed vars whose batch dim is -1
        self.desc = self  # compat shim: reference code reaches var.desc

    @property
    def grad_name(self):
        return grad_var_name(self.name)

    def __str__(self):
        return "Variable(name=%s, shape=%s, dtype=%s%s)" % (
            self.name,
            self.shape,
            self.dtype,
            ", persistable" if self.persistable else "",
        )

    __repr__ = __str__

    def to_dict(self):
        return {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "type": self.type,
            "lod_level": self.lod_level,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "is_data": self.is_data,
            "is_parameter": isinstance(self, Parameter),
            "trainable": getattr(self, "trainable", None),
        }

    # --- operator sugar (reference math_op_patch.py monkey-patches these) ---
    def _binary(self, other, op, reverse=False):
        from .layers import math_op_patch

        return math_op_patch.binary_op(self, other, op, reverse)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __rsub__(self, other):
        return self._binary(other, "elementwise_sub", reverse=True)

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")

    def __rtruediv__(self, other):
        return self._binary(other, "elementwise_div", reverse=True)

    def __pow__(self, other):
        return self._binary(other, "elementwise_pow")

    def __neg__(self):
        from .layers import tensor as tensor_layers

        return tensor_layers.scale(self, scale=-1.0)

    def __lt__(self, other):
        return self._binary(other, "less_than")

    def __le__(self, other):
        return self._binary(other, "less_equal")

    def __gt__(self, other):
        return self._binary(other, "greater_than")

    def __ge__(self, other):
        return self._binary(other, "greater_equal")

    def __eq__(self, other):  # graph-eq, like the reference's patched Variable
        if isinstance(other, (Variable, int, float)):
            return self._binary(other, "equal")
        return NotImplemented

    def __ne__(self, other):
        if isinstance(other, (Variable, int, float)):
            return self._binary(other, "not_equal")
        return NotImplemented

    def __hash__(self):
        return id(self)

    def astype(self, dtype):
        from .layers import tensor as tensor_layers

        return tensor_layers.cast(self, dtype)


class Parameter(Variable):
    """Trainable persistable variable (reference framework.py:2060). Carries
    optimizer-facing attrs: trainable, optimize_attr (learning_rate scale),
    regularizer, gradient clip attr."""

    def __init__(self, block, shape, dtype, **kwargs):
        if shape is None or dtype is None:
            raise ValueError("Parameter needs shape and dtype")
        for d in shape:
            if d < 0:
                raise ValueError("Parameter shape must be static, got %s" % (shape,))
        kwargs.setdefault("persistable", True)
        super().__init__(block, shape=shape, dtype=dtype, **kwargs)
        self.trainable = kwargs.get("trainable", True)
        self.optimize_attr = kwargs.get("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.get("regularizer", None)
        self.gradient_clip_attr = kwargs.get("gradient_clip_attr", None)
        self.do_model_average = kwargs.get("do_model_average", None)


class Operator:
    """One op in a block (reference framework.py:521 / C++ OpDesc). Inputs and
    outputs are slot-name -> [variable names]; attrs is a plain dict whose
    values must be JSON-able (bool/int/float/str/lists) or Block references
    (control flow)."""

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})
        self.attrs.setdefault(OpRole.OP_ROLE_KEY, _current_role())
        # ops created under _optimized_guard carry their (param, grad) pair —
        # the seam the multi-device pass and DistributeTranspiler key on
        # (reference op_proto_maker.h OpRoleVar)
        role_var = _current_role_var()
        if role_var and OpRole.OP_ROLE_VAR_KEY not in self.attrs:
            self.attrs[OpRole.OP_ROLE_VAR_KEY] = list(role_var)
        stage = _current_pipeline_stage()
        if stage is not None and PIPELINE_STAGE_ATTR not in self.attrs:
            self.attrs[PIPELINE_STAGE_ATTR] = stage

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for ns in self.inputs.values() for n in ns]

    @property
    def output_arg_names(self):
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name):
        return self.attrs[name]

    def has_attr(self, name):
        return name in self.attrs

    def _rename_input(self, old, new):
        for slot, names in self.inputs.items():
            self.inputs[slot] = [new if n == old else n for n in names]

    def _rename_output(self, old, new):
        for slot, names in self.outputs.items():
            self.outputs[slot] = [new if n == old else n for n in names]

    def to_dict(self):
        def _attr(v):
            if isinstance(v, Block):
                return {"__block__": v.idx}
            return v

        return {
            "type": self.type,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "attrs": {k: _attr(v) for k, v in self.attrs.items()},
        }

    def __str__(self):
        ins = ", ".join("%s=%s" % kv for kv in sorted(self.inputs.items()))
        outs = ", ".join("%s=%s" % kv for kv in sorted(self.outputs.items()))
        return "{%s} = %s(%s)" % (outs, self.type, ins)

    __repr__ = __str__


class Block:
    """Ordered op list + var map (reference framework.py:964). Sub-blocks (for
    while/cond) link via parent_idx."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = {}  # name -> Variable
        self.ops = []  # [Operator]

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    def var(self, name):
        v = self.vars.get(name)
        if v is None:
            raise KeyError("var %r not in block %d" % (name, self.idx))
        return v

    def has_var(self, name):
        return name in self.vars

    def _var_recursive(self, name):
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        raise KeyError("var %r not found in block %d or ancestors" % (name, self.idx))

    def has_var_recursive(self, name):
        try:
            self._var_recursive(name)
            return True
        except KeyError:
            return False

    def create_var(self, **kwargs):
        name = kwargs.get("name")
        if name is not None and name in self.vars:
            return self.vars[name]
        v = Variable(self, **kwargs)
        self.vars[v.name] = v
        self.program._bump_version()
        return v

    def create_parameter(self, **kwargs):
        p = Parameter(self, **kwargs)
        # parameters are global: registered on block 0 like the reference
        gblock = self.program.global_block()
        gblock.vars[p.name] = p
        p.block = gblock
        self.program._bump_version()
        return p

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.append(op)
        self._infer_shape(op)
        self.program._bump_version()
        return op

    def _prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(0, op)
        self._infer_shape(op)
        self.program._bump_version()
        return op

    def _insert_op(self, index, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(index, op)
        self._infer_shape(op)
        self.program._bump_version()
        return op

    def _remove_op(self, index):
        del self.ops[index]
        self.program._bump_version()

    def _infer_shape(self, op):
        """Run the registered shape/dtype inference so downstream layers see
        concrete metadata at graph-build time (reference: OpDesc InferShape
        called from Operator.__init__, framework.py:667)."""
        from .ops import registry

        registry.infer_shape(op, self)

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def iter_parameters(self):
        return iter(self.all_parameters())

    def to_dict(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [op.to_dict() for op in self.ops],
        }

    def __str__(self):
        lines = ["block %d (parent %d):" % (self.idx, self.parent_idx)]
        for v in self.vars.values():
            lines.append("  " + str(v))
        for op in self.ops:
            lines.append("  " + str(op))
        return "\n".join(lines)


class Program:
    """A whole trainable program: list of Blocks, block 0 global (reference
    framework.py:1466). `clone()` deep-copies the graph; `_version` increments
    on any mutation and keys the executor's executable cache."""

    _uid_counter = itertools.count()

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._version = 0
        # monotonic uid: executor caches key on this instead of id(self) so a
        # new Program can never alias a GC'd one's cache entries
        self._uid = next(Program._uid_counter)
        self._op_role = OpRole.Forward
        self._op_role_var = []
        self._is_test = False

    # --- structure ---
    def global_block(self):
        return self.blocks[0]

    def block(self, idx):
        return self.blocks[idx]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx=None):
        new_idx = len(self.blocks)
        parent = self.current_block_idx if parent_idx is None else parent_idx
        self.blocks.append(Block(self, new_idx, parent_idx=parent))
        self.current_block_idx = new_idx
        return self.current_block()

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def _bump_version(self):
        self._version += 1

    @property
    def num_blocks(self):
        return len(self.blocks)

    # --- op role plumbing (used by backward/optimizer, reference :1504-1563) ---
    @contextlib.contextmanager
    def _optimized_guard(self, param_and_grads):
        old_role, old_var = self._op_role, self._op_role_var
        self._op_role = OpRole.Optimize
        self._op_role_var = [
            v.name if isinstance(v, Variable) else v for v in param_and_grads
        ]
        yield
        self._op_role, self._op_role_var = old_role, old_var

    @contextlib.contextmanager
    def _lr_schedule_guard(self):
        old_role = self._op_role
        self._op_role = OpRole.LRSched
        yield
        self._op_role = old_role

    @contextlib.contextmanager
    def _backward_role_guard(self):
        old_role = self._op_role
        self._op_role = OpRole.Backward
        yield
        self._op_role = old_role

    # --- cloning / pruning ---
    def clone(self, for_test=False):
        """Deep copy. for_test=True flips `is_test` attrs (dropout/batch_norm
        switch to inference behavior), mirroring reference clone(for_test)
        + inference_optimize (framework.py:1616-1700)."""
        p = Program()
        p.random_seed = self.random_seed
        p.blocks = []
        for blk in self.blocks:
            nb = Block(p, blk.idx, blk.parent_idx)
            p.blocks.append(nb)
        for blk, nb in zip(self.blocks, p.blocks):
            for name, v in blk.vars.items():
                if isinstance(v, Parameter):
                    nv = Parameter(
                        nb,
                        shape=v.shape,
                        dtype=v.dtype,
                        name=v.name,
                        trainable=v.trainable,
                        optimize_attr=copy.copy(v.optimize_attr),
                        regularizer=v.regularizer,
                        gradient_clip_attr=v.gradient_clip_attr,
                    )
                else:
                    nv = Variable(
                        nb,
                        name=v.name,
                        shape=v.shape,
                        dtype=v.dtype,
                        type=v.type,
                        lod_level=v.lod_level,
                        persistable=v.persistable,
                        stop_gradient=v.stop_gradient,
                        is_data=v.is_data,
                    )
                nb.vars[name] = nv
            for op in blk.ops:
                if for_test and (
                    int(op.attrs.get(OpRole.OP_ROLE_KEY, OpRole.Forward))
                    & (OpRole.Backward | OpRole.Optimize | OpRole.LRSched)
                ):
                    # reference clone(for_test) prunes the backward/optimizer/
                    # lr-schedule ops (inference_optimize); without this the
                    # "test" program still trains — an sgd step runs on every
                    # inference call
                    continue
                attrs = {}
                for k, val in op.attrs.items():
                    if isinstance(val, Block):
                        attrs[k] = p.blocks[val.idx]
                    else:
                        attrs[k] = copy.copy(val)
                if for_test and "is_test" in attrs:
                    attrs["is_test"] = True
                nop = Operator(
                    nb, op.type, inputs=op.inputs, outputs=op.outputs, attrs=attrs
                )
                nb.ops.append(nop)
        p._is_test = for_test
        p._bump_version()
        return p

    def _prune(self, targets):
        """Keep only ops needed to compute `targets` (names or Variables) —
        used by save_inference_model (reference prune.cc + framework.py:1601)."""
        target_names = set(
            t.name if isinstance(t, Variable) else t for t in targets
        )
        p = self.clone()
        blk = p.global_block()
        needed = set(target_names)
        kept = []
        for op in reversed(blk.ops):
            if any(o in needed for o in op.output_arg_names):
                kept.append(op)
                needed.update(op.input_arg_names)
        blk.ops = list(reversed(kept))
        used = set()
        for op in blk.ops:
            used.update(op.input_arg_names)
            used.update(op.output_arg_names)
        blk.vars = {
            n: v for n, v in blk.vars.items() if n in used or n in target_names
        }
        p._bump_version()
        return p

    def list_vars(self):
        for blk in self.blocks:
            for v in blk.vars.values():
                yield v

    def to_dict(self):
        return {
            "version": 1,
            "random_seed": self.random_seed,
            "blocks": [b.to_dict() for b in self.blocks],
        }

    @staticmethod
    def from_dict(d):
        p = Program()
        p.random_seed = d.get("random_seed", 0)
        p.blocks = []
        for bd in d["blocks"]:
            blk = Block(p, bd["idx"], bd.get("parent_idx", -1))
            p.blocks.append(blk)
        for bd, blk in zip(d["blocks"], p.blocks):
            for vd in bd["vars"]:
                cls_kwargs = dict(
                    name=vd["name"],
                    shape=vd["shape"],
                    dtype=vd["dtype"],
                    type=vd.get("type", VarType.LOD_TENSOR),
                    lod_level=vd.get("lod_level", 0),
                    persistable=vd.get("persistable", False),
                    stop_gradient=vd.get("stop_gradient", False),
                    is_data=vd.get("is_data", False),
                )
                if vd.get("is_parameter"):
                    v = Parameter(
                        blk,
                        shape=vd["shape"],
                        dtype=vd["dtype"],
                        name=vd["name"],
                        trainable=vd.get("trainable", True),
                    )
                else:
                    v = Variable(blk, **cls_kwargs)
                blk.vars[v.name] = v
            for od in bd["ops"]:
                attrs = {}
                for k, val in od["attrs"].items():
                    if isinstance(val, dict) and "__block__" in val:
                        attrs[k] = p.blocks[val["__block__"]]
                    else:
                        attrs[k] = val
                op = Operator(
                    blk, od["type"], inputs=od["inputs"], outputs=od["outputs"], attrs=attrs
                )
                blk.ops.append(op)
        p._bump_version()
        return p

    def to_string(self, throw_on_error=False):
        return "\n".join(str(b) for b in self.blocks)

    __str__ = to_string


def _current_role():
    prog = _main_program_
    return prog._op_role if prog is not None else OpRole.Forward


def _current_role_var():
    prog = _main_program_
    return prog._op_role_var if prog is not None else []


_main_program_ = Program()
_startup_program_ = Program()


def default_main_program():
    return _main_program_


def default_startup_program():
    return _startup_program_


def switch_main_program(program):
    global _main_program_
    old = _main_program_
    _main_program_ = program
    return old


def switch_startup_program(program):
    global _startup_program_
    old = _startup_program_
    _startup_program_ = program
    return old


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    yield
    switch_main_program(old_main)
    if old_startup is not None:
        switch_startup_program(old_startup)


_name_scope_stack = []


@contextlib.contextmanager
def name_scope(prefix=None):
    """Cosmetic op-name namespacing (reference framework.py:91)."""
    _name_scope_stack.append(prefix or "")
    yield
    _name_scope_stack.pop()
