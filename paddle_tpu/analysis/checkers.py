"""fluidlint: registered checkers over an `Analysis` (analysis/dataflow.py).

Each checker is a pure function `fn(analysis) -> iterable[Finding]`
registered under a stable check id — the string a finding carries, the CLI
filters on, and the seeded-defect tests assert. The catalog
(docs/static_analysis.md):

- donation-alias   (error)   the inplace_donation_plan disagrees with the
                             lowering's mut/ro state classification —
                             statically pre-empts _CompiledBlock's runtime
                             divergence raise (executor.py).
- sharding-rules   (mixed)   rule rank exceeds an explicit target's rank
                             (error); dead rules matching zero vars and
                             silent divisibility degradation (warnings) —
                             the lint face of parallel/sharding_rules.
- dtype-boundary   (warning) an op mixes 16-bit and 32-bit float inputs
                             without an explicit cast — silent upcast
                             drift at op edges.
- determinism      (error)   stochastic or host ops reachable in an
                             inference/serving program.
- dead-write       (warning) a non-persistable value overwritten before
                             any read (shadowed store).
- write-never-read (warning) an op none of whose outputs are ever read,
                             fetched, or persisted — dead code.
- fetch-unwritten  (error)   a fetch name no op writes, nothing feeds, and
                             no scope/persistable var backs — pre-empts
                             the executor's "fetch var has no value".
- cf-capture       (error)   a sub-block reads a parent var not threaded
                             through the control-flow op's inputs (KeyError
                             deep inside the trace, and a donation-alias
                             hazard), or writes a parent var the op does
                             not output (silently dropped by the
                             functional lowering).

`lint_program` is the one-call entry: analyze + run checkers; `deep=False`
skips the forward interpretation for the structural subset the PassManager
re-runs per pass (analysis/verify.py).
"""

import re

from ..framework import Block as _Block
from ..ops import registry
from .dataflow import Analysis, SymDim, analyze_program

__all__ = [
    "Finding",
    "CHECKERS",
    "STRUCTURAL_CHECKS",
    "register_checker",
    "lint_program",
    "run_checkers",
    "render_findings",
]

ERROR = "error"
WARNING = "warning"

# ops whose value is their side effect, never their outputs
_SIDE_EFFECT_OPS = frozenset({"print"})

# deliberate mixed-precision seams: explicit casts and the optimizer tier
# (master f32 math over bf16 moments/params is the design, core_ops._opt_f32)
def _dtype_boundary_exempt():
    from ..ops.core_ops import ZERO1_STATE_SLOTS

    return frozenset({"cast", "sgd"}) | frozenset(ZERO1_STATE_SLOTS)


class Finding:
    """One lint finding with op/var provenance. `op_display` is the
    "<type>:<first output>" instance handle (observability/opprof.py) —
    fluid ops are anonymous, outputs are the stable identity."""

    __slots__ = (
        "check", "severity", "message", "var", "block_idx", "op_index",
        "op_type", "op_display",
    )

    def __init__(self, check, severity, message, var=None, block_idx=None,
                 op_index=None, op_type=None, op_display=None):
        self.check = check
        self.severity = severity
        self.message = message
        self.var = var
        self.block_idx = block_idx
        self.op_index = op_index
        self.op_type = op_type
        self.op_display = op_display

    def format(self):
        where = ""
        if self.block_idx is not None and self.op_index is not None:
            where = " b%d/op%d" % (self.block_idx, self.op_index)
        op = " %s" % self.op_display if self.op_display else ""
        var = " var=%r" % self.var if self.var else ""
        return "%s[%s]%s%s%s: %s" % (
            self.severity, self.check, where, op, var, self.message
        )

    def __repr__(self):
        return "Finding(%s)" % self.format()


def _op_finding(check, severity, message, op=None, block_idx=None,
                op_index=None, var=None):
    display = None
    if op is not None:
        from ..observability.opprof import op_display_name

        display = op_display_name(op)
    return Finding(
        check, severity, message, var=var, block_idx=block_idx,
        op_index=op_index, op_type=op.type if op is not None else None,
        op_display=display,
    )


def _node_site(a, name, block_idx=0):
    """(op, block_idx, op_index) of the last producer of `name`, else its
    first consumer, else Nones — the provenance handle for var-keyed
    findings."""
    vn = a.graph.var_node(name, block_idx)
    if vn is not None:
        if vn.producers:
            n = vn.producers[-1]
            return n.op, n.block_idx, n.index
        if vn.consumers:
            n = vn.consumers[0]
            return n.op, n.block_idx, n.index
    return None, None, None


CHECKERS = {}  # check id -> fn(analysis) -> iterable[Finding]

# checkers needing no forward facts — the cheap subset the PassManager
# re-runs after every pass (analysis/verify.py verify_graph)
STRUCTURAL_CHECKS = ("cf-capture", "fetch-unwritten", "donation-alias")


def register_checker(check_id):
    """Decorator registering a checker under a stable id (the ops/registry
    idiom). Re-registration raises — a silent shadow would make lint
    results depend on import order."""

    def deco(fn):
        if check_id in CHECKERS and CHECKERS[check_id] is not fn:
            raise ValueError("checker %r already registered" % check_id)
        CHECKERS[check_id] = fn
        return fn

    return deco


# ---------------------------------------------------------------------------
# 1. donation-alias
# ---------------------------------------------------------------------------


@register_checker("donation-alias")
def _check_donation_alias(a):
    """Recompute the lowering's mut/ro state classification exactly as
    _CompiledBlock does (executor.py) and diff it against the program's
    riding inplace_donation_plan — a divergence means a donated buffer
    would back a read-only value (use-after-donate) or a mutated buffer
    would skip donation. The executor raises at compile; this pre-empts."""
    plan = getattr(a.program, "_donation_plan", None)
    if not plan or plan.get("unknown"):
        return
    scope = a.scope
    if scope is None or plan.get("scope_uid") != getattr(scope, "_uid", None):
        return
    if plan.get("feed") != sorted(a.feed_names):
        return
    if list(plan.get("fetch", ())) != list(a.fetch_names):
        return
    block = a.program.global_block()
    if not all(registry.is_registered(op.type) for op in block.ops):
        return
    ops = [op for op in block.ops if not registry.get(op.type).skip_exec]
    produced, state = set(), []
    fed = set(a.feed_names)
    for op in ops:
        for name in op.input_arg_names:
            if name == registry.EMPTY_VAR_NAME:
                continue
            if name in fed or name in produced or name in state:
                continue
            if scope.find_var(name) is not None:
                state.append(name)
        produced.update(
            n for n in op.output_arg_names if n != registry.EMPTY_VAR_NAME
        )
    for name in a.fetch_names:
        if (
            name not in fed
            and name not in produced
            and name not in state
            and scope.find_var(name) is not None
        ):
            state.append(name)
    written = set()
    for op in ops:
        written.update(
            n for n in op.output_arg_names if n != registry.EMPTY_VAR_NAME
        )
    mut = sorted(set(state) & written)
    ro = sorted(set(state) - written)
    for name in sorted(set(plan.get("mut", ())) - set(mut)):
        op, bi, oi = _node_site(a, name)
        yield _op_finding(
            "donation-alias", ERROR,
            "donation plan donates %r but the lowering classifies it "
            "read-only — the donated buffer stays live after the call "
            "(use-after-donate)" % name,
            op=op, block_idx=bi, op_index=oi, var=name,
        )
    for name in sorted(set(mut) - set(plan.get("mut", ()))):
        op, bi, oi = _node_site(a, name)
        yield _op_finding(
            "donation-alias", ERROR,
            "the lowering mutates state %r but the donation plan classifies "
            "it read-only — a pass likely corrupted def-use edges" % name,
            op=op, block_idx=bi, op_index=oi, var=name,
        )
    for name in sorted(set(plan.get("ro", ())) - set(ro) - set(mut)):
        op, bi, oi = _node_site(a, name)
        yield _op_finding(
            "donation-alias", ERROR,
            "donation plan lists %r as read-only state but the lowering "
            "sees no such state input" % name,
            op=op, block_idx=bi, op_index=oi, var=name,
        )


# ---------------------------------------------------------------------------
# 2. sharding-rules
# ---------------------------------------------------------------------------


@register_checker("sharding-rules")
def _check_sharding_rules(a):
    """Lint the declarative rule set (parallel/sharding_rules): a rule
    matching nothing is dead weight (warning); an explicit-target rank
    mismatch silently resolves to replicated (error — the author asked for
    a layout the engine cannot apply); with a mesh bound, non-divisible
    static dims degrade to replication per dim (warning, the Resolver's
    documented but silent behavior)."""
    rules = (
        a.resolver.rules
        if a.resolver is not None and a.resolver.rules is not None
        else getattr(a.program, "_sharding_rules", None)
    )
    if not rules:
        return
    names = set()
    declared = {}
    for blk in a.program.blocks:
        for name, v in blk.vars.items():
            names.add(name)
            declared.setdefault(name, v)
    if a.scope is not None:
        names.update(a.scope.vars)
    for pattern, spec in rules:
        rx = re.compile(pattern)
        matched = sorted(n for n in names if rx.search(n))
        if not matched:
            yield Finding(
                "sharding-rules", WARNING,
                "sharding rule %r matches no variable in the program or "
                "scope — dead rule" % pattern,
                var=pattern,
            )
            continue
        if spec is None:
            continue
        for name in matched:
            v = declared.get(name)
            fact = a.facts.get(name)
            shape = None
            if fact is not None and fact.kind == "tensor":
                shape = fact.shape
            elif v is not None and v.shape is not None:
                shape = tuple(v.shape)
            elif a.scope is not None and a.scope.find_var(name) is not None:
                shape = tuple(a.scope.vars[name].shape)
            if shape is None:
                continue
            explicit = v is not None and (
                getattr(v, "trainable", None) is not None or v.is_data
            )
            if len(spec) > len(shape):
                if explicit:
                    op, bi, oi = _node_site(a, name)
                    yield _op_finding(
                        "sharding-rules", ERROR,
                        "rule %r assigns a rank-%d spec %r to %r of rank %d "
                        "— the Resolver silently resolves it replicated"
                        % (pattern, len(spec), spec, name, len(shape)),
                        op=op, block_idx=bi, op_index=oi, var=name,
                    )
                continue
            if a.mesh is None:
                continue
            for dim, entry in enumerate(spec):
                axes = () if entry is None else (
                    tuple(entry) if isinstance(entry, tuple) else (entry,)
                )
                kept = tuple(
                    ax for ax in axes if a.mesh.shape.get(ax, 1) > 1
                )
                if not kept:
                    continue
                d = shape[dim]
                if isinstance(d, SymDim) or d < 0:
                    continue
                extent = 1
                for ax in kept:
                    extent *= a.mesh.shape[ax]
                if int(d) % extent != 0:
                    op, bi, oi = _node_site(a, name)
                    yield _op_finding(
                        "sharding-rules", WARNING,
                        "rule %r shards dim %d of %r (extent %d) over %s "
                        "(mesh extent %d) — not divisible, the Resolver "
                        "silently degrades this dim to replication"
                        % (pattern, dim, name, int(d), "x".join(kept), extent),
                        op=op, block_idx=bi, op_index=oi, var=name,
                    )


# ---------------------------------------------------------------------------
# 3. dtype-boundary
# ---------------------------------------------------------------------------

_LOW_FLOATS = frozenset({"float16", "bfloat16"})
_HIGH_FLOATS = frozenset({"float32", "float64"})


@register_checker("dtype-boundary")
def _check_dtype_boundary(a):
    """An op consuming both 16-bit and 32-bit float inputs mixes precisions
    implicitly — jnp promotion upcasts inside the kernel, so the boundary
    (and its memory/accuracy cost) is invisible in the program. Explicit
    `cast` ops and the optimizer tier (master-f32 math by design) are
    exempt."""
    exempt = _dtype_boundary_exempt()
    for rec in a.records:
        if rec.op.type in exempt or rec.op.type.endswith("_grad"):
            continue
        low, high = [], []
        for slot, names in rec.op.inputs.items():
            facts = rec.ins.get(slot, ())
            for name, f in zip(names, facts):
                if f is None or f.kind != "tensor" or f.dtype is None:
                    continue
                if f.dtype in _LOW_FLOATS:
                    low.append((name, f.dtype))
                elif f.dtype in _HIGH_FLOATS:
                    high.append((name, f.dtype))
        if low and high:
            yield _op_finding(
                "dtype-boundary", WARNING,
                "implicit mixed-precision boundary: %s vs %s — insert an "
                "explicit cast where the precision change is intended"
                % (
                    ", ".join("%s:%s" % p for p in low[:3]),
                    ", ".join("%s:%s" % p for p in high[:3]),
                ),
                op=rec.op, block_idx=rec.block_idx, op_index=rec.index,
                var=low[0][0],
            )


# ---------------------------------------------------------------------------
# 4. determinism
# ---------------------------------------------------------------------------


@register_checker("determinism")
def _check_determinism(a):
    """Inference/serving programs must be pure functions of their feeds:
    clone(for_test) prunes training-only stochastic ops, so any survivor
    here means the program was exported wrong (results differ run to run),
    and host ops cannot be jitted by the serving lowering at all."""
    if a.mode not in ("inference", "serving") and not getattr(
        a.program, "_is_test", False
    ):
        return
    for rec in a.records:
        if rec.opdef is None:
            continue
        if rec.opdef.stochastic and not rec.op.attrs.get("is_test", False):
            yield _op_finding(
                "determinism", ERROR,
                "stochastic op %r reachable in a%s program — outputs would "
                "differ run to run; export with clone(for_test=True)"
                % (rec.op.type,
                   "n inference" if a.mode != "serving" else " serving"),
                op=rec.op, block_idx=rec.block_idx, op_index=rec.index,
                var=next(iter(rec.op.output_arg_names), None),
            )
        if rec.opdef.is_host:
            yield _op_finding(
                "determinism", ERROR,
                "host op %r reachable in a %s program — host ops cannot be "
                "jitted by the serving lowering" % (rec.op.type, a.mode),
                op=rec.op, block_idx=rec.block_idx, op_index=rec.index,
                var=next(iter(rec.op.output_arg_names), None),
            )


# ---------------------------------------------------------------------------
# 5 + 6. dead-write / write-never-read (backward liveness)
# ---------------------------------------------------------------------------


def _real_outputs(op):
    return [
        n for n in op.output_arg_names if n != registry.EMPTY_VAR_NAME
    ]


def _liveness_exempt(a, node):
    if node.sub_blocks or node.type in _SIDE_EFFECT_OPS:
        return True
    try:
        opdef = registry.get(node.type)
    except KeyError:
        return True
    return opdef.skip_exec or opdef.is_host


@register_checker("dead-write")
def _check_dead_write(a):
    """A write whose value is overwritten before any read (shadowed store):
    the op ran for nothing, and under donation the stale buffer may alias.
    Flagged only when a LATER op writes the same name — a never-again-
    written dead value is write-never-read's finding instead."""
    nodes = a.graph.op_nodes(0)
    live = a.live_after(0)
    writers = {}
    for i, node in enumerate(nodes):
        for vn in node.outputs:
            writers.setdefault(vn.name, []).append(i)
    for i, node in enumerate(nodes):
        if _liveness_exempt(a, node):
            continue
        for vn in node.outputs:
            if vn.persistable or vn.name in live[i]:
                continue
            later = [j for j in writers.get(vn.name, ()) if j > i]
            if later:
                yield _op_finding(
                    "dead-write", WARNING,
                    "value written to %r is overwritten by op %d (%s) before "
                    "any read — shadowed store"
                    % (vn.name, later[0], nodes[later[0]].type),
                    op=node.op, block_idx=0, op_index=i, var=vn.name,
                )


@register_checker("write-never-read")
def _check_write_never_read(a):
    """An op none of whose outputs are ever read, fetched, persisted, or
    referenced by a sub-block is dead code the dead_op_eliminate pass would
    remove — flag it so the author deletes the source, not just the op.

    `*_grad` ops are exempt: the backward generator emits a gradient for
    every forward input, and grads of stop_gradient / non-trainable vars
    (fixed positional embeddings, labels) land unconsumed by design — DCE
    removes them; the lint targets user-written dead code."""
    nodes = a.graph.op_nodes(0)
    live = a.live_after(0)
    writers = {}
    for i, node in enumerate(nodes):
        for vn in node.outputs:
            writers.setdefault(vn.name, []).append(i)
    for i, node in enumerate(nodes):
        if _liveness_exempt(a, node) or node.type.endswith("_grad"):
            continue
        outs = _real_outputs(node.op)
        if not outs:
            continue
        dead = all(
            n not in live[i] and not any(j > i for j in writers.get(n, ()))
            for n in outs
        )
        if dead:
            yield _op_finding(
                "write-never-read", WARNING,
                "no output of this op is ever read, fetched, or persisted — "
                "dead code (dead_op_eliminate would remove it)",
                op=node.op, block_idx=0, op_index=i, var=outs[0],
            )


# ---------------------------------------------------------------------------
# 7. fetch-unwritten
# ---------------------------------------------------------------------------


@register_checker("fetch-unwritten")
def _check_fetch_unwritten(a):
    """Every fetch must be fed, produced by a block-0 op, or backed by
    scope/persistable state — otherwise the executor raises 'fetch var has
    no value' only after the pass pipeline and lowering already ran."""
    produced = set()
    block = a.program.global_block()
    for op in block.ops:
        produced.update(
            n for n in op.output_arg_names if n != registry.EMPTY_VAR_NAME
        )
    for name in a.fetch_names:
        if name in a.feed_names or name in produced:
            continue
        if a.scope is not None and a.scope.find_var(name) is not None:
            continue
        if a.scope is None and block.has_var_recursive(name):
            if block._var_recursive(name).persistable:
                continue
        yield Finding(
            "fetch-unwritten", ERROR,
            "fetch %r is never written: no op produces it, nothing feeds "
            "it, and no scope/persistable var backs it" % name,
            var=name,
        )


# ---------------------------------------------------------------------------
# 8. cf-capture
# ---------------------------------------------------------------------------


def _block_tree_sets(program, block_idx, memo):
    """(reads, writes, locals) over the block TREE rooted at block_idx —
    the sets layers/control_flow._external_reads_writes derives x_names
    and carried/written names from, extended through nesting."""
    hit = memo.get(block_idx)
    if hit is not None:
        return hit
    reads, writes, locals_ = set(), set(), set()
    stack = [block_idx]
    while stack:
        idx = stack.pop()
        blk = program.blocks[idx]
        locals_.update(blk.vars)
        for op in blk.ops:
            reads.update(op.input_arg_names)
            writes.update(op.output_arg_names)
            stack.extend(
                v.idx for v in op.attrs.values() if isinstance(v, _Block)
            )
    reads.discard(registry.EMPTY_VAR_NAME)
    writes.discard(registry.EMPTY_VAR_NAME)
    memo[block_idx] = (reads, writes, locals_)
    return memo[block_idx]


def _resolvable_above(a, name, block_idx):
    """Does `name` resolve outside the sub-tree: an ancestor block's
    declaration or the executor scope?"""
    idx = block_idx
    prog = a.program
    while idx >= 0:
        if name in prog.blocks[idx].vars:
            return True
        idx = prog.blocks[idx].parent_idx
    return a.scope is not None and a.scope.find_var(name) is not None


@register_checker("cf-capture")
def _check_cf_capture(a):
    """Control-flow capture: the functional lowering of while/cond/recurrent
    sees ONLY the names threaded through the op's input/output slots
    (ops/control_flow_ops.py builds its env from x_names). A sub-block read
    outside that set KeyErrors deep inside the XLA trace — or, worse,
    silently reads a donated buffer; a sub-block write to a parent var the
    op does not output is dropped on the floor each iteration."""
    memo = {}
    for node in a.graph.all_op_nodes():
        sub_idxs = node.sub_blocks
        if not sub_idxs:
            continue
        op = node.op
        ins = set(op.input_arg_names)
        outs = set(op.output_arg_names)
        parent_idx = node.block_idx
        for sub_idx in sub_idxs:
            reads, writes, locals_ = _block_tree_sets(
                a.program, sub_idx, memo
            )
            for name in sorted(reads - locals_ - ins):
                yield _op_finding(
                    "cf-capture", ERROR,
                    "sub-block %d reads %r which is not threaded through "
                    "the %r op's inputs — the functional lowering cannot "
                    "see it (KeyError at trace time; under a donation plan "
                    "the read would alias a donated buffer)"
                    % (sub_idx, name, op.type),
                    op=op, block_idx=parent_idx, op_index=node.index,
                    var=name,
                )
            for name in sorted(writes - locals_ - outs):
                if not _resolvable_above(a, name, parent_idx):
                    continue
                yield _op_finding(
                    "cf-capture", ERROR,
                    "sub-block %d writes parent variable %r but the %r op "
                    "does not output it — the write is dropped by the "
                    "functional lowering every iteration"
                    % (sub_idx, name, op.type),
                    op=op, block_idx=parent_idx, op_index=node.index,
                    var=name,
                )


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def run_checkers(analysis, checks=None):
    """Run registered checkers (all, or the given ids in registration
    order) over an Analysis; returns [Finding], errors first."""
    findings = []
    for check_id, fn in CHECKERS.items():
        if checks is not None and check_id not in checks:
            continue
        findings.extend(fn(analysis) or ())
    findings.sort(key=lambda f: 0 if f.severity == ERROR else 1)
    return findings


def lint_program(program, feed_names=(), fetch_names=(), scope=None,
                 mesh=None, rules=None, mode="training", checks=None,
                 deep=True):
    """Analyze + lint in one call; returns (analysis, findings).

    deep=False skips the forward abstract interpretation — only the
    structural checkers (STRUCTURAL_CHECKS) see enough; the PassManager's
    per-pass re-verification uses it to stay cheap."""
    if deep:
        analysis = analyze_program(
            program, feed_names, fetch_names, scope=scope, mesh=mesh,
            rules=rules, mode=mode,
        )
    else:
        from ..passes.graph import Graph

        graph = program if isinstance(program, Graph) else Graph(program)
        analysis = Analysis(
            program if not isinstance(program, Graph) else graph.program,
            graph, feed_names, fetch_names, scope, mesh, None, mode,
        )
        if checks is None:
            checks = STRUCTURAL_CHECKS
    return analysis, run_checkers(analysis, checks=checks)


def render_findings(findings):
    """One line per finding, errors first (the CLI/report format)."""
    return "\n".join(f.format() for f in findings)
