"""Forward abstract interpretation + backward liveness over the Graph IR.

The reference framework proves program well-formedness with ~400 hand-written
per-op InferShape/InferVarType functions run at build time (operator.cc:705).
This port derives shapes by tracing (ops/registry.infer_shape), which is
always consistent with execution but only fires op-locally at append_op time
— nothing re-checks a WHOLE program after passes rewrote it, after a model
was loaded from disk, or before the serving runtime commits to an expensive
XLA trace. This module is that whole-program pass:

- `analyze_program` walks every block in execution order, propagating a
  `VarFact` lattice per variable: shape (ints plus `SymDim` symbols for
  dynamic axes), dtype, LoD level, kind (tensor / tensor-array / opaque),
  and the sharding spec the PR 13 Resolver would assign. Per-op transfer
  functions come from the registry: an op with an `abstract_eval` hook
  (OpDef) is interpreted by the hook — control-flow ops recurse into their
  sub-blocks with real entry facts — and every other lowering is abstracted
  with `jax.eval_shape`, exactly the machinery ops/registry.infer_shape
  uses, so the static facts agree with traced avals by construction.
- `Analysis.live_after` is the backward pass: per-op live-variable sets over
  the graph's def-use edges (sub-block-aware — a control-flow op reads every
  parent var its sub-block tree touches), feeding the dead-write and
  write-never-read checkers (analysis/checkers.py).

The lattice is deliberately shallow: a fact is either precise or `opaque`
(unknown), and transfer failures degrade to opaque + a recorded note instead
of raising — the analyzer must never reject a program the executor would
run. Checkers (analysis/checkers.py) turn facts into findings; the
flag-gated compile gate lives in analysis/verify.py.
"""

import jax
import jax.numpy as jnp

from .. import framework
from ..ops import registry

__all__ = [
    "SymDim",
    "VarFact",
    "OpRecord",
    "Analysis",
    "analyze_program",
]

# Symbolic-extent sentinels substituted for dynamic (-1) dims during
# jax.eval_shape. The base matches ops/registry._DYN_SENTINEL; each further
# symbol steps down by a prime stride so arithmetic on one symbol (conv
# windows, pad, slice) does not land on a neighboring symbol's sentinel.
# Collisions would only mislabel an analysis fact, never execution — the
# executors re-trace with concrete feed shapes (same caveat as the registry).
_SYM_BASE = 8191
_SYM_STRIDE = 101
_SYM_MAX = 40


class SymDim:
    """One symbolic dynamic extent (a -1 dim). Identity is the symbol: two
    facts share a SymDim object iff the analyzer proved the extents equal
    (same feed dim, or propagated through a transfer function)."""

    __slots__ = ("name", "sentinel")

    def __init__(self, name, sentinel):
        self.name = name
        self.sentinel = sentinel

    def __repr__(self):
        return "?%s" % self.name


class VarFact:
    """The abstract value of one variable name at one program point.

    kind: "tensor" (shape/dtype meaningful), "array" (a tensor-array: shape
    is the time-major BUFFER shape [cap, ...]), or "opaque" (unknown —
    the bottom of the lattice; transfer functions degrade to it rather
    than guess). shape entries are ints or SymDim; shape None means even
    the rank is unknown. spec is the sharding-rule layout the Resolver
    assigns (None replicated / no resolver bound). writer is the
    producing (block_idx, op_index) or None for external values."""

    __slots__ = ("shape", "dtype", "lod_level", "kind", "spec", "writer")

    def __init__(self, shape=None, dtype=None, lod_level=0, kind="tensor",
                 spec=None, writer=None):
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lod_level = lod_level
        self.kind = kind
        self.spec = spec
        self.writer = writer

    @property
    def known(self):
        """Precise enough to abstract into a ShapeDtypeStruct."""
        return (
            self.kind == "tensor"
            and self.shape is not None
            and self.dtype is not None
        )

    def concrete_shape(self):
        """Shape with SymDims replaced by -1 (the Program metadata idiom)."""
        if self.shape is None:
            return None
        return tuple(-1 if isinstance(d, SymDim) else int(d) for d in self.shape)

    def __repr__(self):
        if self.kind == "opaque":
            return "VarFact(opaque)"
        return "VarFact(%s%s %s)" % (
            "array " if self.kind == "array" else "",
            list(self.shape) if self.shape is not None else "?",
            self.dtype,
        )


class OpRecord:
    """One interpreted op: the facts flowing in and out, plus a note when
    the transfer degraded ("host", "unregistered", "opaque-inputs",
    "skip", or "transfer-error: ...")."""

    __slots__ = ("op", "block_idx", "index", "opdef", "ins", "outs", "note")

    def __init__(self, op, block_idx, index, opdef, ins, outs, note=None):
        self.op = op
        self.block_idx = block_idx
        self.index = index
        self.opdef = opdef
        self.ins = ins
        self.outs = outs
        self.note = note

    def display(self):
        from ..observability.opprof import op_display_name

        return op_display_name(self.op)


class Analysis:
    """The analyzer's report: final facts, per-op records, analyzer-level
    problems, and the backward-liveness query the checkers consume."""

    def __init__(self, program, graph, feed_names, fetch_names, scope, mesh,
                 resolver, mode):
        self.program = program
        self.graph = graph
        self.feed_names = tuple(feed_names)
        self.fetch_names = tuple(fetch_names)
        self.scope = scope
        self.mesh = mesh
        self.resolver = resolver
        self.mode = mode
        self.facts = {}  # block-0 final env: name -> VarFact
        self.records = []  # [OpRecord] in interpretation order (all blocks)
        self.problems = []  # [(block_idx, op_index, op, message)]
        self.entry_origin = {}  # external name -> "feed" | "scope" | "declared"
        self._live = {}  # block_idx -> [set(name) live AFTER each op]

    def problem(self, block_idx, op_index, op, message):
        self.problems.append((block_idx, op_index, op, message))

    def records_in_block(self, block_idx):
        return [r for r in self.records if r.block_idx == block_idx]

    def live_after(self, block_idx=0):
        """Backward liveness over the block's ops: live_after[i] is the set
        of names read by any LATER op in the block (def-use through the
        graph's sub-block-aware edges) or live out of the block (fetched,
        persistable, scope-resident, or referenced below block 0)."""
        cached = self._live.get(block_idx)
        if cached is not None:
            return cached
        nodes = self.graph.op_nodes(block_idx)
        roots = set(self.fetch_names)
        sub_names = self.graph.subblock_reachable_names()
        for node in nodes:
            for vn in node.inputs + node.outputs:
                if vn.persistable or vn.name in sub_names:
                    roots.add(vn.name)
                elif self.scope is not None and self.scope.find_var(vn.name) is not None:
                    roots.add(vn.name)
        live = set(roots)
        out = [None] * len(nodes)
        for i in range(len(nodes) - 1, -1, -1):
            node = nodes[i]
            out[i] = set(live)
            # standard kill-then-gen: writes are whole-value rebinds in the
            # functional lowering, so a write kills even a root — a fetched
            # or persistable var overwritten before any read is dead there.
            # Read-modify-write ops (sgd's Param/ParamOut) stay live via the
            # gen of their own input below.
            live -= {vn.name for vn in node.outputs}
            live |= {vn.name for vn in node.inputs}
        self._live[block_idx] = out
        return out


class _AbstractCtx:
    """What an OpDef.abstract_eval hook sees: sub-block recursion, symbol
    interning, and a problem sink (ops/control_flow_ops.py registers hooks
    for while/cond/recurrent and the tensor-array family)."""

    def __init__(self, analyzer, block_idx, op_index, op):
        self._analyzer = analyzer
        self.block_idx = block_idx
        self.op_index = op_index
        self.op = op

    def sym(self, name):
        return self._analyzer._sym(name)

    def analyze_block(self, block, env):
        """Interpret `block`'s ops with (and into) the given name->fact env;
        returns the env after the last op."""
        return self._analyzer._run_block(block, env)

    def problem(self, message):
        self._analyzer.report.problem(
            self.block_idx, self.op_index, self.op, message
        )

    def opaque(self):
        return VarFact(kind="opaque", writer=(self.block_idx, self.op_index))


class _Analyzer:
    def __init__(self, program, graph, feed_names, fetch_names, scope, mesh,
                 resolver, mode, feed_facts=None):
        self.program = graph.program  # analyze the graph's shadow program
        self.graph = graph
        self.scope = scope
        self.resolver = resolver
        self.feed_facts = dict(feed_facts or {})
        self.report = Analysis(
            program, graph, feed_names, fetch_names, scope, mesh, resolver,
            mode,
        )
        self._symbols = {}  # name -> SymDim
        self._by_sentinel = {}  # sentinel int -> SymDim

    # ------------------------------------------------------------- symbols
    def _sym(self, name):
        s = self._symbols.get(name)
        if s is None:
            k = len(self._symbols)
            sentinel = _SYM_BASE - _SYM_STRIDE * min(k, _SYM_MAX)
            s = SymDim(name, sentinel)
            self._symbols[name] = s
            self._by_sentinel.setdefault(sentinel, s)
        return s

    def _shape_from_meta(self, name, shape):
        """Program metadata shape -> fact shape; each -1 becomes the
        per-(name, dim) symbol so distinct dynamic axes stay distinct."""
        if shape is None:
            return None
        out = []
        for i, d in enumerate(shape):
            if d == -1:
                # dim 0 of data vars is the batch axis; share one symbol so
                # facts derived from different feeds stay comparable
                key = "batch" if i == 0 else "%s.%d" % (name, i)
                out.append(self._sym(key))
            else:
                out.append(int(d))
        return tuple(out)

    # ------------------------------------------------------- external facts
    def _external_fact(self, name, block):
        """Fact for a name read before any write: feed, scope state, or the
        declared metadata (the _CompiledBlock classification order)."""
        override = self.feed_facts.get(name)
        if override is not None:
            self.report.entry_origin.setdefault(name, "feed")
            return override
        if name in self.report.feed_names:
            v = block._var_recursive(name) if block.has_var_recursive(name) else None
            fact = self._fact_from_var(name, v)
            self.report.entry_origin.setdefault(name, "feed")
            return fact
        if self.scope is not None and self.scope.find_var(name) is not None:
            val = self.scope.vars[name]
            shape = getattr(val, "shape", None)
            dtype = getattr(val, "dtype", None)
            if shape is not None and dtype is not None:
                fact = VarFact(
                    shape=tuple(int(d) for d in shape),
                    dtype=framework.convert_np_dtype(dtype),
                )
            else:
                fact = VarFact(kind="opaque")
            self.report.entry_origin.setdefault(name, "scope")
            return self._with_spec(name, fact)
        v = block._var_recursive(name) if block.has_var_recursive(name) else None
        self.report.entry_origin.setdefault(name, "declared")
        return self._with_spec(name, self._fact_from_var(name, v))

    def _fact_from_var(self, name, v):
        if v is None or v.shape is None or v.dtype is None:
            return VarFact(kind="opaque")
        return VarFact(
            shape=self._shape_from_meta(name, v.shape),
            dtype=framework.convert_np_dtype(v.dtype),
            lod_level=getattr(v, "lod_level", 0) or 0,
        )

    def _with_spec(self, name, fact):
        if self.resolver is not None and fact.kind == "tensor":
            try:
                fact.spec = self.resolver.spec(name, fact.concrete_shape())
            except Exception:
                pass
        return fact

    # ------------------------------------------------------------ transfer
    def _gather(self, op, env, block):
        ins = {}
        for slot, names in op.inputs.items():
            if not names:
                continue
            row = []
            for n in names:
                if n == registry.EMPTY_VAR_NAME:
                    row.append(None)
                    continue
                f = env.get(n)
                if f is None:
                    f = self._external_fact(n, block)
                    env[n] = f
                row.append(f)
            ins[slot] = row
        return ins

    def _scatter(self, op, outs, env, site):
        rec_outs = {}
        for slot, names in op.outputs.items():
            vals = (outs or {}).get(slot)
            row = []
            for i, n in enumerate(names):
                f = vals[i] if vals is not None and i < len(vals) else None
                if f is None:
                    f = VarFact(kind="opaque")
                f.writer = site
                if n != registry.EMPTY_VAR_NAME:
                    env[n] = self._with_spec(n, f)
                row.append(f)
            rec_outs[slot] = row
        return rec_outs

    def _default_transfer(self, op, opdef, ins):
        """Abstract the lowering with jax.eval_shape, the exact machinery of
        ops/registry.infer_shape — SymDims ride through as sentinel extents
        and map back on output."""
        abstract_ins = {}
        for slot, facts in ins.items():
            row = []
            for f in facts:
                if f is None:
                    row.append(None)
                    continue
                if not f.known:
                    return None, "opaque-inputs"
                shape = tuple(
                    d.sentinel if isinstance(d, SymDim) else int(d)
                    for d in f.shape
                )
                row.append(jax.ShapeDtypeStruct(shape, jnp.dtype(f.dtype)))
            abstract_ins[slot] = row

        attrs = dict(op.attrs)

        def run(a_ins):
            c = registry.LowerCtx(
                jax.random.key(0), is_test=bool(attrs.get("is_test", False))
            )
            return opdef.lower(c, a_ins, attrs)

        try:
            outs = jax.eval_shape(run, abstract_ins)
        except Exception as e:
            return None, "transfer-error: %s" % (str(e).splitlines() or [""])[0]

        facts = {}
        for slot, vals in outs.items():
            row = []
            for aval in vals:
                if aval is None or not hasattr(aval, "shape"):
                    row.append(None)
                    continue
                shape = tuple(
                    self._by_sentinel.get(int(d), int(d)) for d in aval.shape
                )
                row.append(
                    VarFact(
                        shape=shape,
                        dtype=framework.convert_np_dtype(aval.dtype),
                    )
                )
            facts[slot] = row
        return facts, None

    # ----------------------------------------------------------- main walk
    def _run_block(self, block, env):
        for index, op in enumerate(block.ops):
            site = (block.idx, index)
            try:
                opdef = registry.get(op.type)
            except KeyError:
                opdef = None
            ins = self._gather(op, env, block)
            note = None
            outs = None
            if opdef is None:
                note = "unregistered"
            elif opdef.skip_exec:
                note = "skip"
            elif opdef.abstract_eval is not None:
                actx = _AbstractCtx(self, block.idx, index, op)
                try:
                    outs = opdef.abstract_eval(actx, op, ins)
                except Exception as e:
                    note = "transfer-error: %s" % (str(e).splitlines() or [""])[0]
                    self.report.problem(block.idx, index, op, note)
            elif opdef.is_host:
                note = "host"
            elif opdef.lower is None:
                note = "no-lowering"
            else:
                outs, note = self._default_transfer(op, opdef, ins)
                if note is not None and note.startswith("transfer-error"):
                    self.report.problem(block.idx, index, op, note)
            rec_outs = self._scatter(op, outs, env, site)
            self.report.records.append(
                OpRecord(op, block.idx, index, opdef, ins, rec_outs, note)
            )
        return env

    def run(self):
        env = {}
        block = self.program.global_block()
        # feeds enter the env up front so fed names never fall back to scope
        for n in self.report.feed_names:
            env[n] = self._with_spec(n, self._external_fact(n, block))
        self._run_block(block, env)
        self.report.facts = env
        return self.report


def analyze_program(program, feed_names=(), fetch_names=(), scope=None,
                    mesh=None, rules=None, mode="training", feed_facts=None):
    """Whole-program forward abstract interpretation.

    Returns an `Analysis`. `rules` defaults to the program's attached
    ShardingRules; with a `mesh` they bind into a Resolver so every fact
    carries the layout the executor would assign. `feed_facts` (name ->
    VarFact) overrides feed metadata with concrete run shapes. `mode` is
    "training" / "inference" / "serving" — consumed by the determinism
    checker, not the interpretation itself."""
    from ..passes.graph import Graph

    graph = program if isinstance(program, Graph) else Graph(program)
    # report.program must be a Program (checkers call global_block on it);
    # callers handing a live Graph get its shadow program as the identity
    program = graph.program if isinstance(program, Graph) else program
    resolver = None
    if mesh is not None:
        from ..parallel.sharding_rules import Resolver, ShardingRules

        combined = ShardingRules()
        combined.extend(getattr(graph.program, "_sharding_rules", None)
                        or getattr(program, "_sharding_rules", None))
        combined.extend(rules)
        blk = graph.program.global_block()

        def var_lookup(name):
            try:
                return blk._var_recursive(name)
            except KeyError:
                return None

        resolver = Resolver(mesh, rules=combined, var_lookup=var_lookup)
        resolver.add_aliases(graph.program.global_block().ops)
    return _Analyzer(
        program, graph, feed_names, fetch_names, scope, mesh, resolver, mode,
        feed_facts=feed_facts,
    ).run()
