"""The FLAGS_static_verify compile gate.

With the flag on (off by default), every compile path proves the program
against the fluidlint checker suite BEFORE tracing: Executor.run and
ParallelExecutor.run on an executable-cache miss, aot_serve_lowering (the
serving/generation model-load path), and the PassManager's pipeline (stage-0
plus a cheap structural re-verification after every pass). Error findings
raise `StaticVerifyError` listing every finding with op/var provenance;
warnings count through the observability registry (`analysis/*`) and pass.

Verification never mutates the program, so gated and ungated runs are
bit-identical by construction (tests/test_fluidlint.py proves it across the
zoo). Results memoize per (program uid/version, feeds, fetches, scope, mode)
— the gate costs one dict lookup on the executors' hot path once a program
verified.
"""

from .checkers import ERROR, STRUCTURAL_CHECKS, lint_program, render_findings

__all__ = [
    "StaticVerifyError",
    "static_verify",
    "maybe_static_verify",
    "verify_graph",
]


class StaticVerifyError(RuntimeError):
    """The static analyzer rejected a program. `findings` carries every
    Finding (errors and warnings) from the failing lint."""

    def __init__(self, where, findings):
        self.where = where
        self.findings = list(findings)
        errors = [f for f in self.findings if f.severity == ERROR]
        RuntimeError.__init__(
            self,
            "static verification failed at %s (%d error%s):\n%s"
            % (
                where or "compile",
                len(errors),
                "" if len(errors) == 1 else "s",
                render_findings(self.findings),
            ),
        )


def _flag_on():
    from .. import flags as _flags

    return _flags.get_flags("static_verify")["static_verify"]


def _metrics():
    from ..observability import registry as _registry

    reg = _registry.default_registry()
    return {
        "verifies": reg.counter(
            "analysis/verifies", "static_verify gate runs, labeled by where"
        ),
        "findings": reg.counter(
            "analysis/findings",
            "fluidlint findings, labeled by check and severity",
        ),
        "wall_ms": reg.gauge(
            "analysis/verify_wall_ms", "last static_verify wall time (ms)"
        ),
    }


def static_verify(program, feed_names=(), fetch_names=(), scope=None,
                  mesh=None, rules=None, mode="training", where="",
                  checks=None, deep=True):
    """Lint and raise StaticVerifyError on any error-severity finding;
    returns the full findings list (warnings included) otherwise. Counters
    land in the observability registry either way."""
    import time

    t0 = time.perf_counter()
    _, findings = lint_program(
        program, feed_names, fetch_names, scope=scope, mesh=mesh,
        rules=rules, mode=mode, checks=checks, deep=deep,
    )
    m = _metrics()
    m["verifies"].inc(where=where or "direct")
    m["wall_ms"].set((time.perf_counter() - t0) * 1000.0)
    for f in findings:
        m["findings"].inc(check=f.check, severity=f.severity)
    if any(f.severity == ERROR for f in findings):
        raise StaticVerifyError(where, findings)
    return findings


_VERIFIED = {}  # memo key -> findings (successful verifications only)
_VERIFIED_CAP = 256


def maybe_static_verify(program, feed_names=(), fetch_names=(), scope=None,
                        mesh=None, rules=None, mode="training", where=""):
    """The flag-gated, memoized gate the executors and serving loaders call
    at their compile points. No flag → no work; verified programs cost one
    dict lookup per subsequent compile."""
    if not _flag_on():
        return None
    key = (
        program._uid,
        program._version,
        tuple(sorted(feed_names)),
        tuple(fetch_names),
        getattr(scope, "_uid", None),
        mode,
        rules.fingerprint() if rules is not None else None,
    )
    hit = _VERIFIED.get(key)
    if hit is not None:
        return hit
    findings = static_verify(
        program, feed_names, fetch_names, scope=scope, mesh=mesh,
        rules=rules, mode=mode, where=where,
    )
    if len(_VERIFIED) >= _VERIFIED_CAP:
        _VERIFIED.pop(next(iter(_VERIFIED)))
    _VERIFIED[key] = findings
    return findings


def verify_graph(graph, ctx, stage=""):
    """The PassManager hook: with FLAGS_static_verify on, run the cheap
    structural checker subset (STRUCTURAL_CHECKS — no forward
    interpretation) over the pipeline's live graph, raising on errors.
    Called as stage 0 before any pass and re-run after every pass, so a
    pass that breaks control-flow capture or drops a fetched producer is
    named immediately, not at the next compile."""
    if not _flag_on():
        return None
    return static_verify(
        graph, ctx.feed_names, ctx.fetch_names, scope=ctx.scope,
        where="pipeline:%s" % (stage or "0"), checks=STRUCTURAL_CHECKS,
        deep=False,
    )
