"""Whole-program static analysis + the fluidlint checker suite.

The reference framework rejects malformed programs at build time with ~400
per-op InferShape functions; this package is the port's equivalent gate,
run over WHOLE programs at the compile seams instead of per append_op:

- `analyze_program` (dataflow.py): forward abstract interpretation over the
  Graph IR — shape (with symbolic dynamic dims), dtype, LoD, tensor-array
  kinds, and sharding specs — plus backward liveness.
- `lint_program` / `CHECKERS` (checkers.py): the ~8 registered fluidlint
  checkers (donation-alias, sharding-rules, dtype-boundary, determinism,
  dead-write, write-never-read, fetch-unwritten, cf-capture).
- `static_verify` / `maybe_static_verify` / `verify_graph` (verify.py): the
  FLAGS_static_verify gate the executors, serving loaders, and the
  PassManager call.

CLI: tools/fluidlint.py. Docs: docs/static_analysis.md.
"""

from .checkers import (
    CHECKERS,
    STRUCTURAL_CHECKS,
    Finding,
    lint_program,
    register_checker,
    render_findings,
    run_checkers,
)
from .dataflow import Analysis, OpRecord, SymDim, VarFact, analyze_program
from .verify import (
    StaticVerifyError,
    maybe_static_verify,
    static_verify,
    verify_graph,
)

__all__ = [
    "Analysis",
    "CHECKERS",
    "Finding",
    "OpRecord",
    "STRUCTURAL_CHECKS",
    "StaticVerifyError",
    "SymDim",
    "VarFact",
    "analyze_program",
    "lint_program",
    "maybe_static_verify",
    "register_checker",
    "render_findings",
    "run_checkers",
    "static_verify",
    "verify_graph",
]
