"""SelectedRows — the sparse-gradient side structure, TPU-native.

Reference analog: paddle/fluid/framework/selected_rows.h — a (rows, value)
pair where `rows` lists the touched table rows and `value` holds one gradient
row per entry; lookup_table_grad emits it when is_sparse=True, and the sparse
optimizer kernels (sgd_op.h SparseSGDFunctor, adam_op.h SparseAdamFunctor)
scatter only those rows. The pserver wire carried the same pair
(sendrecvop_utils.cc SerializeToByteBuffer).

On TPU the structure cannot be a dynamic ragged tensor — XLA shapes are
static — so the analog is a *fixed-capacity* pair carried through the Program
as two ordinary Variables:

- values `<W>@GRAD`      : (capacity, dim), the cotangent rows, in the
                           cotangent's dtype (bf16 stays bf16 on the wire);
- rows   `<W>@GRAD@ROWS` : (capacity,) int32 global row ids, with
                           ROW_SENTINEL (-1) for slots that must not
                           contribute (negative/masked ids, padding_idx).

`capacity` is the number of id slots in the step's batch (ids.size), so the
memory/wire cost is O(batch * dim) instead of O(table_rows * dim) — the whole
point of SelectedRows. Duplicate ids are NOT pre-merged in the grad op;
`merge_rows` (the merge_selected_rows analog, operators/math/
selected_rows_functor.cc MergeAdd) runs inside the optimizer lowering where
the f32 accumulation is needed anyway.

The values Variable is flagged in-Program (`is_selected_rows=True`, plus the
rows var name and the table height) so backward.py, clip.py, regularizer.py
and optimizer.py can recognise and route it without a new IR node type.
"""

import jax.numpy as jnp

ROW_SENTINEL = -1

__all__ = [
    "ROW_SENTINEL",
    "mark_selected_rows",
    "is_selected_rows",
    "rows_var_name",
    "merge_rows",
    "densify",
]


def mark_selected_rows(values_var, rows_name, height):
    """Flag a Program Variable as the values half of a SelectedRows pair."""
    values_var.is_selected_rows = True
    values_var.selected_rows_rows = rows_name
    values_var.selected_rows_height = int(height)
    return values_var


def is_selected_rows(var):
    return bool(getattr(var, "is_selected_rows", False))


def rows_var_name(values_name):
    """Canonical rows-var name for a values var (reference kept both inside
    one SelectedRows object; here they are sibling Variables)."""
    return values_name + "@ROWS"


def merge_rows(rows, values, height):
    """Deduplicate rows and sum their value rows — MergeAdd, statically
    shaped. Returns (uniq, summed):

    - uniq   : (capacity,) int32, sorted unique row ids; sentinel/invalid
               slots map to `height` (one past the last row) and unused
               unique slots are filled with `height` too, so a single
               OOB-dropping scatter handles both.
    - summed : (capacity, dim) f32 — per-unique-row gradient sums. The f32
               accumulator is the same bf16-swamping defence as the dense
               lookup_table_grad (core_ops.py): repeated ids add exactly.
    """
    cap = int(rows.shape[0])
    rows_m = jnp.where(rows < 0, height, rows).astype(jnp.int32)
    uniq, inv = jnp.unique(
        rows_m, size=cap, fill_value=height, return_inverse=True
    )
    inv = inv.reshape(-1)
    summed = (
        jnp.zeros((cap, values.shape[1]), jnp.float32)
        .at[inv]
        .add(values.astype(jnp.float32))
    )
    return uniq.astype(jnp.int32), summed


def densify(rows, values, height, dtype=None):
    """Scatter a SelectedRows pair into a dense (height, dim) gradient —
    the reference's SelectedRows→LoDTensor merge for optimizers without a
    sparse kernel. f32 accumulation, cast once at the end."""
    dtype = dtype or values.dtype
    cap = rows.shape[0]
    safe = jnp.where(rows < 0, height, rows).astype(jnp.int32)
    dense = (
        jnp.zeros((height, values.shape[1]), jnp.float32)
        .at[safe]
        .add(values.astype(jnp.float32), mode="drop")
    )
    return dense.astype(dtype)
