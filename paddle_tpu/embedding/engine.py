"""EmbeddingEngine — row-sharded giant embedding tables as one object.

Reference analog: the distributed lookup table stack — lookup_table_op.cc
with is_distributed, distribute_transpiler._split_table_grad_and_add_send_vars
sharding the table across pservers, parameter_prefetch.cc fetching rows by
RPC, and lookup_sparse_table_op growing rows on demand. The TPU redesign
collapses that machinery into one engine that owns:

- **table creation**: one Parameter with a `(axis, None)` sharding RULE
  registered on the program (parallel.sharding_rules.program_rules) so
  ParallelExecutor stores it — and its optimizer accumulators — row-sharded
  over the mesh's `ep` axis (GSPMD placement, the executor's rule Resolver)
  — no pserver processes;
- **forward**: the `distributed_lookup_table` op → gather over the local
  shard + one psum (embedding/lookup.py) instead of an RPC prefetch;
- **sparse backward**: `is_sparse=True` routes lookup_table_grad through the
  SelectedRows analog (selected_rows.py) and per-row optimizer updates
  (ops/sparse_ops.py) whose cost scales with ids-per-batch, not table rows;
- **sharded checkpoints**: save/load the table plus its row-aligned optimizer
  accumulators as N row-range shards with a manifest — the analog of the
  pserver-side checkpoint_notify/table recovery, but just files.

A table qualifies as "giant" when its dense optimizer state would not fit one
chip; `state_bytes_per_device` quantifies that and feeds the embedding/
gauges (observability registry) and BENCH_recsys.json.
"""

import json
import os

import numpy as np

from .. import framework
from ..framework import default_main_program
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr

__all__ = ["EmbeddingEngine", "engines_of"]

_MANIFEST = "EMBEDDING_MANIFEST.json"


def engines_of(program):
    """Every EmbeddingEngine built inside `program` (layers.distributed_embedding
    constructs engines internally without returning them; the online trainer
    discovers them here to wire touched-rows bookkeeping)."""
    return list(getattr(program, "_embedding_engines", ()))


def _registry():
    from ..observability.registry import default_registry

    return default_registry()


class EmbeddingEngine:
    """One row-sharded embedding table + its training state.

    Build-time (inside a program_guard): creates the Parameter and appends
    lookup ops. Run-time (with a Scope): sharded checkpoint save/load and
    byte accounting. The same program runs on any mesh — the op lowerings
    fall back to the exact single-device computation when the mesh has no
    `axis_name` extent (ops/parallel_ops.py).
    """

    def __init__(
        self,
        name,
        num_rows,
        dim,
        dtype="float32",
        axis_name="ep",
        padding_idx=None,
        is_sparse=True,
        param_attr=None,
    ):
        import re as _re

        from ..parallel import program_rules

        self.num_rows = int(num_rows)
        self.dim = int(dim)
        self.dtype = dtype
        self.axis_name = axis_name
        self.is_sparse = bool(is_sparse)
        # normalize like layers.embedding: -1 means "no padding row"
        self.padding_idx = (
            -1
            if padding_idx is None
            else int(padding_idx)
            if padding_idx >= 0
            else self.num_rows + int(padding_idx)
        )
        helper = LayerHelper("embedding_engine")
        attr = param_attr if param_attr is not None else ParamAttr(name=name)
        self.table = helper.create_parameter(
            attr=attr, shape=[self.num_rows, self.dim], dtype=dtype, is_bias=False
        )
        # declare the row-sharded layout through the sharding-rule engine
        # (parallel/sharding_rules) instead of a per-var attr: the anchored
        # `(_.*)?` suffix covers the table AND its optimizer accumulators
        # (`<table>_<slot>_acc_<k>`), so moments row-shard alongside the rows
        # they update — same placement the old shard_parameter path produced
        # (bit-parity asserted by tests/test_sharding_rules.py)
        program_rules(self.table.block.program).add(
            "^%s(_.*)?$" % _re.escape(self.table.name), (axis_name, None)
        )
        self.name = name if name is not None else self.table.name
        # last-touched step per row, allocated lazily on the first
        # note_touched (num_rows can be recsys-scale; pay only when the
        # online delta path is in use). -1 = never touched.
        self._last_touched = None
        program = self.table.block.program
        if not hasattr(program, "_embedding_engines"):
            program._embedding_engines = []
        program._embedding_engines.append(self)
        self._emit_static_gauges()

    # ------------------------------------------------------------------ build
    def lookup(self, ids):
        """Append the sharded lookup; returns (ids.shape…, dim) activations.
        ids with a trailing extent-1 dim have it folded away, like the dense
        lookup_table op."""
        helper = LayerHelper("embedding_engine")
        out = helper.create_variable_for_type_inference(self.dtype)
        helper.append_op(
            type="distributed_lookup_table",
            inputs={"W": [self.table.name], "Ids": [ids.name]},
            outputs={"Out": [out.name]},
            attrs={
                "axis_name": self.axis_name,
                "padding_idx": self.padding_idx,
                "is_sparse": self.is_sparse,
            },
        )
        if getattr(ids, "_len_name", None):
            out._len_name = ids._len_name
        return out

    # -------------------------------------------------- touched-row tracking
    def touched_rows_var_name(self):
        """The SelectedRows row-id var the sparse grad maker emits for this
        table (`<table>@GRAD@ROWS`, ops/sparse_ops._lookup_grad_maker) —
        fetch it alongside the loss to feed note_touched."""
        from ..framework import grad_var_name
        from .selected_rows import rows_var_name

        return rows_var_name(grad_var_name(self.table.name))

    def note_touched(self, step, rows):
        """Record that `rows` (the fetched SelectedRows row ids, ROW_SENTINEL
        and out-of-range padding slots tolerated) were updated at training
        step `step`. O(ids) per step; the tracker is one int64 per table
        row."""
        rows = np.asarray(rows).reshape(-1)
        if self._last_touched is None:
            self._last_touched = np.full(self.num_rows, -1, np.int64)
        valid = rows[(rows >= 0) & (rows < self.num_rows)]
        if valid.size:
            self._last_touched[valid] = int(step)

    def touched_rows_since(self, step):
        """Sorted row ids updated AFTER training step `step` (exclusive) —
        the rows an incremental checkpoint delta must ship. Rows never noted
        are never returned; an engine with no bookkeeping yet returns
        empty."""
        if self._last_touched is None:
            return np.empty(0, np.int64)
        return np.nonzero(self._last_touched > int(step))[0].astype(np.int64)

    # ------------------------------------------------------------- accounting
    def state_var_names(self, program=None):
        """The table plus every row-aligned accumulator the optimizer hung off
        it (moment vars share the table's (num_rows, dim) shape and its
        `<table>_<slot>_acc` name prefix — optimizer._add_accumulator). Scalar
        state (beta pows) is excluded: it is replicated, not row-sharded."""
        block = (program or default_main_program()).global_block()
        names = [self.table.name]
        prefix = self.table.name + "_"
        for v in block.vars.values():
            # accumulator names are `<param>_<slot>_acc_<k>` (unique_name)
            if (
                v.name.startswith(prefix)
                and "_acc" in v.name
                and tuple(v.shape or ()) == (self.num_rows, self.dim)
            ):
                names.append(v.name)
        return names

    def table_bytes(self):
        return self.num_rows * self.dim * _dtype_bytes(self.dtype)

    def state_bytes_per_device(self, num_devices, program=None, scope=None):
        """Per-chip HBM bytes for the table + row-aligned accumulators when
        row-sharded over `num_devices` (the engine's placement). Compare with
        num_devices=1 for the dense-resident requirement."""
        total = 0
        block = (program or default_main_program()).global_block()
        for n in self.state_var_names(program):
            v = block.vars[n]
            total += self.num_rows * self.dim * _dtype_bytes(v.dtype)
        return total // max(1, int(num_devices))

    def _emit_static_gauges(self):
        try:
            _registry().gauge(
                "embedding/table_rows",
                help="rows in the sharded embedding table",
            ).set(float(self.num_rows), table=self.name)
            _registry().gauge(
                "embedding/table_bytes",
                help="global HBM bytes of the table (divide by ep for per-shard)",
            ).set(float(self.table_bytes()), table=self.name)
        except Exception:
            pass  # observability must never break model build

    # ------------------------------------------------------------ checkpoints
    def save_sharded(self, scope, dirname, num_shards=1, program=None):
        """Write the table and its row-aligned optimizer state as `num_shards`
        row-range .npz shards + a manifest. Shard k holds rows
        [k*rows/N, (k+1)*rows/N) of every array — the layout a future
        multi-host restore reads back per-host without touching other shards
        (the pserver checkpoint sharding, made into plain files). bf16 arrays
        are stored as f32 (lossless widening) and cast back on load."""
        os.makedirs(dirname, exist_ok=True)
        names = self.state_var_names(program)
        num_shards = int(num_shards)
        if self.num_rows % num_shards:
            raise ValueError(
                "num_rows=%d not divisible by num_shards=%d"
                % (self.num_rows, num_shards)
            )
        rows_per = self.num_rows // num_shards
        dtypes = {}
        arrays = {}
        for n in names:
            a = np.asarray(scope.find_var(n))
            if a.shape != (self.num_rows, self.dim):
                raise ValueError(
                    "scope var %r has shape %s, expected %s"
                    % (n, a.shape, (self.num_rows, self.dim))
                )
            dtypes[n] = str(a.dtype)
            if "bfloat16" in str(a.dtype):
                a = a.astype(np.float32)
            arrays[n] = a
        for k in range(num_shards):
            lo, hi = k * rows_per, (k + 1) * rows_per
            np.savez(
                os.path.join(dirname, _shard_file(k, num_shards)),
                **{n: arrays[n][lo:hi] for n in names},
            )
        manifest = {
            "name": self.name,
            "table": self.table.name,
            "num_rows": self.num_rows,
            "dim": self.dim,
            "num_shards": num_shards,
            "row_ranges": [
                [k * rows_per, (k + 1) * rows_per] for k in range(num_shards)
            ],
            "arrays": dtypes,
            "version": 1,
        }
        tmp = os.path.join(dirname, _MANIFEST + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1)
        os.replace(tmp, os.path.join(dirname, _MANIFEST))
        return manifest

    def load_sharded(self, scope, dirname):
        """Reassemble every array from its row-range shards into the scope.
        The next executor run re-places them onto the mesh (state_sharding),
        so the on-disk shard count is independent of the run-time ep size."""
        manifest = self.read_manifest(dirname)
        if manifest["num_rows"] != self.num_rows or manifest["dim"] != self.dim:
            raise ValueError(
                "checkpoint table is %dx%d, engine is %dx%d"
                % (
                    manifest["num_rows"],
                    manifest["dim"],
                    self.num_rows,
                    self.dim,
                )
            )
        num_shards = manifest["num_shards"]
        shards = [
            np.load(os.path.join(dirname, _shard_file(k, num_shards)))
            for k in range(num_shards)
        ]
        for n, dt in manifest["arrays"].items():
            full = np.concatenate([s[n] for s in shards], axis=0)
            if "bfloat16" in dt:
                import jax.numpy as jnp

                full = jnp.asarray(full, dtype=jnp.bfloat16)
            scope.vars[n] = full
        return manifest

    @staticmethod
    def read_manifest(dirname):
        with open(os.path.join(dirname, _MANIFEST)) as f:
            return json.load(f)


def _shard_file(k, n):
    return "embedding-%05d-of-%05d.npz" % (k, n)


def _dtype_bytes(dtype):
    d = str(dtype)
    if "bfloat16" in d or d in ("float16", "f16"):
        return 2
    if d in ("float64", "int64", "f64"):
        return 8
    return 4
