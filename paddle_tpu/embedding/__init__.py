"""paddle_tpu.embedding — sparse embedding engine for recsys-scale tables.

The TPU-native replacement for the reference's pserver distributed lookup
table (SURVEY.md §2.7.5): row-sharded tables over the mesh `ep` axis,
SelectedRows-style sparse gradients whose cost scales with touched rows, and
per-row optimizer updates with row-sharded moments. See docs/embedding.md.
"""

from .engine import EmbeddingEngine, engines_of
from .lookup import sharded_embedding_lookup
from .selected_rows import (
    ROW_SENTINEL,
    densify,
    is_selected_rows,
    mark_selected_rows,
    merge_rows,
    rows_var_name,
)

__all__ = [
    "EmbeddingEngine",
    "engines_of",
    "sharded_embedding_lookup",
    "ROW_SENTINEL",
    "densify",
    "is_selected_rows",
    "mark_selected_rows",
    "merge_rows",
    "rows_var_name",
]
