"""Row-sharded embedding lookup (the forward gather+psum leg of the engine).

Reference analog: the distributed lookup table (SURVEY.md §2.7.5) — a
high-dimensional embedding sharded across parameter servers, rows fetched by
RPC prefetch (distributed/parameter_prefetch.cc:26) and gradients pushed as
SelectedRows. TPU-native redesign: the table is row-sharded over a mesh axis;
each rank gathers its local hits (out-of-range ids produce zeros) and a psum
over the axis combines them — one ICI collective instead of an RPC round trip.

Semantics match the dense lookup_table op (ops/core_ops.py) exactly:
negative ids and padding_idx rows produce zeros, and the zero-masking
preserves the table dtype (a bf16/fp16 table must not come back f32 — the
old `jnp.where(..., 0.0)` could upcast under strict promotion rules and,
worse, silently doubled the activation's HBM footprint).
"""

import functools

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.collectives import shard_map

__all__ = ["sharded_embedding_lookup"]


def _local_lookup(table_shard, ids, axis_name, padding_idx=None):
    """table_shard: (rows_local, d); ids: global int ids, any shape."""
    rows_local = table_shard.shape[0]
    me = lax.axis_index(axis_name)
    offset = me * rows_local
    flat = ids.reshape(-1).astype(jnp.int32)
    local = flat - offset
    # negative global ids are padding/masked slots (AsyncExecutor's bucketed
    # batches): zero rows everywhere, like the dense op
    in_range = (local >= 0) & (local < rows_local) & (flat >= 0)
    if padding_idx is not None and int(padding_idx) != -1:
        in_range = in_range & (flat != jnp.int32(padding_idx))
    safe = jnp.clip(local, 0, rows_local - 1)
    picked = jnp.take(table_shard, safe, axis=0)
    zero = jnp.zeros((), picked.dtype)
    picked = jnp.where(in_range[:, None], picked, zero)
    out = picked.reshape(ids.shape + (table_shard.shape[1],))
    return lax.psum(out, axis_name)


def sharded_embedding_lookup(table, ids, mesh, axis_name="ep", padding_idx=None):
    """table: (rows, d) global array sharded on rows over `axis_name`;
    ids: int array whose leading dim is the batch — kept sharded over 'dp'
    (when the mesh has it) so per-device work scales with batch/dp, not the
    global batch. Returns (ids.shape..., d) with the same dp sharding.

    padding_idx: already-normalized non-negative row index (or None/-1) whose
    looked-up rows are zeros, matching the dense lookup_table attr."""
    batch_spec = P(("dp",)) if "dp" in mesh.shape else P()
    fn = shard_map(
        functools.partial(
            _local_lookup, axis_name=axis_name, padding_idx=padding_idx
        ),
        mesh=mesh,
        in_specs=(P((axis_name,), None), batch_spec),
        out_specs=batch_spec,
    )
    return fn(table, ids)
