"""Composite network helpers (reference python/paddle/fluid/nets.py:
simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention)."""

from . import layers

__all__ = [
    "simple_img_conv_pool",
    "sequence_conv_pool",
    "glu",
    "scaled_dot_product_attention",
    "img_conv_group",
]


def simple_img_conv_pool(
    input,
    num_filters,
    filter_size,
    pool_size,
    pool_stride,
    pool_padding=0,
    pool_type="max",
    global_pooling=False,
    conv_stride=1,
    conv_padding=0,
    conv_dilation=1,
    conv_groups=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    use_cudnn=True,
):
    conv_out = layers.conv2d(
        input=input,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=conv_stride,
        padding=conv_padding,
        dilation=conv_dilation,
        groups=conv_groups,
        param_attr=param_attr,
        bias_attr=bias_attr,
        act=act,
    )
    return layers.pool2d(
        input=conv_out,
        pool_size=pool_size,
        pool_type=pool_type,
        pool_stride=pool_stride,
        pool_padding=pool_padding,
        global_pooling=global_pooling,
    )


def img_conv_group(
    input,
    conv_num_filter,
    pool_size,
    conv_padding=1,
    conv_filter_size=3,
    conv_act=None,
    param_attr=None,
    conv_with_batchnorm=False,
    conv_batchnorm_drop_rate=0.0,
    pool_stride=1,
    pool_type="max",
    use_cudnn=True,
):
    tmp = input
    if not isinstance(conv_padding, list):
        conv_padding = [conv_padding] * len(conv_num_filter)
    if not isinstance(conv_filter_size, list):
        conv_filter_size = [conv_filter_size] * len(conv_num_filter)
    if not isinstance(conv_with_batchnorm, list):
        conv_with_batchnorm = [conv_with_batchnorm] * len(conv_num_filter)
    if not isinstance(conv_batchnorm_drop_rate, list):
        conv_batchnorm_drop_rate = [conv_batchnorm_drop_rate] * len(conv_num_filter)
    for i, nf in enumerate(conv_num_filter):
        local_act = conv_act if not conv_with_batchnorm[i] else None
        tmp = layers.conv2d(
            input=tmp,
            num_filters=nf,
            filter_size=conv_filter_size[i],
            padding=conv_padding[i],
            param_attr=param_attr,
            act=local_act,
        )
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            if conv_batchnorm_drop_rate[i]:
                tmp = layers.dropout(tmp, dropout_prob=conv_batchnorm_drop_rate[i])
    return layers.pool2d(
        input=tmp, pool_size=pool_size, pool_type=pool_type, pool_stride=pool_stride
    )


def sequence_conv_pool(
    input, num_filters, filter_size, param_attr=None, act="sigmoid", pool_type="max"
):
    conv_out = layers.sequence_conv(
        input=input,
        num_filters=num_filters,
        filter_size=filter_size,
        param_attr=param_attr,
        act=act,
    )
    return layers.sequence_pool(input=conv_out, pool_type=pool_type)


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1, dropout_rate=0.0):
    """reference nets.py scaled_dot_product_attention (3-D q/k/v)."""
    from .models.transformer import multi_head_attention

    d_model = queries.shape[-1]
    return multi_head_attention(
        queries,
        keys,
        values,
        None,
        d_key=d_model // num_heads,
        d_value=values.shape[-1] // num_heads,
        d_model=values.shape[-1],
        n_head=num_heads,
        dropout_rate=dropout_rate,
    )
