"""append_backward: reverse-mode autodiff as a program rewrite.

Reference analog: python/paddle/fluid/backward.py:469 — walk ops in reverse
from the loss, emit grad ops per forward op, sum-deduplicate repeated-var
gradients (reference _addup_repetitive_outputs_:135), prune branches that
don't need grad, tag ops with OpRole.Backward + op_role_var.

The TPU-first difference is WHERE gradients come from: the reference calls each
op's hand-written C++ GradOpDescMaker; here a forward op `t` gets a generic
`t_grad` op whose lowering is jax.vjp over `t`'s forward lowering
(ops/registry.py:_make_generic_grad). Because the executor compiles forward
and backward into one XLA module, the vjp's forward replay is deduplicated by
XLA CSE — no extra FLOPs materialize.

Grad op slot convention (matches reference grad_op_desc_maker.h): inputs are
the forward input slots, forward output slots, and `<slot>@GRAD` cotangents;
outputs are `<in-slot>@GRAD`. Missing entries use the `@EMPTY@` placeholder
(reference core.kEmptyVarName).
"""

from . import framework
from .framework import OpRole, Parameter, grad_var_name
from .ops import registry

__all__ = ["append_backward"]

from .ops.registry import EMPTY_VAR_NAME


def _create_grad_var(block, ref_var, name):
    if block.has_var(name):
        return block.vars[name]
    return block.create_var(
        name=name,
        shape=ref_var.shape,
        dtype=ref_var.dtype,
        persistable=False,
        stop_gradient=False,
    )


def _needs_grad(block, name, no_grad_set):
    if name in no_grad_set:
        return False
    try:
        v = block._var_recursive(name)
    except KeyError:
        return False
    if v.stop_gradient:
        return False
    return framework.is_float_dtype(v.dtype) if v.dtype else False


def append_backward(loss, parameter_list=None, no_grad_set=None, callbacks=None):
    """Append backward ops computing d(loss)/d(param) into loss's program.

    Returns [(param, grad_var)] like the reference (backward.py:469). Grad vars
    are named `<param>@GRAD`.
    """
    block = loss.block
    program = block.program
    no_grad_set = set(no_grad_set or [])
    for v in block.vars.values():
        if v.stop_gradient:
            no_grad_set.add(v.name)

    # locate the op producing loss — ops after it (metrics etc.) are irrelevant
    loss_idx = None
    for i in reversed(range(len(block.ops))):
        if loss.name in block.ops[i].output_arg_names:
            loss_idx = i
            break
    if loss_idx is None:
        raise ValueError("loss %r is not produced by any op in its block" % loss.name)

    with program._backward_role_guard():
        # d(loss)/d(loss) = 1
        loss_grad = _create_grad_var(block, loss, grad_var_name(loss.name))
        block.append_op(
            type="fill_constant",
            outputs={"Out": [loss_grad.name]},
            attrs={
                "shape": list(loss.shape),
                "value": 1.0,
                "dtype": loss.dtype,
                OpRole.OP_ROLE_KEY: OpRole.Backward | OpRole.Loss,
            },
        )

        # pending[var_name] = [contribution grad var names]
        pending = {loss.name: [loss_grad.name]}

        def finalize_grad(name):
            """Collapse pending contributions for `name` into `<name>@GRAD`.
            Multiple consumers contribute separately; a `sum` op merges them
            (reference _addup_repetitive_outputs_)."""
            contribs = pending.get(name)
            if not contribs:
                return None
            canonical = grad_var_name(name)
            if len(contribs) > 1 and any(
                getattr(block.vars.get(c), "is_selected_rows", False)
                for c in contribs
            ):
                # the sparse grad maker (ops/sparse_ops.py) only emits a
                # SelectedRows grad for single-consumer tables, so this is a
                # bug guard, not a reachable path: `sum` over mixed
                # dense/SelectedRows contributions would silently add a
                # (cap, dim) values array to a (rows, dim) gradient
                raise ValueError(
                    "gradient of %r has %d contributions including a "
                    "SelectedRows (sparse) one — sparse grads cannot be "
                    "sum-merged; use is_sparse=False for multiply-consumed "
                    "tables" % (name, len(contribs))
                )
            if len(contribs) == 1:
                if contribs[0] != canonical:
                    # single contribution under a renamed var: alias via assign
                    ref = block._var_recursive(name)
                    _create_grad_var(block, ref, canonical)
                    block.append_op(
                        type="assign",
                        inputs={"X": [contribs[0]]},
                        outputs={"Out": [canonical]},
                    )
                return canonical
            ref = block._var_recursive(name)
            _create_grad_var(block, ref, canonical)
            block.append_op(
                type="sum",
                inputs={"X": list(contribs)},
                outputs={"Out": [canonical]},
            )
            pending[name] = [canonical]
            return canonical

        def add_contribution(name):
            """Allocate a grad var name for a new contribution to d(loss)/d(name)."""
            ref = block._var_recursive(name)
            canonical = grad_var_name(name)
            lst = pending.setdefault(name, [])
            gname = canonical if not lst else "%s@RENAME@%d" % (canonical, len(lst))
            lst.append(gname)
            _create_grad_var(block, ref, gname)
            return gname

        for i in range(loss_idx, -1, -1):
            op = block.ops[i]
            try:
                opdef = registry.get(op.type)
            except KeyError:
                continue
            if opdef.no_grad:
                continue
            out_grads_avail = any(
                pending.get(n) for n in op.output_arg_names
            )
            if not out_grads_avail:
                continue
            diff_inputs = [
                n for n in op.input_arg_names if _needs_grad(block, n, no_grad_set)
            ]
            if not diff_inputs:
                continue

            # finalize cotangents for this op's outputs (all consumers already
            # processed since we walk in reverse program order)
            out_grad_names = {}
            for slot, names in op.outputs.items():
                gs = [finalize_grad(n) for n in names]
                if any(g is not None for g in gs):
                    out_grad_names[slot] = [g or EMPTY_VAR_NAME for g in gs]

            if opdef.grad is not None:
                # custom grad maker (e.g. dropout reusing its Mask)
                grad_map = {}
                for slot, names in op.outputs.items():
                    for n in names:
                        g = pending.get(n)
                        if g:
                            grad_map[n] = g[0] if len(g) == 1 else grad_var_name(n)
                for n in diff_inputs:
                    grad_map[n] = add_contribution(n)
                for spec in opdef.grad(op, block, grad_map):
                    spec.setdefault("attrs", {})[OpRole.OP_ROLE_KEY] = OpRole.Backward
                    block.append_op(**spec)
                continue

            g_inputs = {}
            for slot, names in op.inputs.items():
                if names:
                    g_inputs[slot] = list(names)
            for slot, names in op.outputs.items():
                if names:
                    g_inputs[slot] = list(names)
            for slot, gnames in out_grad_names.items():
                g_inputs[slot + "@GRAD"] = gnames

            g_outputs = {}
            role_vars = []
            for slot, names in op.inputs.items():
                gs = []
                has = False
                for n in names:
                    if _needs_grad(block, n, no_grad_set):
                        gname = add_contribution(n)
                        gs.append(gname)
                        has = True
                        if isinstance(block._var_recursive(n), Parameter):
                            role_vars += [n, gname]
                    else:
                        gs.append(EMPTY_VAR_NAME)
                if has:
                    g_outputs[slot + "@GRAD"] = gs

            attrs = dict(op.attrs)
            attrs[registry.FWD_IN_SLOTS_ATTR] = list(op.inputs.keys())
            attrs[registry.FWD_OUT_SLOTS_ATTR] = list(op.outputs.keys())
            attrs[OpRole.OP_ROLE_KEY] = OpRole.Backward
            if role_vars:
                attrs[OpRole.OP_ROLE_VAR_KEY] = role_vars
            block.append_op(
                type=op.type + "_grad",
                inputs=g_inputs,
                outputs=g_outputs,
                attrs=attrs,
            )

        # finalize any parameter grads never consumed by another grad op
        params = (
            [block._var_recursive(p) if isinstance(p, str) else p for p in parameter_list]
            if parameter_list
            else block.all_parameters()
        )
        params_and_grads = []
        for p in params:
            if not getattr(p, "trainable", True):
                continue
            g = finalize_grad(p.name)
            if g is None:
                continue
            params_and_grads.append((p, block._var_recursive(g)))
    return params_and_grads
