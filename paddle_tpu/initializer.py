"""Initializers — emitted as ops into the startup program.

Reference analog: python/paddle/fluid/initializer.py (ConstantInitializer,
UniformInitializer, NormalInitializer, TruncatedNormalInitializer,
XavierInitializer, MSRAInitializer, BilinearInitializer,
NumpyArrayInitializer). Each __call__(var, block) appends the init op to the
given (startup) block; the executor materializes values when the startup
program runs — identical flow to the reference.
"""

import numpy as np

from . import framework

__all__ = [
    "Constant",
    "Uniform",
    "Normal",
    "TruncatedNormal",
    "Xavier",
    "MSRA",
    "Bilinear",
    "NumpyArrayInitializer",
    "force_init_on_cpu",
    "init_on_cpu",
]


def force_init_on_cpu():  # compat: placement is XLA's concern on TPU
    return False


class init_on_cpu:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    @staticmethod
    def _fan_in_out(var):
        shape = var.shape
        if len(shape) < 2:
            return shape[0] if shape else 1, shape[0] if shape else 1
        receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        fan_in = shape[1] * receptive if len(shape) > 2 else shape[0]
        fan_out = shape[0] * receptive if len(shape) > 2 else shape[1]
        return fan_in, fan_out


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block):
        return block.append_op(
            type="fill_constant",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": var.dtype, "value": float(self.value)},
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            type="uniform_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "min": float(self.low),
                "max": float(self.high),
                "seed": self.seed,
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": float(self.loc),
                "std": float(self.scale),
                "seed": self.seed,
            },
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="truncated_gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": float(self.loc),
                "std": float(self.scale),
                "seed": self.seed,
            },
        )


class XavierInitializer(Initializer):
    """Glorot init (reference initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = self._fan_in_out(var)
        fan_in = self.fan_in if self.fan_in is not None else fi
        fan_out = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = float(np.sqrt(2.0 / (fan_in + fan_out)))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """He/Kaiming init (reference initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = self._fan_in_out(var)
        fan_in = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = float(np.sqrt(6.0 / fan_in))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = float(np.sqrt(2.0 / fan_in))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """Bilinear upsampling kernel init for conv_transpose (reference
    initializer.py BilinearInitializer)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("Bilinear init needs a 4-D conv weight")
        weight = np.zeros(shape, dtype=np.float32)
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape[2:]))):
            x, y = i % shape[3], i // shape[3]
            val = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            weight[:, :, y, x] = val
        return NumpyArrayInitializer(weight)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        dt = framework.convert_np_dtype(var.dtype)
        vals = self.value.astype("float32" if framework.is_float_dtype(dt) else "int32")
        return block.append_op(
            type="assign_value",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(self.value.shape),
                "dtype": dt,
                "values": vals.reshape(-1).tolist(),
            },
        )


# fluid-style public aliases (reference initializer.py tail)
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer
