"""CoNLL-2005 SRL reader creators (reference python/paddle/dataset/
conll05.py: test() yields (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2,
verb_ids, mark, label_ids) — 8 input slots + label; get_dict() returns
(word_dict, verb_dict, label_dict))."""

import numpy as np

from . import common

__all__ = ["test", "get_dict", "get_embedding"]

WORD_VOCAB = 4000
VERB_VOCAB = 200
N_LABELS = 59  # CoNLL05 label count (B-/I- args + O)
SENTENCES = 500


def get_dict():
    word_dict = {"w%04d" % i: i for i in range(WORD_VOCAB)}
    verb_dict = {"v%03d" % i: i for i in range(VERB_VOCAB)}
    label_dict = {"l%02d" % i: i for i in range(N_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Pretrained word embedding matrix analog (reference serves emb.txt)."""
    rng = common.synthetic_rng("conll05-emb")
    return rng.rand(WORD_VOCAB, 32).astype("float32") * 0.1


def _samples(tag, n):
    rng = common.synthetic_rng("conll05-" + tag)
    for _ in range(n):
        length = rng.randint(4, 18)
        words = [int(w) for w in rng.randint(0, WORD_VOCAB, length)]
        verb_pos = int(rng.randint(0, length))
        verb = words[verb_pos] % VERB_VOCAB
        pad = lambda i: words[i] if 0 <= i < length else 0
        ctx_n2 = [pad(verb_pos - 2)] * length
        ctx_n1 = [pad(verb_pos - 1)] * length
        ctx_0 = [pad(verb_pos)] * length
        ctx_p1 = [pad(verb_pos + 1)] * length
        ctx_p2 = [pad(verb_pos + 2)] * length
        mark = [1 if i == verb_pos else 0 for i in range(length)]
        # learnable labels: function of distance to the verb
        labels = [
            min(abs(i - verb_pos), N_LABELS - 1) for i in range(length)
        ]
        yield (
            words,
            ctx_n2,
            ctx_n1,
            ctx_0,
            ctx_p1,
            ctx_p2,
            [verb] * length,
            mark,
            labels,
        )


def test():
    return lambda: _samples("test", SENTENCES)
