"""WMT14 fr→en reader creators (reference python/paddle/dataset/wmt14.py:
train/test yield (src_ids, trg_ids, trg_ids_next); get_dict returns
(src_dict, trg_dict); <s>=0, <e>=1, <unk>=2). Synthetic fallback: source
sentences whose target is a deterministic token mapping, so seq2seq models
can genuinely learn the translation."""

import numpy as np

from . import common

__all__ = ["train", "test", "get_dict"]

START, END, UNK_IDX = 0, 1, 2
TRAIN_PAIRS = 1000
TEST_PAIRS = 100


def _dicts(dict_size):
    src = {"<s>": 0, "<e>": 1, "<unk>": 2}
    trg = {"<s>": 0, "<e>": 1, "<unk>": 2}
    for i in range(dict_size - 3):
        src["f%04d" % i] = i + 3
        trg["e%04d" % i] = i + 3
    return src, trg


def get_dict(dict_size, reverse=False):
    src, trg = _dicts(dict_size)
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg


def _reader_creator(tag, n, dict_size):
    def reader():
        rng = common.synthetic_rng("wmt14-" + tag)
        for _ in range(n):
            length = rng.randint(3, 12)
            src = [int(t) for t in rng.randint(3, dict_size, length)]
            # deterministic "translation": same content, reversed order —
            # the classic toy task attention must learn
            trg = [(t * 3 + 1) % (dict_size - 3) + 3 for t in reversed(src)]
            trg_in = [START] + trg
            trg_next = trg + [END]
            yield src, trg_in, trg_next

    return reader


def train(dict_size):
    return _reader_creator("train", TRAIN_PAIRS, dict_size)


def test(dict_size):
    return _reader_creator("test", TEST_PAIRS, dict_size)
