"""MQ2007 learning-to-rank reader creators (reference python/paddle/dataset/
mq2007.py: modes pointwise (feature46, relevance), pairwise (better, worse),
listwise (per-query feature list, label list))."""

import numpy as np

from . import common

__all__ = ["train", "test"]

N_FEATURES = 46
N_QUERIES = 120
DOCS_PER_QUERY = 8


def _queries(tag, n):
    rng = common.synthetic_rng("mq2007-" + tag)
    w = common.synthetic_rng("mq2007-w").rand(N_FEATURES) - 0.5  # hidden scorer
    for _ in range(n):
        feats = rng.rand(DOCS_PER_QUERY, N_FEATURES).astype("float32")
        scores = feats @ w
        rel = np.digitize(scores, np.quantile(scores, [0.5, 0.85]))
        yield feats, rel.astype("int64")


def _creator(tag, n, format):
    def reader():
        for feats, rel in _queries(tag, n):
            if format == "pointwise":
                for f, r in zip(feats, rel):
                    yield f, int(r)
            elif format == "pairwise":
                for i in range(len(rel)):
                    for j in range(len(rel)):
                        if rel[i] > rel[j]:
                            yield feats[i], feats[j]
            else:  # listwise
                yield feats, rel

    return reader


def train(format="pairwise"):
    return _creator("train", N_QUERIES, format)


def test(format="pairwise"):
    return _creator("test", N_QUERIES // 6, format)
