"""Oxford-102 flowers reader creators (reference python/paddle/dataset/
flowers.py: train/test/valid yield (3x224x224 float image, int label))."""

from . import common

__all__ = ["train", "test", "valid"]

N_CLASSES = 102


def _samples(tag, n, use_xmap=True):
    rng = common.synthetic_rng("flowers-" + tag)
    for _ in range(n):
        label = int(rng.randint(0, N_CLASSES))
        img = (rng.rand(3, 224, 224).astype("float32") - 0.5) * 0.1
        # class-dependent color cast: learnable by any conv net
        img[label % 3] += (label / N_CLASSES) * 0.5
        yield img.reshape(-1), label


def train(use_xmap=True):
    return lambda: _samples("train", 512, use_xmap)


def test(use_xmap=True):
    return lambda: _samples("test", 64, use_xmap)


def valid(use_xmap=True):
    return lambda: _samples("valid", 64, use_xmap)
