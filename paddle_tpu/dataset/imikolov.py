"""PTB language-model reader creators (reference
python/paddle/dataset/imikolov.py: build_dict + train/test yielding n-gram
id tuples or SEQ pairs). Synthetic fallback: a Markov-ish token stream with a
Zipfian vocabulary so next-word prediction is learnable."""

import numpy as np

from . import common

__all__ = ["train", "test", "build_dict", "DataType"]

VOCAB = 2072  # small PTB-like vocab for the synthetic stream
TRAIN_SENTENCES = 2000
TEST_SENTENCES = 200


class DataType:
    NGRAM = 1
    SEQ = 2


def build_dict(min_word_freq=50):
    """word -> id map; id 0..VOCAB-1, plus <unk>/<e>/<s> like the reference
    (ids chosen to match usage: <s>=start, <e>=end, <unk>=last)."""
    d = {"<s>": 0, "<e>": 1, "<unk>": 2}
    for i in range(VOCAB - 3):
        d["w%04d" % i] = i + 3
    return d


def _sentences(tag, n):
    rng = common.synthetic_rng("imikolov-" + tag)
    # Zipf-distributed tokens with a deterministic bigram bias: the next
    # token tends toward (prev*7+3) % VOCAB, so an LM can beat uniform
    for _ in range(n):
        length = rng.randint(5, 20)
        sent = [int(rng.zipf(1.3)) % (VOCAB - 3) + 3]
        for _ in range(length - 1):
            if rng.rand() < 0.6:
                sent.append((sent[-1] * 7 + 3) % (VOCAB - 3) + 3)
            else:
                sent.append(int(rng.zipf(1.3)) % (VOCAB - 3) + 3)
        yield sent


def _reader_creator(tag, n_sent, word_idx, n, data_type):
    def reader():
        for sent in _sentences(tag, n_sent):
            if data_type == DataType.NGRAM:
                ids = [0] * (n - 1) + sent + [1]
                for i in range(n, len(ids) + 1):
                    yield tuple(ids[i - n : i])
            else:
                ids = [0] + sent + [1]
                yield ids[:-1], ids[1:]

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return _reader_creator("train", TRAIN_SENTENCES, word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    return _reader_creator("test", TEST_SENTENCES, word_idx, n, data_type)
