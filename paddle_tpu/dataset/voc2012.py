"""VOC2012 segmentation reader creators (reference python/paddle/dataset/
voc2012.py: train/test/val yield (3xHxW image bytes, HxW label mask))."""

import numpy as np

from . import common

__all__ = ["train", "test", "val"]

N_CLASSES = 21
H = W = 64  # synthetic tier keeps masks small


def _samples(tag, n):
    rng = common.synthetic_rng("voc2012-" + tag)
    for _ in range(n):
        img = (rng.rand(3, H, W).astype("float32") - 0.5) * 0.2
        mask = np.zeros((H, W), "int64")
        # one rectangular object of a random class; its channel is brightened
        cls = int(rng.randint(1, N_CLASSES))
        y0, x0 = rng.randint(0, H // 2), rng.randint(0, W // 2)
        y1, x1 = y0 + rng.randint(8, H // 2), x0 + rng.randint(8, W // 2)
        mask[y0:y1, x0:x1] = cls
        img[cls % 3, y0:y1, x0:x1] += 0.5
        yield img, mask


def train():
    return lambda: _samples("train", 256)


def test():
    return lambda: _samples("test", 32)


def val():
    return lambda: _samples("val", 32)
