"""MovieLens-1M reader creators (reference python/paddle/dataset/
movielens.py: train/test yield [user_id, gender, age, job, movie_id,
category_ids, title_ids, rating]; plus meta accessors max_user_id etc.).
Synthetic fallback with the same field layout and a learnable
user-genre affinity signal."""

import numpy as np

from . import common

__all__ = [
    "train",
    "test",
    "max_user_id",
    "max_movie_id",
    "max_job_id",
    "age_table",
    "movie_categories",
    "user_info",
    "movie_info",
]

N_USERS = 500
N_MOVIES = 400
N_CATEGORIES = 18
N_JOBS = 21
TITLE_VOCAB = 1000
RATINGS = 6000
age_table = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [self.index, self.categories, self.title]


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]


def max_user_id():
    return N_USERS


def max_movie_id():
    return N_MOVIES


def max_job_id():
    return N_JOBS


def movie_categories():
    return ["cat%02d" % i for i in range(N_CATEGORIES)]


def _movies():
    rng = common.synthetic_rng("movielens-movies")
    out = {}
    for mid in range(1, N_MOVIES + 1):
        cats = sorted(
            set(int(c) for c in rng.randint(0, N_CATEGORIES, rng.randint(1, 4)))
        )
        title = [int(t) for t in rng.randint(0, TITLE_VOCAB, rng.randint(1, 6))]
        out[mid] = MovieInfo(mid, cats, title)
    return out


def _users():
    rng = common.synthetic_rng("movielens-users")
    out = {}
    for uid in range(1, N_USERS + 1):
        out[uid] = UserInfo(
            uid,
            "M" if rng.rand() < 0.5 else "F",
            int(rng.randint(0, len(age_table))),
            int(rng.randint(0, N_JOBS)),
        )
    return out


def movie_info():
    return _movies()


def user_info():
    return _users()


def _ratings(tag, n):
    rng = common.synthetic_rng("movielens-" + tag)
    movies = _movies()
    users = _users()
    # learnable signal: each user has a favourite category; rating depends
    # on overlap between it and the movie's categories
    fav = {uid: uid % N_CATEGORIES for uid in users}
    for _ in range(n):
        uid = int(rng.randint(1, N_USERS + 1))
        mid = int(rng.randint(1, N_MOVIES + 1))
        u, m = users[uid], movies[mid]
        base = 4.5 if fav[uid] in m.categories else 2.5
        rating = float(np.clip(round(base + rng.randn() * 0.5), 1, 5))
        yield u.value() + m.value() + [rating]


def train():
    return lambda: _ratings("train", RATINGS)


def test():
    return lambda: _ratings("test", RATINGS // 10)
