"""WMT16 en↔de reader creators (reference python/paddle/dataset/wmt16.py:
train/test/validation yield (src_ids, trg_ids, trg_ids_next) with
configurable src/trg language; get_dict(lang, dict_size))."""

from . import common, wmt14

__all__ = ["train", "test", "validation", "get_dict"]


def get_dict(lang, dict_size, reverse=False):
    src, trg = wmt14.get_dict(dict_size, reverse)
    return src if lang == "en" else trg


def _creator(tag, n, src_dict_size, trg_dict_size, src_lang):
    # direction matters: the stream (and its deterministic seed) differs per
    # source language, and the token mapping inverts, so en->de and de->en
    # callers see genuinely swapped corpora
    mult = 5 if src_lang == "en" else 7

    def reader():
        rng = common.synthetic_rng("wmt16-%s-%s" % (src_lang, tag))
        for _ in range(n):
            length = rng.randint(3, 12)
            src = [int(t) for t in rng.randint(3, src_dict_size, length)]
            trg = [(t * mult + 2) % (trg_dict_size - 3) + 3 for t in reversed(src)]
            yield src, [wmt14.START] + trg, trg + [wmt14.END]

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _creator("train", 1000, src_dict_size, trg_dict_size, src_lang)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _creator("test", 100, src_dict_size, trg_dict_size, src_lang)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _creator("val", 100, src_dict_size, trg_dict_size, src_lang)
