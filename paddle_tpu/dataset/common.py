"""Shared dataset plumbing (reference python/paddle/dataset/common.py —
download cache dir, md5 checks; here: local cache dir + synthetic fallback)."""

import hashlib
import os

import numpy as np

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA_HOME",
    os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu", "dataset"),
)


def local_path(*parts):
    return os.path.join(DATA_HOME, *parts)


def have_local(*parts):
    return os.path.exists(local_path(*parts))


def synthetic_rng(tag):
    """Deterministic per-dataset RNG so synthetic streams are reproducible
    across processes (stable hash — Python's str hash is per-process salted)."""
    seed = int.from_bytes(hashlib.sha256(tag.encode()).digest()[:4], "big")
    return np.random.RandomState(seed % (2**31))
