"""IMDB sentiment (reference python/paddle/dataset/imdb.py: word_dict(),
train(word_dict)/test(word_dict) yielding (word-id sequence, 0/1 label)).
Synthetic streams use sentiment-bearing token distributions so text models
can actually learn."""

import numpy as np

from . import common

__all__ = ["word_dict", "train", "test"]

VOCAB = 5000


def word_dict():
    return {("w%d" % i).encode(): i for i in range(VOCAB)}


def _synthetic(tag, n):
    rng = common.synthetic_rng("imdb-" + tag)

    def reader():
        for i in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 64))
            # positive reviews skew toward low token ids, negative toward high
            base = rng.randint(0, VOCAB // 2, length)
            if label == 0:
                base = VOCAB // 2 + base
            yield base.astype("int64").tolist(), label

    return reader


def train(word_idx=None):
    return _synthetic("train", 2048)


def test(word_idx=None):
    return _synthetic("test", 256)
