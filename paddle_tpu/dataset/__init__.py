"""Dataset package (reference python/paddle/dataset/ — mnist, cifar, imdb,
uci_housing, wmt14/16, movielens, flowers…).

The reference downloads from public mirrors at import time. This build runs in
zero-egress environments, so each dataset module serves from a local cache dir
(`PADDLE_TPU_DATA_HOME`, default ~/.cache/paddle_tpu/dataset) when real files
exist there, and otherwise falls back to a DOCUMENTED deterministic synthetic
sample stream with the same shapes/dtypes/vocabulary so that models, readers,
and tests exercise the identical code path.
"""

from . import (
    cifar,
    conll05,
    flowers,
    imdb,
    imikolov,
    mnist,
    movielens,
    mq2007,
    sentiment,
    uci_housing,
    voc2012,
    wmt14,
    wmt16,
)

__all__ = [
    "mnist",
    "cifar",
    "uci_housing",
    "imdb",
    "imikolov",
    "movielens",
    "sentiment",
    "conll05",
    "flowers",
    "voc2012",
    "wmt14",
    "wmt16",
    "mq2007",
    "common",
]
