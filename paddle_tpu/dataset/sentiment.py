"""Movie-review sentiment reader creators (reference python/paddle/dataset/
sentiment.py over NLTK movie_reviews: train/test yield (word_ids, 0|1);
get_word_dict())."""

from . import common

__all__ = ["train", "test", "get_word_dict"]

VOCAB = 5147  # reference's movie_reviews vocab magnitude
POS_MARKERS = tuple(range(10, 60))  # synthetic "positive" token ids


def get_word_dict():
    return {"w%04d" % i: i for i in range(VOCAB)}


def _samples(tag, n):
    rng = common.synthetic_rng("sentiment-" + tag)
    for _ in range(n):
        label = int(rng.rand() < 0.5)
        length = rng.randint(8, 40)
        ids = [int(w) for w in rng.randint(60, VOCAB, length)]
        # learnable: positive docs contain marker tokens
        if label == 0:  # reference: 0 = positive class order per file list
            k = rng.randint(2, 6)
            for pos in rng.randint(0, length, k):
                ids[pos] = int(rng.choice(POS_MARKERS))
        yield ids, label


def train():
    return lambda: _samples("train", 800)


def test():
    return lambda: _samples("test", 200)
