"""CIFAR-10/100 reader creators (reference python/paddle/dataset/cifar.py:
train10()/test10()/train100()/test100() yielding (3072-float image, label))."""

import numpy as np

from . import common

__all__ = ["train10", "test10", "train100", "test100"]


def _synthetic(tag, n, classes):
    rng = common.synthetic_rng("cifar-" + tag)
    imgs = rng.rand(n, 3, 32, 32).astype("float32") * 0.2
    labels = rng.randint(0, classes, n)
    for i in range(n):
        c = labels[i] % 3
        imgs[i, c, : 16, : 16] += (labels[i] + 1) / float(classes)

    def reader():
        for i in range(n):
            yield imgs[i].reshape(-1), int(labels[i])

    return reader


def train10():
    return _synthetic("train10", 4096, 10)


def test10():
    return _synthetic("test10", 512, 10)


def train100():
    return _synthetic("train100", 4096, 100)


def test100():
    return _synthetic("test100", 512, 100)
