"""UCI housing regression (reference python/paddle/dataset/uci_housing.py:
train()/test() yielding (13-dim features, price))."""

import numpy as np

from . import common

__all__ = ["train", "test"]

_W = None


def _data(tag, n):
    global _W
    rng = common.synthetic_rng("uci-shared")
    if _W is None:
        _W = rng.randn(13).astype("float32")
    rng2 = common.synthetic_rng("uci-" + tag)
    x = rng2.randn(n, 13).astype("float32")
    y = x @ _W + 0.1 * rng2.randn(n).astype("float32")

    def reader():
        for i in range(n):
            yield x[i], np.asarray([y[i]], dtype="float32")

    return reader


def train():
    return _data("train", 404)


def test():
    return _data("test", 102)
