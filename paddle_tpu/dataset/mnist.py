"""MNIST reader creators (reference python/paddle/dataset/mnist.py:
train()/test() yielding (784-float image in [-1,1], int label)).

Serves real idx files from the local cache when present; otherwise a
deterministic synthetic stream with a learnable class-dependent pattern (so
convergence tests remain meaningful)."""

import gzip
import os
import struct

import numpy as np

from . import common

__all__ = ["train", "test"]

TRAIN_SIZE = 8192
TEST_SIZE = 1024


def _read_idx(images_path, labels_path, limit=None):
    with gzip.open(labels_path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), dtype=np.uint8)
    with gzip.open(images_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
    if limit:
        images, labels = images[:limit], labels[:limit]
    for img, lbl in zip(images, labels):
        yield img.astype("float32") / 127.5 - 1.0, int(lbl)


def _synthetic(tag, n):
    rng = common.synthetic_rng("mnist-" + tag)
    imgs = (rng.rand(n, 784).astype("float32") - 0.5) * 0.2
    labels = rng.randint(0, 10, n)
    # class-dependent block pattern: rows [0:8]*class intensity
    for i in range(n):
        l = labels[i]
        img2d = imgs[i].reshape(28, 28)
        img2d[:14, :14] += l / 10.0
        img2d[14:, 14:] -= l / 10.0
    def reader():
        for i in range(n):
            yield imgs[i], int(labels[i])
    return reader


def train():
    imgs = common.local_path("mnist", "train-images-idx3-ubyte.gz")
    lbls = common.local_path("mnist", "train-labels-idx1-ubyte.gz")
    if os.path.exists(imgs) and os.path.exists(lbls):
        return lambda: _read_idx(imgs, lbls)
    return _synthetic("train", TRAIN_SIZE)


def test():
    imgs = common.local_path("mnist", "t10k-images-idx3-ubyte.gz")
    lbls = common.local_path("mnist", "t10k-labels-idx1-ubyte.gz")
    if os.path.exists(imgs) and os.path.exists(lbls):
        return lambda: _read_idx(imgs, lbls)
    return _synthetic("test", TEST_SIZE)
