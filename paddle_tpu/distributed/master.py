"""Fault-tolerant dataset task master + client.

Reference analog: go/master/service.go — partitions RecordIO chunks into
tasks (:69-106), dispatches them to trainers, re-dispatches tasks whose
owner times out (:311-341), discards tasks that failed `failure_max` times
(:368+), and snapshots state to etcd for recovery (:166-207); trainers use
python/paddle/v2/master/client.py (get_task / task_finished / task_failed).

TPU-native redesign: same task state machine, JSON-line protocol over TCP
(the cluster fabric here is plain sockets, like distributed/rpc.py), and the
etcd snapshot becomes an atomic local-file snapshot (the coordination service
of a TPU pod slice is per-job, not a shared etcd) — restart the master with
the same snapshot_path and pending/todo state is recovered.

Tasks are (path, begin, end) RecordIO byte ranges produced from
native.chunk_offsets, so a trainer reads its shard with
reader.creator.recordio(path, begin, end).
"""

import json
import os
import socket
import threading
import time

from .. import native

__all__ = ["Master", "MasterClient"]


class _Task:
    def __init__(self, task_id, path, begin, end):
        self.id = task_id
        self.path = path
        self.begin = begin
        self.end = end
        self.failures = 0
        self.deadline = None  # set while dispatched

    def spec(self):
        return {"id": self.id, "path": self.path, "begin": self.begin, "end": self.end}


class Master:
    def __init__(
        self,
        endpoint="127.0.0.1:0",
        chunks_per_task=8,
        timeout_s=30.0,
        failure_max=3,
        snapshot_path=None,
    ):
        self.chunks_per_task = chunks_per_task
        self.timeout_s = timeout_s
        self.failure_max = failure_max
        self.snapshot_path = snapshot_path
        self.todo = []
        self.pending = {}  # id -> _Task
        self.done = []
        self.discarded = []
        self._lock = threading.Lock()
        self._next_id = 0
        host, _, port = endpoint.rpartition(":")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host or "127.0.0.1", int(port)))
        self._sock.listen(64)
        self.endpoint = "%s:%d" % (host or "127.0.0.1", self._sock.getsockname()[1])
        self._closed = False
        self._recovered = False
        if snapshot_path and os.path.exists(snapshot_path):
            self._recover()
            self._recovered = True

    # ------------------------------ dataset -------------------------------

    def set_dataset(self, paths):
        """Partition files into chunk-range tasks (service.go partition()).
        A no-op after snapshot recovery — the restart script re-runs this, and
        appending fresh tasks would re-train every finished shard (the
        reference's SetDataset skips when state was recovered the same way)."""
        with self._lock:
            if self._recovered:
                return
            for path in paths:
                offsets = native.chunk_offsets(path) + [os.path.getsize(path)]
                for i in range(0, len(offsets) - 1, self.chunks_per_task):
                    begin = offsets[i]
                    end = offsets[min(i + self.chunks_per_task, len(offsets) - 1)]
                    self.todo.append(_Task(self._next_id, path, begin, end))
                    self._next_id += 1
            self._snapshot_locked()

    # ----------------------------- state I/O ------------------------------

    def _snapshot_locked(self):
        if not self.snapshot_path:
            return
        state = {
            "next_id": self._next_id,
            "todo": [t.spec() | {"failures": t.failures} for t in self.todo]
            + [t.spec() | {"failures": t.failures} for t in self.pending.values()],
            "done": self.done,
            "discarded": self.discarded,
        }
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self.snapshot_path)  # atomic, like etcd txn

    def _recover(self):
        with open(self.snapshot_path) as f:
            state = json.load(f)
        self._next_id = state["next_id"]
        for spec in state["todo"]:
            t = _Task(spec["id"], spec["path"], spec["begin"], spec["end"])
            t.failures = spec.get("failures", 0)
            self.todo.append(t)
        self.done = state["done"]
        self.discarded = state["discarded"]

    # ----------------------------- scheduling -----------------------------

    def _requeue_timed_out_locked(self):
        now = time.monotonic()
        for tid in [t for t, task in self.pending.items() if task.deadline < now]:
            task = self.pending.pop(tid)
            task.failures += 1
            if task.failures >= self.failure_max:
                self.discarded.append(task.id)  # service.go failure_max drop
            else:
                self.todo.append(task)

    def _handle(self, req):
        op = req.get("op")
        with self._lock:
            self._requeue_timed_out_locked()
            if op == "get_task":
                if not self.todo:
                    if self.pending:
                        return {"status": "wait"}
                    return {"status": "no_more"}
                task = self.todo.pop(0)
                task.deadline = time.monotonic() + self.timeout_s
                self.pending[task.id] = task
                self._snapshot_locked()
                return {"status": "ok", "task": task.spec()}
            if op == "task_finished":
                task = self.pending.pop(int(req["id"]), None)
                if task is not None:
                    self.done.append(task.id)
                    self._snapshot_locked()
                return {"status": "ok"}
            if op == "task_failed":
                task = self.pending.pop(int(req["id"]), None)
                if task is not None:
                    task.failures += 1
                    if task.failures >= self.failure_max:
                        self.discarded.append(task.id)
                    else:
                        self.todo.append(task)
                    self._snapshot_locked()
                return {"status": "ok"}
            if op == "stats":
                return {
                    "status": "ok",
                    "todo": len(self.todo),
                    "pending": len(self.pending),
                    "done": len(self.done),
                    "discarded": len(self.discarded),
                }
        return {"status": "bad_request"}

    # ------------------------------ serving -------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self

    def _serve(self):
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn):
        try:
            f = conn.makefile("rw")
            for line in f:
                resp = self._handle(json.loads(line))
                f.write(json.dumps(resp) + "\n")
                f.flush()
        except (OSError, ValueError):
            pass
        finally:
            conn.close()

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class MasterClient:
    """Trainer-side client (reference python/paddle/v2/master/client.py)."""

    def __init__(self, endpoint, timeout=60.0):
        host, _, port = endpoint.rpartition(":")
        self._conn = socket.create_connection((host, int(port)), timeout=timeout)
        self._f = self._conn.makefile("rw")
        self._lock = threading.Lock()

    def _call(self, req):
        with self._lock:
            self._f.write(json.dumps(req) + "\n")
            self._f.flush()
            return json.loads(self._f.readline())

    def get_task(self, wait_s=0.2):
        """Blocks until a task is available; returns None when the dataset is
        exhausted (every task done or discarded)."""
        while True:
            resp = self._call({"op": "get_task"})
            if resp["status"] == "ok":
                return resp["task"]
            if resp["status"] == "no_more":
                return None
            time.sleep(wait_s)

    def task_finished(self, task_id):
        self._call({"op": "task_finished", "id": task_id})

    def task_failed(self, task_id):
        self._call({"op": "task_failed", "id": task_id})

    def stats(self):
        return self._call({"op": "stats"})

    def close(self):
        try:
            self._conn.close()
        except OSError:
            pass
