"""Fault-tolerant dataset task master + client.

Reference analog: go/master/service.go — partitions RecordIO chunks into
tasks (:69-106), dispatches them to trainers, re-dispatches tasks whose
owner times out (:311-341), discards tasks that failed `failure_max` times
(:368+), and snapshots state to etcd for recovery (:166-207); trainers use
python/paddle/v2/master/client.py (get_task / task_finished / task_failed).

TPU-native redesign: same task state machine, JSON-line protocol over TCP
(the cluster fabric here is plain sockets, like distributed/rpc.py), and the
etcd snapshot becomes an atomic local-file snapshot (the coordination service
of a TPU pod slice is per-job, not a shared etcd) — restart the master with
the same snapshot_path and pending/todo state is recovered.

Tasks are (path, begin, end) RecordIO byte ranges produced from
native.chunk_offsets, so a trainer reads its shard with
reader.creator.recordio(path, begin, end).
"""

import json
import os
import socket
import threading
import time
import warnings

from .. import native
from ..resilience import faults as _faults
from ..resilience import health as _health
from ..resilience.retry import DeadlineExceeded, RetryPolicy

__all__ = ["Master", "MasterClient"]


class _Task:
    def __init__(self, task_id, path, begin, end):
        self.id = task_id
        self.path = path
        self.begin = begin
        self.end = end
        self.failures = 0
        self.deadline = None  # set while dispatched

    def spec(self):
        return {"id": self.id, "path": self.path, "begin": self.begin, "end": self.end}


class Master:
    def __init__(
        self,
        endpoint="127.0.0.1:0",
        chunks_per_task=8,
        timeout_s=30.0,
        failure_max=3,
        snapshot_path=None,
    ):
        self.chunks_per_task = chunks_per_task
        self.timeout_s = timeout_s
        self.failure_max = failure_max
        self.snapshot_path = snapshot_path
        self.todo = []
        self.pending = {}  # id -> _Task
        self.done = []
        self.discarded = []
        self._lock = threading.Lock()
        self._next_id = 0
        host, _, port = endpoint.rpartition(":")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host or "127.0.0.1", int(port)))
        self._sock.listen(64)
        self.endpoint = "%s:%d" % (host or "127.0.0.1", self._sock.getsockname()[1])
        self._closed = False
        self._recovered = False
        if snapshot_path and os.path.exists(snapshot_path):
            self._recovered = self._recover()

    # ------------------------------ dataset -------------------------------

    def set_dataset(self, paths):
        """Partition files into chunk-range tasks (service.go partition()).
        A no-op after snapshot recovery — the restart script re-runs this, and
        appending fresh tasks would re-train every finished shard (the
        reference's SetDataset skips when state was recovered the same way)."""
        with self._lock:
            if self._recovered:
                return
            for path in paths:
                offsets = native.chunk_offsets(path) + [os.path.getsize(path)]
                for i in range(0, len(offsets) - 1, self.chunks_per_task):
                    begin = offsets[i]
                    end = offsets[min(i + self.chunks_per_task, len(offsets) - 1)]
                    self.todo.append(_Task(self._next_id, path, begin, end))
                    self._next_id += 1
            self._snapshot_locked()

    # ----------------------------- state I/O ------------------------------

    def _snapshot_locked(self):
        if not self.snapshot_path:
            return
        state = {
            "next_id": self._next_id,
            "todo": [t.spec() | {"failures": t.failures} for t in self.todo]
            + [t.spec() | {"failures": t.failures} for t in self.pending.values()],
            "done": self.done,
            "discarded": self.discarded,
        }
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        # crash point between write and rename (resilience fault injection):
        # a master dying here leaves only the .tmp — the committed snapshot
        # is still whole, which is what _recover depends on
        _faults.crash("snapshot_crash", self.snapshot_path)
        os.replace(tmp, self.snapshot_path)  # atomic, like etcd txn

    def _recover(self):
        """Rebuild state from the snapshot. A corrupt/truncated snapshot
        (torn disk, a crash that outran the atomic rename discipline of an
        older layout) must not kill the master: start fresh with a warning —
        re-partitioning the dataset re-trains some shards, losing the whole
        job loses all of them. Returns True iff state was recovered."""
        try:
            with open(self.snapshot_path) as f:
                state = json.load(f)
            next_id = state["next_id"]
            todo = []
            for spec in state["todo"]:
                t = _Task(spec["id"], spec["path"], spec["begin"], spec["end"])
                t.failures = spec.get("failures", 0)
                todo.append(t)
            done = state["done"]
            discarded = state["discarded"]
        except (OSError, ValueError, KeyError, TypeError) as e:
            _health.incr("master_snapshot_corrupt")
            warnings.warn(
                "master snapshot %s unreadable (%r); starting fresh"
                % (self.snapshot_path, e)
            )
            return False
        self._next_id = next_id
        self.todo = todo
        self.done = done
        self.discarded = discarded
        return True

    # ----------------------------- scheduling -----------------------------

    def _requeue_timed_out_locked(self):
        now = time.monotonic()
        for tid in [t for t, task in self.pending.items() if task.deadline < now]:
            task = self.pending.pop(tid)
            task.failures += 1
            if task.failures >= self.failure_max:
                self.discarded.append(task.id)  # service.go failure_max drop
            else:
                self.todo.append(task)

    def _handle(self, req):
        op = req.get("op")
        with self._lock:
            self._requeue_timed_out_locked()
            if op == "get_task":
                if not self.todo:
                    if self.pending:
                        return {"status": "wait"}
                    return {"status": "no_more"}
                task = self.todo.pop(0)
                task.deadline = time.monotonic() + self.timeout_s
                self.pending[task.id] = task
                self._snapshot_locked()
                return {"status": "ok", "task": task.spec()}
            if op == "task_finished":
                task = self.pending.pop(int(req["id"]), None)
                if task is not None:
                    self.done.append(task.id)
                    self._snapshot_locked()
                return {"status": "ok"}
            if op == "task_failed":
                task = self.pending.pop(int(req["id"]), None)
                if task is not None:
                    task.failures += 1
                    if task.failures >= self.failure_max:
                        self.discarded.append(task.id)
                    else:
                        self.todo.append(task)
                    self._snapshot_locked()
                return {"status": "ok"}
            if op == "stats":
                return {
                    "status": "ok",
                    "todo": len(self.todo),
                    "pending": len(self.pending),
                    "done": len(self.done),
                    "discarded": len(self.discarded),
                }
        return {"status": "bad_request"}

    # ------------------------------ serving -------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self

    def _serve(self):
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn):
        try:
            f = conn.makefile("rw")
            for line in f:
                resp = self._handle(json.loads(line))
                if _faults.fires("master_conn_drop"):
                    # injected worker-facing failure: the request WAS handled
                    # but the reply is lost (the realistic half-failure — a
                    # dropped get_task reply leaves the task pending until
                    # the timeout re-queues it); client reconnect-retries
                    return
                f.write(json.dumps(resp) + "\n")
                f.flush()
        except (OSError, ValueError):
            pass
        finally:
            conn.close()

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class MasterClient:
    """Trainer-side client (reference python/paddle/v2/master/client.py).

    Calls run under the unified RetryPolicy with reconnect: a master restart
    or a dropped connection is retried with backoff instead of killing the
    trainer. `op_timeout` bounds each connect/read (a HUNG master surfaces
    as a typed DeadlineExceeded), while `timeout` is the OVERALL retry
    budget per call — the two deadlines are deliberately distinct.

    Retry safety: every master op is either read-only (stats), idempotent
    (task_finished/task_failed re-apply as no-ops once the task left
    pending), or self-healing (a get_task whose reply is lost re-queues via
    the task timeout) — so blanket retry is correct here, unlike the RPC
    variable-send path."""

    def __init__(self, endpoint, timeout=60.0, op_timeout=10.0, max_attempts=5):
        host, _, port = endpoint.rpartition(":")
        self._addr = (host, int(port))
        self._op_timeout = op_timeout
        self._conn = None
        self._f = None
        self._lock = threading.Lock()
        self._retry = RetryPolicy(
            max_attempts=max_attempts,
            base_delay=0.05,
            max_delay=1.0,
            deadline=timeout,
        )
        self._connect()  # fail fast on a wrong endpoint, like before

    def _connect(self):
        self._conn = socket.create_connection(self._addr, timeout=self._op_timeout)
        self._f = self._conn.makefile("rw")

    def _drop_conn(self):
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
        self._conn = None
        self._f = None

    def _call(self, req):
        line = json.dumps(req) + "\n"

        def attempt():
            with self._lock:
                if self._f is None:
                    self._connect()
                try:
                    self._f.write(line)
                    self._f.flush()
                    resp = self._f.readline()
                except socket.timeout as e:
                    self._drop_conn()
                    raise DeadlineExceeded(
                        "master %s:%d: no reply within %.1fs"
                        % (self._addr + (self._op_timeout,))
                    ) from e
                except OSError:
                    self._drop_conn()
                    raise
                if not resp:  # EOF: master closed/dropped the connection
                    self._drop_conn()
                    raise ConnectionError("master closed connection")
                return json.loads(resp)

        return self._retry.call(
            attempt, on_retry=lambda _a, _e: _health.incr("master_retries")
        )

    def get_task(self, wait_s=0.2):
        """Blocks until a task is available; returns None when the dataset is
        exhausted (every task done or discarded)."""
        while True:
            resp = self._call({"op": "get_task"})
            if resp["status"] == "ok":
                return resp["task"]
            if resp["status"] == "no_more":
                return None
            time.sleep(wait_s)

    def task_finished(self, task_id):
        self._call({"op": "task_finished", "id": task_id})

    def task_failed(self, task_id):
        self._call({"op": "task_failed", "id": task_id})

    def stats(self):
        return self._call({"op": "stats"})

    def close(self):
        with self._lock:
            self._drop_conn()
