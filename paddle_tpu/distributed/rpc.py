"""Socket RPC for parameter-shard training: variable send/get + barriers.

Reference analog: paddle/fluid/operators/distributed/ — RPCClient
(rpc_client.h:36-79: AsyncSendVar/AsyncGetVar/barriers/SendComplete), RPCServer
(rpc_server.h:48-105: registered handlers + barrier machinery), and the gRPC
wire format (send_recv.proto.in VariableMessage; zero-copy serialization in
grpc_serde.cc). Redesigned host-side for the TPU runtime: a length-prefixed
binary frame over TCP — varname, dtype, dims, raw tensor bytes — with no
protobuf/pickle dependency; tensors cross the wire as the numpy buffer exactly
once (the grpc_serde zero-extra-copy property).

Frame layout (little-endian):
  u8   msg kind (SEND_VAR / GET_VAR / VAR_REPLY / SEND_BARRIER / FETCH_BARRIER
                 / COMPLETE / ACK)
  i32  trainer_id
  u16  len(varname), varname utf-8
  u16  len(dtype str), dtype utf-8      (SEND_VAR / VAR_REPLY only; 0 marks a
                                         var-less frame and ends it — the
                                         "unknown var" reply)
  u8   ndim, i64 × ndim dims            (SEND_VAR / VAR_REPLY, dtype len > 0)
  u64  payload byte length, payload     (SEND_VAR / VAR_REPLY, dtype len > 0)
"""

import socket
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..resilience import faults as _faults
from ..resilience import health as _health
from ..resilience.retry import DeadlineExceeded, FatalError, RetryPolicy

__all__ = ["RPCClient", "RPCServer", "serialize_var", "read_frame"]

SEND_VAR = 1
GET_VAR = 2
VAR_REPLY = 3
SEND_BARRIER = 4
FETCH_BARRIER = 5
COMPLETE = 6
ACK = 7

_HEADER = struct.Struct("<Bi")
_U16 = struct.Struct("<H")
_U64 = struct.Struct("<Q")


def _pack_str(s):
    b = s.encode("utf-8")
    return _U16.pack(len(b)) + b


def serialize_var(kind, trainer_id, name, array=None):
    parts = [_HEADER.pack(kind, trainer_id), _pack_str(name)]
    if array is not None:
        arr = np.ascontiguousarray(array)
        parts.append(_pack_str(str(arr.dtype)))
        parts.append(struct.pack("<B", arr.ndim))
        parts.append(struct.pack("<%dq" % arr.ndim, *arr.shape))
        payload = arr.tobytes()  # the single host copy
        parts.append(_U64.pack(len(payload)))
        parts.append(payload)
    elif kind in (SEND_VAR, VAR_REPLY):
        parts.append(_U16.pack(0))  # zero dtype length = var-less frame
    return b"".join(parts)


def _recv_exact(sock, n):
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed")
        got += r
    return bytes(buf)


def read_frame(sock):
    """Returns (kind, trainer_id, varname, array-or-None)."""
    kind, trainer_id = _HEADER.unpack(_recv_exact(sock, _HEADER.size))
    (nlen,) = _U16.unpack(_recv_exact(sock, 2))
    name = _recv_exact(sock, nlen).decode("utf-8")
    arr = None
    if kind in (SEND_VAR, VAR_REPLY):
        (dlen,) = _U16.unpack(_recv_exact(sock, 2))
        if dlen:
            dtype = _recv_exact(sock, dlen).decode("utf-8")
            (ndim,) = struct.unpack("<B", _recv_exact(sock, 1))
            dims = struct.unpack("<%dq" % ndim, _recv_exact(sock, 8 * ndim)) if ndim else ()
            (plen,) = _U64.unpack(_recv_exact(sock, 8))
            payload = _recv_exact(sock, plen)
            arr = np.frombuffer(payload, dtype=dtype).reshape(dims)
    return kind, trainer_id, name, arr


class NonIdempotentError(FatalError):
    """A mutating frame failed after its bytes may have reached the server:
    resending could double-apply a gradient or double-count a barrier.
    Subclassed below with the concrete failure type mixed in, so callers
    keep catching ConnectionError/TimeoutError while RetryPolicy (for which
    FatalError is fatal) never resends."""


class _NonIdempotentConnError(NonIdempotentError, ConnectionError):
    pass


class _NonIdempotentDeadline(NonIdempotentError, DeadlineExceeded):
    pass


class RPCClient:
    """One per trainer process (reference rpc_client.h singleton GetInstance).
    Maintains one persistent connection per endpoint; async ops run on a
    thread pool, wait() joins them (AsyncSendVar/Wait semantics)."""

    _instance = None
    _lock = threading.Lock()

    @classmethod
    def instance(cls, trainer_id=0):
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls(trainer_id)
        return cls._instance

    def __init__(self, trainer_id=0, timeout=None):
        from .. import flags as _flags

        self.trainer_id = trainer_id
        # FLAGS_rpc_deadline governs connects and reply waits (reference
        # grpc_client.cc FLAGS_rpc_deadline)
        self.timeout = (
            float(_flags.get_flags("rpc_deadline")["rpc_deadline"])
            if timeout is None
            else timeout
        )
        self._socks = {}
        self._sock_locks = {}
        self._connect_lock = threading.Lock()
        self._pool = ThreadPoolExecutor(max_workers=8)
        self._futures = []

    def _sock(self, endpoint):
        # pool workers race to first-connect an endpoint; a per-endpoint
        # connect lock serializes creation without letting one slow/dead
        # endpoint's connect stall RPCs to every other endpoint
        try:
            return self._socks[endpoint], self._sock_locks[endpoint]
        except KeyError:
            pass
        with self._connect_lock:
            ep_lock = self._sock_locks.setdefault(endpoint, threading.Lock())
        with ep_lock:
            if endpoint not in self._socks:
                host, port = endpoint.rsplit(":", 1)
                s = socket.create_connection((host, int(port)), timeout=self.timeout)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._socks[endpoint] = s
            return self._socks[endpoint], ep_lock

    def _drop_sock(self, endpoint, sock):
        # drop ONLY the socket this attempt used: a concurrent worker may
        # already have reconnected a healthy one under the same endpoint
        with self._connect_lock:
            if self._socks.get(endpoint) is sock:
                self._socks.pop(endpoint, None)
        try:
            sock.close()
        except OSError:
            pass

    def _retry_policy(self):
        """Unified retry policy (resilience.retry): attempts from
        FLAGS_rpc_max_retry, overall budget FLAGS_rpc_deadline (the reference
        grpc_client.cc pair), exponential backoff + jitter between attempts."""
        from .. import flags as _flags

        fl = _flags.get_flags(["rpc_max_retry", "rpc_deadline"])
        return RetryPolicy(
            max_attempts=int(fl["rpc_max_retry"]) + 1,
            base_delay=0.05,
            max_delay=2.0,
            deadline=float(fl["rpc_deadline"]),
        )

    def _rpc(self, endpoint, frame, want_reply):
        """One request/response under the unified RetryPolicy: reconnect and
        retry on connection failure (a pserver restarting mid-training must
        not kill the trainer).

        Idempotency contract (unchanged from the hand-rolled loop this
        replaces): GET-style calls (want_reply) are repeatable; mutating
        frames (SEND_VAR, barriers) are retried only while the failure is at
        the CONNECT stage — once bytes may have reached the server, a resend
        could double-apply a gradient, so a fatal NonIdempotentError
        surfaces instead. Within one attempt, FLAGS_rpc_op_deadline bounds
        the reply wait so a HUNG peer becomes a typed DeadlineExceeded
        rather than an indefinite block on _recv_exact."""
        from .. import flags as _flags

        op_deadline = float(_flags.get_flags("rpc_op_deadline")["rpc_op_deadline"])

        def attempt():
            # connect stage — nothing sent yet, every failure is retryable
            # (OSError from _sock propagates as-is); injected faults land
            # here too so they are survivable for every frame kind
            sock, lock = self._sock(endpoint)
            if _faults.fires("rpc_drop"):
                self._drop_sock(endpoint, sock)
                raise ConnectionResetError("injected rpc_drop to %s" % endpoint)
            _faults.delay("rpc_delay")
            try:
                with lock:
                    sock.settimeout(op_deadline)
                    sock.sendall(frame)
                    # GETs read the VAR_REPLY; sends read the ACK that keeps
                    # them flow-controlled
                    kind, _, _name, arr = read_frame(sock)
                    if want_reply:
                        return arr if kind == VAR_REPLY else None
                    return None
            except socket.timeout as e:
                self._drop_sock(endpoint, sock)
                msg = "rpc to %s: no reply within %.1fs" % (endpoint, op_deadline)
                if not want_reply:
                    raise _NonIdempotentDeadline(msg) from e
                raise DeadlineExceeded(msg) from e
            except (OSError, EOFError) as e:
                self._drop_sock(endpoint, sock)
                if not want_reply:
                    raise _NonIdempotentConnError(
                        "rpc to %s failed after send may have been delivered "
                        "(not retried: non-idempotent): %r" % (endpoint, e)
                    ) from e
                raise

        return self._retry_policy().call(
            attempt, on_retry=lambda _a, _e: _health.incr("rpc_retries")
        )

    # --- async API (reference rpc_client.h:36-79) ---
    def async_send_var(self, endpoint, name, array):
        f = self._pool.submit(
            self._rpc, endpoint,
            serialize_var(SEND_VAR, self.trainer_id, name, np.asarray(array)),
            False,
        )
        self._futures.append(f)
        return f

    def async_get_var(self, endpoint, name):
        f = self._pool.submit(
            self._rpc, endpoint, serialize_var(GET_VAR, self.trainer_id, name), True
        )
        self._futures.append(f)
        return f

    def send_barrier(self, endpoint):
        f = self._pool.submit(
            self._rpc, endpoint, serialize_var(SEND_BARRIER, self.trainer_id, ""), False
        )
        self._futures.append(f)
        return f

    def fetch_barrier(self, endpoint):
        f = self._pool.submit(
            self._rpc, endpoint, serialize_var(FETCH_BARRIER, self.trainer_id, ""), False
        )
        self._futures.append(f)
        return f

    def send_complete(self, endpoint):
        try:
            self._rpc(endpoint, serialize_var(COMPLETE, self.trainer_id, ""), False)
        except (ConnectionError, OSError):
            pass  # server may already be down

    def wait(self):
        fs, self._futures = self._futures, []
        for f in fs:
            f.result(timeout=self.timeout)

    def close(self):
        for s in self._socks.values():
            try:
                s.close()
            except OSError:
                pass
        self._socks.clear()
        self._pool.shutdown(wait=False)
        with RPCClient._lock:
            if RPCClient._instance is self:
                RPCClient._instance = None


class RPCServer:
    """Parameter-shard server transport (reference rpc_server.h:48 +
    grpc_server.cc). Owns the listening socket and per-connection threads;
    the training-loop semantics (sync barriers, grad merge, optimize) live in
    listen_and_serv.py, wired in via the three handler callbacks, mirroring
    the reference's RequestSend/RequestGet handler registration."""

    def __init__(self, endpoint, fanin):
        host, port = endpoint.rsplit(":", 1)
        self.fanin = fanin
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host or "0.0.0.0", int(port)))
        self._listener.listen(64)
        self.endpoint = "%s:%d" % (host, self._listener.getsockname()[1])
        self._threads = []
        self._stop = threading.Event()
        self.cond = threading.Condition()
        # trainer_id -> monotonically increasing barrier count (see
        # listen_and_serv.py: round r waits for count > r; monotonic counters
        # replace the reference's racy ResetBarrierCounter)
        self.barrier_counts = {SEND_BARRIER: {}, FETCH_BARRIER: {}}
        self.exited_trainers = set()
        # handlers set by the serving loop (RequestSendHandler etc.)
        self.on_send = None  # fn(name, array, trainer_id)
        self.on_get = None  # fn(name, trainer_id) -> np array (may block)

    def start(self):
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self):
        self._listener.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._serve_conn, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while not self._stop.is_set():
                kind, trainer_id, name, arr = read_frame(conn)
                if kind == SEND_VAR:
                    self.on_send(name, arr, trainer_id)
                    conn.sendall(serialize_var(ACK, 0, ""))
                elif kind == GET_VAR:
                    out = self.on_get(name, trainer_id)
                    if out is None:
                        # unknown var: reply empty so the client raises
                        # instead of timing out (reference returns a gRPC
                        # error status)
                        conn.sendall(serialize_var(VAR_REPLY, 0, name, None))
                    else:
                        conn.sendall(serialize_var(VAR_REPLY, 0, name, out))
                elif kind in (SEND_BARRIER, FETCH_BARRIER):
                    with self.cond:
                        counts = self.barrier_counts[kind]
                        counts[trainer_id] = counts.get(trainer_id, 0) + 1
                        self.cond.notify_all()
                    conn.sendall(serialize_var(ACK, 0, ""))
                elif kind == COMPLETE:
                    with self.cond:
                        self.exited_trainers.add(trainer_id)
                        self.cond.notify_all()
                    conn.sendall(serialize_var(ACK, 0, ""))
        except (ConnectionError, OSError):
            pass
        except BaseException:
            import traceback

            traceback.print_exc()
        finally:
            conn.close()

    # --- barrier machinery (reference rpc_server.h WaitBarrier/ResetBarrier) ---
    def wait_barrier(self, kind, round_idx):
        """Wait until every live trainer passed barrier round `round_idx`
        (count > round_idx); returns False once every trainer exited instead
        (graceful shutdown, rpc_server.h:98 Complete)."""
        with self.cond:
            while True:
                if len(self.exited_trainers) >= self.fanin:
                    return False
                counts = self.barrier_counts[kind]
                passed = sum(
                    1
                    for t, c in counts.items()
                    if c > round_idx and t not in self.exited_trainers
                )
                if passed >= self.fanin - len(self.exited_trainers):
                    return True
                self.cond.wait(timeout=0.5)

    def wait_all_exited(self):
        with self.cond:
            while len(self.exited_trainers) < self.fanin:
                self.cond.wait(timeout=0.5)

    def all_exited(self):
        with self.cond:
            return len(self.exited_trainers) >= self.fanin

    def stop(self):
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass


class CollectiveClient:
    """Gather a named var from many servers at once (reference
    distributed/collective_client.h:62 CollectiveClient::Gather of remote
    SelectedRows slices — the cross-node sparse-allgather building block).
    Dense redesign: each pserver serves its slice; gather returns them in
    endpoint order for host-side concat."""

    def __init__(self, trainer_id=0):
        self._client = RPCClient.instance(trainer_id)

    def gather(self, endpoints, var_name, timeout=None):
        # one OVERALL deadline across all endpoints (the futures run
        # concurrently; per-future fresh budgets would multiply the wait)
        budget = self._client.timeout if timeout is None else timeout
        deadline = time.monotonic() + budget
        futures = [
            (ep, self._client.async_get_var(ep, var_name)) for ep in endpoints
        ]
        out = []
        for ep, f in futures:
            remaining = max(deadline - time.monotonic(), 0.001)
            arr = f.result(timeout=remaining)
            if arr is None:
                raise KeyError("gather: %s has no var %r" % (ep, var_name))
            out.append(arr)
        return out
