"""Distributed runtime: socket RPC + parameter-shard serving loop.

Reference analog: paddle/fluid/operators/distributed/ (SURVEY.md §2.7). The
collective path (multi-host SPMD over ICI/DCN) lives in paddle_tpu/parallel/;
this package is the host-side RPC tier used by the pserver transpile mode.
"""

from .rpc import RPCClient, RPCServer  # noqa: F401

from . import master  # noqa: F401
from .master import Master, MasterClient  # noqa: F401
from .rpc import CollectiveClient  # noqa: F401
