"""Parameter-shard serving loop (the pserver main op).

Reference analog: operators/distributed_ops/listen_and_serv_op.cc —
RunSyncLoop (:106-176: wait send-barrier → run optimize sub-blocks per grad →
serve gets until fetch-barrier) and RunAsyncLoop (:216: optimize immediately
per arriving grad, no barriers) — plus the request handlers
(distributed/request_handler_impl.cc: sync-mode scope merge of per-trainer
grads, get serves params). The optimizer sub-blocks execute through the same
whole-block XLA executor as regular programs (executor.py), so shard updates
run compiled, not interpreted.

Synchronization redesign: the reference resets barrier counters each round
(rpc_server.h ResetBarrierCounter), which races with fast trainers; here each
trainer's barrier count is MONOTONIC and round r waits for count > r — no
reset, no race.
"""

import logging
import threading

import numpy as np

from .. import framework
from .rpc import FETCH_BARRIER, SEND_BARRIER, RPCServer

__all__ = ["run_pserver"]


class _BlockRunner:
    """Compile-and-run one sub-block against the pserver scope."""

    def __init__(self, program, block, scope):
        self.program = program
        self.block = block
        self.scope = scope
        self._compiled = None

    def run(self):
        from ..executor import _CompiledBlock

        if self._compiled is None:
            self._compiled = _CompiledBlock(
                self.program, self.block, [], [], self.scope
            )
        self._compiled(self.scope, {})


def run_pserver(op, scope):
    """Blocks until every trainer sent COMPLETE (reference listen_and_serv
    blocks its executor thread the same way)."""
    attrs = op.attrs
    endpoint = attrs["endpoint"]
    sync_mode = bool(attrs.get("sync_mode", True))
    fanin = int(attrs.get("Fanin", 1))
    program = op.block.program
    opt_block_ids = list(attrs.get("optimize_blocks", []))
    grad_to_block_id = dict(
        kv.split(":") for kv in attrs.get("grad_to_block_id", [])
    )
    lr_block_id = int(attrs.get("lr_decay_block_id", -1))

    server = RPCServer(endpoint, fanin)
    runners = {
        bid: _BlockRunner(program, program.block(bid), scope)
        for bid in opt_block_ids
    }
    lr_runner = (
        _BlockRunner(program, program.block(lr_block_id), scope)
        if lr_block_id >= 0
        else None
    )
    grad_block = {g: int(b) for g, b in grad_to_block_id.items()}

    state_lock = threading.Lock()
    staged = {}  # grad name -> accumulated np array (sync mode round staging)
    prefetch_ids = {}  # (trainer_id, "<table>:<req>") -> staged __prefetch__ ids
    optimized_rounds = [0]
    ready = threading.Condition()
    # gradient-merge window state, shared with the checkpoint handler so a
    # mid-window checkpoint/restore resumes the exact trajectory: acc holds
    # the rounds accumulated so far, phase the count of rounds into the
    # current window. Restored values arrive via scope under the reserved
    # __gm_acc__:/__gm_rnd_phase__ names (written by a prior checkpoint).
    gm_state = {"acc": {}, "phase": 0}
    for vname in list(scope.vars):
        if vname == "__gm_rnd_phase__":
            gm_state["phase"] = int(
                np.asarray(scope.vars.pop(vname)).reshape(())
            )
        elif vname.startswith("__gm_acc__:"):
            gm_state["acc"][vname[len("__gm_acc__:"):]] = np.asarray(
                scope.vars.pop(vname)
            )

    def on_send(name, arr, trainer_id):
        if arr is None:
            return
        if name.startswith("__prefetch_ids__:"):
            # RequestPrefetchHandler (request_handler_impl.h + parameter_
            # prefetch.cc): stage the id vector; the matching GET computes
            # and returns the table rows. Keyed per trainer so concurrent
            # prefetches of the same table don't collide.
            with state_lock:
                prefetch_ids[(trainer_id, name.split(":", 1)[1])] = np.asarray(arr)
            return
        if sync_mode:
            with state_lock:
                cur = staged.get(name)
                staged[name] = arr.copy() if cur is None else cur + arr
        else:
            # async: optimize immediately per arriving grad (RunAsyncLoop)
            with state_lock:
                scope.set_var(name, _to_device(arr))
                bid = grad_block.get(name)
                if bid is not None:
                    runners[bid].run()

    def on_get(name, trainer_id):
        if name.startswith("__prefetch_out__:"):
            # key layout: __prefetch_out__:<table>:<req> — rows of this
            # shard's table slice for the staged ids (masked slots, id<0,
            # return zero rows; merge_ids drops them by position)
            key = name.split(":", 1)[1]
            table_name, _, _req = key.partition(":")
            with state_lock:
                ids = prefetch_ids.pop((trainer_id, key), None)
                table = scope.find_var(table_name)
            if ids is None or table is None:
                return None
            tbl = np.asarray(table)
            ids64 = ids.astype(np.int64)
            # ids here are GLOBAL row ids served against a full table; the
            # split_byref row-sharded layout is not served via prefetch (the
            # distribute transpiler keeps lookup tables whole on one pserver
            # — see distribute_transpiler lookup-table rewrite). Reject out-
            # of-range ids loudly instead of clamping to the last row.
            if np.any(ids64 >= tbl.shape[0]):
                # empty reply → the client raises (same contract as an
                # unknown var) instead of silently serving the last row
                logging.error(
                    "prefetch id %d out of range for table %r with %d rows",
                    int(ids64.max()), table_name, tbl.shape[0],
                )
                return None
            # masked slots (id<0) index row 0 then zero out below
            idx = np.maximum(ids64, 0)
            rows = tbl[idx]
            rows[ids64 < 0] = 0
            return rows
        if name.startswith("__checkpoint__:"):
            # RequestCheckpointHandler (request_handler_impl.h:103): persist
            # this shard's vars under the trainer-provided dir, outside the
            # barrier protocol so a notify can land mid-round
            ckpt_dir = name.split(":", 1)[1]
            if not ckpt_dir:
                return None  # var-less reply → client raises instead of
                # reporting a checkpoint that was never written
            from .. import io as fluid_io

            # jax arrays are immutable and set_var only rebinds names, so a
            # dict snapshot under the lock is a consistent checkpoint; the
            # device→host copies and disk writes run outside it so concurrent
            # sends/optimize rounds don't stall on I/O. Grad staging vars
            # (`*@GRAD`) are transient — skip them, like save_persistables.
            with state_lock:
                snapshot = {
                    vname: val
                    for vname, val in scope.vars.items()
                    if val is not None and "@" not in vname
                }
                # gradient-merge window state rides in the checkpoint under
                # reserved names so a restored pserver resumes mid-window
                # (run_pserver pops them back out of the scope at start)
                if gm_state["acc"] or gm_state["phase"]:
                    snapshot["__gm_rnd_phase__"] = np.asarray(
                        [gm_state["phase"]], np.int64
                    )
                    for g, arr in gm_state["acc"].items():
                        snapshot["__gm_acc__:" + g] = arr
            fluid_io.save_arrays(ckpt_dir, snapshot)
            return np.ones((1,), np.int64)
        if sync_mode:
            # serve only after this trainer's current round was optimized
            want = server.barrier_counts[SEND_BARRIER].get(trainer_id, 0)
            with ready:
                while optimized_rounds[0] < want and not server.all_exited():
                    ready.wait(timeout=0.5)
        val = scope.find_var(name)
        return None if val is None else np.asarray(val)

    server.on_send = on_send
    server.on_get = on_get
    server.start()
    op.attrs["__bound_endpoint__"] = server.endpoint  # port 0 → real port

    try:
        if sync_mode:
            # pserver-side gradient merge (reference
            # ir/multi_batch_merge_pass.cc driven by
            # test_dist_mnist_batch_merge.py — there the TRAINER accumulates k
            # micro-batch grads before one optimizer step; summing on the
            # pserver across k sync rounds is numerically the same fold and
            # composes with sharding without conditional RPC): accumulate the
            # trainer-summed grads each round, run the optimize blocks every
            # k-th round on the (optionally k-averaged) accumulator. A
            # partial window at training end is discarded, like the
            # reference's trailing micro-batches.
            gm_k = int(attrs.get("gradient_merge_k", 0) or 0)
            gm_avg = bool(attrs.get("gradient_merge_avg", True))
            rnd = 0
            while True:
                if not server.wait_barrier(SEND_BARRIER, rnd):
                    break
                # state_lock covers the scope mutations too, so a concurrent
                # checkpoint snapshot never sees torn mid-update params
                with state_lock:
                    grads = dict(staged)
                    staged.clear()
                    if gm_k > 1:
                        gm_acc = gm_state["acc"]
                        for g, arr in grads.items():
                            gm_acc[g] = (
                                arr if g not in gm_acc else gm_acc[g] + arr
                            )
                        gm_state["phase"] += 1
                        if gm_state["phase"] % gm_k == 0:
                            for g, arr in gm_acc.items():
                                scope.set_var(
                                    g,
                                    _to_device(arr / gm_k if gm_avg else arr),
                                )
                            if lr_runner is not None:
                                lr_runner.run()
                            for g in gm_acc:
                                bid = grad_block.get(g)
                                if bid is not None:
                                    runners[bid].run()
                            gm_acc.clear()
                            gm_state["phase"] = 0
                    else:
                        for g, arr in grads.items():
                            # sync merge = sum over trainers, then the
                            # per-grad optimize block
                            # (request_handler_impl.cc scope merge)
                            scope.set_var(g, _to_device(arr))
                        if lr_runner is not None:
                            lr_runner.run()
                        for g in grads:
                            bid = grad_block.get(g)
                            if bid is not None:
                                runners[bid].run()
                with ready:
                    optimized_rounds[0] = rnd + 1
                    ready.notify_all()
                if not server.wait_barrier(FETCH_BARRIER, rnd):
                    break
                rnd += 1
        else:
            server.wait_all_exited()
    except BaseException:
        # serving-loop failures must be visible: they run on daemon threads
        # (reference pserver glog-fatals here)
        import traceback

        traceback.print_exc()
        raise
    finally:
        with ready:
            ready.notify_all()
        server.stop()


def _to_device(arr):
    import jax.numpy as jnp

    return jnp.asarray(arr)
