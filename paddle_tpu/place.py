"""Places (reference paddle/fluid/platform/place.h:26-99 — CPUPlace,
CUDAPlace, CUDAPinnedPlace). The TPU build adds TPUPlace — SURVEY.md's north
star — and keeps CUDAPlace as a compatibility alias so unchanged fluid scripts
run (device selection maps onto jax devices; actual placement is XLA's)."""

import jax

__all__ = ["CPUPlace", "CUDAPlace", "CUDAPinnedPlace", "TPUPlace", "is_compiled_with_cuda"]


class Place:
    def __eq__(self, other):
        return type(self) is type(other) and getattr(self, "device_id", 0) == getattr(
            other, "device_id", 0
        )

    def __hash__(self):
        return hash((type(self).__name__, getattr(self, "device_id", 0)))


class CPUPlace(Place):
    def jax_device(self):
        return jax.devices("cpu")[0]

    def __repr__(self):
        return "CPUPlace"


class TPUPlace(Place):
    def __init__(self, device_id=0):
        self.device_id = device_id

    def jax_device(self):
        devs = jax.devices()
        return devs[self.device_id % len(devs)]

    def __repr__(self):
        return "TPUPlace(%d)" % self.device_id


class CUDAPlace(TPUPlace):
    """Compatibility alias: fluid scripts that say CUDAPlace(0) run on the
    TPU chip instead — the drop-in promise of BASELINE.json's north star."""

    def __repr__(self):
        return "CUDAPlace(%d)->TPU" % self.device_id


class CUDAPinnedPlace(CPUPlace):
    def __repr__(self):
        return "CUDAPinnedPlace"


def is_compiled_with_cuda():
    return False
