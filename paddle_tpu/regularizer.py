"""Weight-decay regularizers appended as graph ops (reference
python/paddle/fluid/regularizer.py: L1DecayRegularizer, L2DecayRegularizer,
append_regularization_ops)."""

from .framework import OpRole, default_main_program

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer",
           "append_regularization_ops"]


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def __call__(self, param, grad, block):
        decay = block.create_var(dtype=param.dtype, shape=param.shape)
        block.append_op(
            type="scale",
            inputs={"X": [param.name]},
            outputs={"Out": [decay.name]},
            attrs={"scale": self._regularization_coeff},
        )
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def __call__(self, param, grad, block):
        sign = block.create_var(dtype=param.dtype, shape=param.shape)
        block.append_op(
            type="sign", inputs={"X": [param.name]}, outputs={"Out": [sign.name]}
        )
        decay = block.create_var(dtype=param.dtype, shape=param.shape)
        block.append_op(
            type="scale",
            inputs={"X": [sign.name]},
            outputs={"Out": [decay.name]},
            attrs={"scale": self._regularization_coeff},
        )
        return decay


def append_regularization_ops(parameters_and_grads, regularization=None):
    """grad += regularizer(param); per-param regularizer overrides the global
    one (reference regularizer.py:25 append_regularization_ops)."""
    params_and_grads = []
    program = default_main_program()
    for param, grad in parameters_and_grads:
        if grad is None:
            params_and_grads.append((param, grad))
            continue
        if getattr(grad, "is_selected_rows", False):
            # weight decay on a SelectedRows grad would touch EVERY table row
            # (the decay term is param-shaped), densifying the update and
            # defeating the O(touched-rows) cost — the reference raised for
            # this combination (regularization_op + SelectedRows); we skip
            # decay on sparse tables instead
            params_and_grads.append((param, grad))
            continue
        regularization_term = None
        with program._optimized_guard([param, grad]):
            block = grad.block
            if param.regularizer is not None:
                regularization_term = param.regularizer(param, grad, block)
            elif regularization is not None:
                regularization_term = regularization(param, grad, block)
            if regularization_term is None:
                params_and_grads.append((param, grad))
                continue
            block.append_op(
                type="elementwise_add",
                inputs={"X": [grad.name], "Y": [regularization_term.name]},
                outputs={"Out": [grad.name]},
                attrs={"axis": -1},
            )
        params_and_grads.append((param, grad))
    return params_and_grads


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
