"""Operator registry: lowering, shape inference, and gradient definitions.

Reference analog: paddle/fluid/framework/op_registry.h:196 (REGISTER_OPERATOR)
plus per-op InferShape and GradOpDescMaker (grad_op_desc_maker.h). The TPU-first
redesign collapses all three into one artifact — the JAX lowering:

- **lowering**: `lower(ctx, ins, attrs) -> outs` maps slot-name->[jax arrays] to
  slot-name->[jax arrays]. This replaces the reference's per-op CPU/CUDA kernels
  (operators/*.cc/.cu); XLA fuses across ops since the executor lowers whole
  blocks into one jitted function (executor.py).
- **shape inference**: `jax.eval_shape` over the lowering — free and always
  consistent with execution, replacing ~400 hand-written InferShape functions.
  Dynamic batch dims (-1) are substituted with a sentinel extent and mapped back.
- **gradients**: unless an op registers a custom grad, `{type}_grad` is derived
  automatically with `jax.vjp` over the forward lowering (functional transforms
  instead of hand-written *_grad kernels). append_backward (backward.py) emits
  grad ops in the program exactly like the reference's GradOpDescMaker pass.
"""

import functools
import re as _re

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework

# Sentinel extent substituted for -1 (dynamic batch) dims during eval_shape.
# Any output dim equal to it is mapped back to -1. Chosen to be an implausible
# real extent; collisions would only mislabel build-time metadata, never
# execution (the executor re-traces with concrete feed shapes).
_DYN_SENTINEL = 8191

_GRAD_SUFFIX = "@GRAD"

# meta attrs attached by backward.py to generic grad ops
FWD_IN_SLOTS_ATTR = "__fwd_in_slots__"
FWD_OUT_SLOTS_ATTR = "__fwd_out_slots__"

_META_ATTRS = (
    FWD_IN_SLOTS_ATTR,
    FWD_OUT_SLOTS_ATTR,
    framework.OpRole.OP_ROLE_KEY,
    framework.OpRole.OP_ROLE_VAR_KEY,
)


class OpDef:
    def __init__(
        self,
        type,
        lower=None,
        infer_shape=None,
        grad=None,
        no_grad=False,
        stochastic=False,
        skip_exec=False,
        host_fn=None,
        abstract_eval=None,
    ):
        self.type = type
        self.lower = lower
        self.custom_infer_shape = infer_shape
        # abstract_eval: the static analyzer's transfer function
        # (analysis/dataflow.py), `fn(actx, op, ins) -> {slot: [VarFact]}`.
        # Most ops need none — the analyzer abstracts the lowering itself
        # with jax.eval_shape, the same machinery infer_shape below uses.
        # Register one only where the lowering cannot be abstracted from
        # flat tensor facts: control-flow ops recurse into their sub-blocks
        # (actx.analyze_block), tensor-array ops model (buffer, size) pairs
        # (ops/control_flow_ops.py).
        self.abstract_eval = abstract_eval
        # grad: fn(op, block, grad_name_map) -> list of op-spec dicts, or None
        # for the generic vjp-derived gradient.
        self.grad = grad
        self.no_grad = no_grad
        self.stochastic = stochastic
        self.skip_exec = skip_exec  # executor/infer ignore (feed/fetch markers)
        # host ops run OUTSIDE the jitted computation, between XLA segments
        # (RPC send/recv, listen_and_serv, checkpoint notify — the reference's
        # non-kernel OperatorBase ops, SURVEY.md §2.7). Signature:
        # host_fn(op, scope). The executor partitions the block at host ops.
        self.host_fn = host_fn

    @property
    def is_host(self):
        return self.host_fn is not None


OPS = {}


def register(type, **kwargs):
    """Decorator: @register("matmul") def lower(ctx, ins, attrs): ..."""

    def deco(fn):
        OPS[type] = OpDef(type, lower=fn, **kwargs)
        return fn

    return deco


def register_no_lower(type, **kwargs):
    OPS[type] = OpDef(type, lower=None, skip_exec=True, **kwargs)


def register_host(type, **kwargs):
    """Decorator: @register_host("send") def run(op, scope): ... Host ops are
    no-grad and contribute no shape inference."""

    def deco(fn):
        OPS[type] = OpDef(type, lower=None, no_grad=True, host_fn=fn, **kwargs)
        return fn

    return deco


def get(type):
    d = OPS.get(type)
    if d is not None:
        return d
    if type.endswith("_grad"):
        base = OPS.get(type[: -len("_grad")])
        if base is not None and base.lower is not None:
            d = OpDef(type, lower=_make_generic_grad(base), no_grad=True)
            OPS[type] = d
            return d
    raise KeyError("no op registered for type %r" % type)


def is_registered(type):
    try:
        get(type)
        return True
    except KeyError:
        return False


class LowerCtx:
    """Per-trace context handed to lowerings. Threads the PRNG key through the
    block (stochastic ops call next_rng()), carries build attrs, and exposes
    the SPMD mesh (None single-device) so mesh-aware ops (ring attention,
    sharded embedding) can pick their distributed lowering.

    zero1_axis (a mesh axis name, normally 'dp') selects the ZeRO-1 sharded
    optimizer tier: optimizer-op lowerings (core_ops._opt_f32) constrain their
    gradient to a sharded layout (GSPMD → reduce-scatter), update the 1/dp
    param+moment shard locally, and constrain ParamOut back to replicated
    (→ all-gather). Set by _CompiledBlock when the ParallelExecutor build
    strategy asks for ReduceStrategy.Reduce.

    sharding (a parallel.sharding_rules.Resolver, or None) is the
    declarative rule engine bound to this trace's mesh: optimizer lowerings
    consult it for the parameter's storage layout (FSDP/TP take precedence
    over the zero1 tier per param), fused Pallas lowerings decline when it
    shards their tile dims, and _lower_one constrains rule-matched op
    outputs. `op` is the framework Operator currently being lowered (set by
    _lower_one; lowerings only see traced values, so the op is the only
    handle back to variable NAMES)."""

    def __init__(self, key, is_test=False, mesh=None, zero1_axis=None,
                 sharding=None):
        self.key = key
        self.is_test = is_test
        self.mesh = mesh
        self.zero1_axis = zero1_axis
        self.sharding = sharding
        self.op = None

    def next_rng(self):
        self.key, sub = jax.random.split(self.key)
        return sub


def _clean_attrs(attrs):
    return {k: v for k, v in attrs.items() if k not in _META_ATTRS}


def _make_generic_grad(fwd_def):
    """Build the vjp-derived lowering for `{type}_grad`.

    The grad op's inputs follow the reference convention (grad_op_desc_maker.h
    DefaultGradOpDescMaker): forward input slots, forward output slots, and
    `<out-slot>@GRAD` cotangents. Outputs are `<in-slot>@GRAD`. Differentiable
    leaves are the floating-point forward inputs; everything else rides in the
    closure. Missing cotangents become zeros.
    """

    def lower(ctx, ins, attrs):
        in_slots = list(attrs[FWD_IN_SLOTS_ATTR])
        out_slots = list(attrs[FWD_OUT_SLOTS_ATTR])
        fwd_attrs = _clean_attrs(attrs)
        fwd_ins = {s: list(ins[s]) for s in in_slots if s in ins}

        leaves, spec = [], []
        for s in in_slots:
            for i, v in enumerate(fwd_ins.get(s, [])):
                if v is not None and jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating):
                    leaves.append(v)
                    spec.append((s, i))

        def f(*leaf_vals):
            d = {s: list(vs) for s, vs in fwd_ins.items()}
            for (s, i), v in zip(spec, leaf_vals):
                d[s][i] = v
            outs = fwd_def.lower(ctx, d, fwd_attrs)
            return tuple(tuple(outs.get(s, ())) for s in out_slots)

        primals, vjp_fn = jax.vjp(f, *leaves)

        cots = []
        for s, pvals in zip(out_slots, primals):
            gs = ins.get(s + _GRAD_SUFFIX)
            row = []
            for i, p in enumerate(pvals):
                g = gs[i] if gs is not None and i < len(gs) and gs[i] is not None else None
                row.append(
                    g.astype(p.dtype) if g is not None else jnp.zeros(p.shape, p.dtype)
                )
            cots.append(tuple(row))
        grads = vjp_fn(tuple(cots))

        out = {}
        for (s, i), g in zip(spec, grads):
            lst = out.setdefault(s + _GRAD_SUFFIX, {})
            lst[i] = g
        result = {}
        for s, d in out.items():
            n = max(d) + 1
            result[s] = [d.get(i) for i in range(n)]
        return result

    return lower


EMPTY_VAR_NAME = "@EMPTY@"  # reference core.kEmptyVarName

# named_scope only keeps a conservative charset (jax drops e.g. '@', so
# "x@GRAD" would silently become "x"); sanitize OURSELVES so the exact
# string that lands in the HLO op_name metadata is predictable and the
# parser (profiler._hlo_op_attribution) can invert it
_SCOPE_UNSAFE = _re.compile(r"[^A-Za-z0-9_.=\-]")
OUT_SCOPE_PREFIX = "out="

# passes.builtin.FuseElemwiseActPass tags matmul/conv+add[+act] chains with
# this attr; lower_ops lowers a contiguous run sharing one tag inside a
# single enclosing named_scope ("fusion_group=<id>") so XLA's fusion
# heuristics see the chain as a unit and the profiler can attribute its
# HLO to the group (profiler._hlo_op_attribution skips the wrapper segment)
FUSION_GROUP_ATTR = "__fusion_group__"
FUSION_SCOPE_PREFIX = "fusion_group="

# Kernel-substitution tier (docs/passes.md "Kernel substitution"): the
# fuse_gemm_epilogue / fuse_layer_norm / fuse_optimizer passes tag op runs
# with a group id + a kernel family name; lower_ops hands a contiguous
# same-group run to the family's registered FUSED lowering (a Pallas kernel,
# ops/pallas_kernels.py) instead of lowering op by op. A fused lowering may
# DECLINE at trace time (ragged shapes, unsupported attrs, ZeRO-1 sharding)
# by returning False — the run then falls back to per-op lowering with
# identical semantics, so tagging is always safe. Like FUSION_GROUP_ATTR
# the tags are attr-only: def-use, op order, and count are untouched.
PALLAS_GROUP_ATTR = "__pallas_group__"
PALLAS_KERNEL_ATTR = "__pallas_kernel__"
PALLAS_SCOPE_PREFIX = "pallas_kernel="

# kernel family name -> fused lowering fn(ctx, ops, env) -> bool (True when
# the run was handled and its outputs written into env)
FUSED_LOWERINGS = {}


def register_fused(family):
    """Decorator: @register_fused("gemm_epilogue")
    def lower_run(ctx, ops, env) -> bool: ..."""

    def deco(fn):
        FUSED_LOWERINGS[family] = fn
        return fn

    return deco


def gather_op_inputs(op, env):
    """Resolve an op's input slots from the lowering env (shared by
    _lower_one and the fused lowerings)."""
    ins = {}
    for slot, names in op.inputs.items():
        if names:
            ins[slot] = [
                env[n] if n != EMPTY_VAR_NAME else None for n in names
            ]
    return ins


def scatter_op_outputs(op, outs, env):
    """Bind an op's output slots back into the lowering env (shared by
    _lower_one and the fused lowerings)."""
    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        for name, val in zip(names, vals):
            if val is not None and name != EMPTY_VAR_NAME:
                env[name] = val


def op_output_scope(op):
    """Scope name carrying the op's identity (its first real output var) into
    the HLO metadata, or None for ops with no named outputs. Ops themselves
    are anonymous in fluid programs — outputs are the only stable handle."""
    for name in op.output_arg_names:
        if name != EMPTY_VAR_NAME:
            return OUT_SCOPE_PREFIX + _SCOPE_UNSAFE.sub("_", name)
    return None


def _lower_one(ctx, op, env):
    """Lower a single op into env (see lower_ops)."""
    opdef = get(op.type)
    if opdef.skip_exec:
        return
    ins = gather_op_inputs(op, env)
    # named_scope tags every HLO this op emits with op_name="…/<type>/…"
    # metadata — the correlation key profiler.device_op_profile uses to
    # fold XLA's per-HLO device timings back onto framework op types
    # (the reference correlates CUPTI kernels to ops the same way,
    # platform/device_tracer.cc). A nested "out=<first output>" scope
    # distinguishes op INSTANCES (profiler._hlo_op_attribution); the
    # type-level parse skips it, so device_op_profile is unchanged.
    out_scope = op_output_scope(op)
    ctx.op = op  # name handle for sharding-aware lowerings (LowerCtx doc)
    with jax.named_scope(op.type):
        if out_scope is None:
            outs = opdef.lower(ctx, ins, op.attrs)
        else:
            with jax.named_scope(out_scope):
                outs = opdef.lower(ctx, ins, op.attrs)
    ctx.op = None
    scatter_op_outputs(op, outs, env)
    if ctx.sharding is not None:
        # rule-matched outputs (params written back, annotated activations)
        # get their declared placement pinned right where they materialize
        ctx.sharding.constrain_outputs(op, env)


def _lower_pallas_run(ctx, run, env):
    """Try the registered fused Pallas lowering for a tagged run; fall back to
    per-op lowering when the family is unknown or the lowering declines."""
    family = run[0].attrs.get(PALLAS_KERNEL_ATTR)
    fused = FUSED_LOWERINGS.get(family)
    gid = run[0].attrs.get(PALLAS_GROUP_ATTR)
    # "<family>.<gid>" so the profiler can attribute the kernel's HLO to a
    # "pallas:<family>" row with per-group instances (profiler.py)
    scope = PALLAS_SCOPE_PREFIX + _SCOPE_UNSAFE.sub(
        "_", "%s.%s" % (family, gid)
    )
    if fused is not None:
        with jax.named_scope(scope):
            if fused(ctx, run, env):
                return
    for member in run:
        _lower_one(ctx, member, env)


def lower_ops(ctx, ops, env):
    """Lower a list of ops into an env (name -> traced value), rebinding
    outputs. The single shared interpreter loop for the whole-block executor
    (executor.py) and for sub-block control-flow ops (while/cond/recurrent in
    control_flow_ops.py) — the reference's Executor::RunPreparedContext loop
    (executor.cc:389-396) respectively its nested-Executor reuse inside
    while_op.cc:36.

    Contiguous runs of ops sharing a FUSION_GROUP_ATTR value (tagged by the
    fuse_elemwise_act pass) lower inside ONE enclosing named_scope: the
    group's HLO shares an op_name prefix, so XLA's fusion heuristics and the
    profiler's attribution both see the chain as a unit.

    Contiguous runs sharing a PALLAS_GROUP_ATTR value (tagged by the
    fuse_gemm_epilogue / fuse_layer_norm / fuse_optimizer passes) are handed
    to the family's fused Pallas lowering (FUSED_LOWERINGS); a decline falls
    back to per-op lowering. Pallas tags take precedence over fusion-group
    tags when an op carries both (the kernel subsumes the XLA fusion hint)."""
    i, n = 0, len(ops)
    while i < n:
        op = ops[i]
        pg = op.attrs.get(PALLAS_GROUP_ATTR)
        if pg is not None:
            j = i
            while j < n and ops[j].attrs.get(PALLAS_GROUP_ATTR) == pg:
                j += 1
            _lower_pallas_run(ctx, ops[i:j], env)
            i = j
            continue
        fg = op.attrs.get(FUSION_GROUP_ATTR)
        if fg is None:
            _lower_one(ctx, op, env)
            i += 1
            continue
        j = i
        while j < n and (
            ops[j].attrs.get(FUSION_GROUP_ATTR) == fg
            and ops[j].attrs.get(PALLAS_GROUP_ATTR) is None
        ):
            j += 1
        with jax.named_scope(
            FUSION_SCOPE_PREFIX + _SCOPE_UNSAFE.sub("_", str(fg))
        ):
            for member in ops[i:j]:
                _lower_one(ctx, member, env)
        i = j
    return env


# ---------------------------------------------------------------------------
# shape inference (reference: per-op InferShape, operator.cc:705; here derived
# from the lowering itself with jax.eval_shape)
# ---------------------------------------------------------------------------


def infer_shape(op, block):
    try:
        opdef = get(op.type)
    except KeyError:
        return  # unknown ops get shapes from custom layer code or stay None
    if opdef.custom_infer_shape is not None:
        opdef.custom_infer_shape(op, block)
        return
    if opdef.lower is None or opdef.skip_exec:
        return

    abstract_ins = {}
    for slot, names in op.inputs.items():
        vals = []
        for name in names:
            if name == EMPTY_VAR_NAME:
                vals.append(None)
                continue
            v = block._var_recursive(name)
            if v.shape is None or v.dtype is None:
                return  # cannot infer yet (e.g. fed later) — leave outputs as-is
            shape = tuple(_DYN_SENTINEL if d == -1 else d for d in v.shape)
            vals.append(jax.ShapeDtypeStruct(shape, jnp.dtype(v.dtype)))
        abstract_ins[slot] = vals

    attrs = dict(op.attrs)
    ctx = LowerCtx(jax.eval_shape(lambda: jax.random.key(0)), is_test=True)

    def run(ins):
        c = LowerCtx(jax.random.key(0), is_test=bool(attrs.get("is_test", False)))
        return opdef.lower(c, ins, attrs)

    try:
        outs = jax.eval_shape(run, abstract_ins)
    except Exception as e:  # surface shape errors at build time, like InferShape
        raise ValueError(
            "shape inference failed for op %s: %s" % (op, e)
        ) from e

    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        for name, aval in zip(names, vals):
            if aval is None or name == EMPTY_VAR_NAME:
                continue
            v = block._var_recursive(name)
            v.shape = tuple(-1 if d == _DYN_SENTINEL else d for d in aval.shape)
            v.dtype = framework.convert_np_dtype(aval.dtype)


# ---------------------------------------------------------------------------
# shared helpers for lowerings
# ---------------------------------------------------------------------------


def bcast_y(x, y, axis):
    """Paddle elementwise broadcast: align y's dims to x starting at `axis`
    (reference operators/elementwise/elementwise_op_function.h). axis=-1 means
    align trailing dims (NumPy style after right-padding)."""
    if x.ndim == y.ndim:
        return y
    if axis == -1:
        axis = x.ndim - y.ndim
    # trim trailing 1s in y (paddle allows y shape (..., 1, 1))
    yshape = list(y.shape)
    while yshape and yshape[-1] == 1 and len(yshape) > 1 and axis + len(yshape) > x.ndim:
        yshape.pop()
    new_shape = [1] * x.ndim
    for i, d in enumerate(yshape):
        new_shape[axis + i] = d
    return y.reshape(new_shape)


def reduce_grad_to_shape(g, shape):
    """Sum-reduce a broadcasted gradient back to `shape` (for custom grads)."""
    if tuple(g.shape) == tuple(shape):
        return g
    extra = g.ndim - len(shape)
    if extra > 0:
        g = g.sum(axis=tuple(range(extra)))
    axes = tuple(i for i, d in enumerate(shape) if d == 1 and g.shape[i] != 1)
    if axes:
        g = g.sum(axis=axes, keepdims=True)
    return g.reshape(shape)
