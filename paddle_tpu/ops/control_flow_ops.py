"""Control-flow ops: while / conditional_block / recurrent (scan) / tensor
arrays / print.

Reference analog: paddle/fluid/operators/controlflow/ — while_op.cc:36 runs its
sub-block via a nested Executor once per iteration, saving per-step scopes
(StepScopes) for the hand-written while_grad (while_op.cc:112);
conditional_block_op.cc likewise nests an Executor. The TPU-first redesign
lowers the sub-block *into the same XLA computation*:

- ``while``   -> jax.lax.while_loop over a carry of the loop-written outer vars
  (with ``maximum_iterations`` set, a masked lax.scan instead, which XLA can
  reverse-differentiate — replacing the reference's StepScopes grad machinery
  with jax.vjp through scan).
- ``conditional_block`` -> jax.lax.cond; the false branch returns the prior
  values of the written vars (the reference leaves them untouched in the scope;
  rebinding the old value is the functional equivalent).
- ``recurrent`` -> jax.lax.scan; this is the engine under StaticRNN/DynamicRNN
  (reference recurrent_op.cc + layers/control_flow.py:429,1546). Variable-length
  sequences use a SeqLen companion and per-row masking instead of the
  reference's shrinking-batch LoD reordering (SURVEY.md §5.7).
- tensor arrays (write_to_array / read_from_array, lod_tensor_to_array /
  array_to_lod_tensor, reference controlflow/tensor_array_read_write_op.cc,
  lod_tensor_to_array_op.cc) are (buffer[T, ...], size) pairs — a fixed-
  capacity time-major buffer plus a logical length, static shapes for XLA.

Carries in while/scan must be fixed-shape: arrays written inside a loop must be
pre-allocated (create_array(shape=...) or lod_tensor_to_array); outside loops
writes grow the buffer by concatenation (each call site is its own trace).
"""

import jax
import jax.numpy as jnp
from jax import lax

from .registry import LowerCtx, lower_ops, register

# Why the remaining _noop_infer escapes are genuinely dynamic — one entry
# per op still registered with it (tests/test_analysis.py asserts the two
# sets match, so a new noop escape must document itself here). Everything
# shape-inferable at build time has a real infer below; the static analyzer
# (analysis/dataflow.py) sees through even these via the abstract_eval
# hooks, which model array VALUES as (buffer, size) facts.
NOOP_INFER_REASONS = {
    "create_array": (
        "the array VALUE is a (buffer, size) pair; a capacity-less array "
        "has no buffer until the first trace-time write_to_array"
    ),
    "write_to_array": (
        "buffer capacity evolves with trace-time growth bookkeeping "
        "(init_cap / grow_slots) invisible in flat var metadata"
    ),
    "read_from_array": (
        "the element shape lives in the array VALUE's buffer, not in the "
        "array variable's flat metadata"
    ),
    "lod_tensor_to_array": (
        "the output is an array value (time-major buffer, size) that flat "
        "var metadata cannot carry"
    ),
    "array_to_lod_tensor": (
        "the output shape is the input array VALUE's buffer transposed — "
        "unknown until the buffer exists at trace time"
    ),
    # registered in decode_ops.py, documented here with the rest
    "beam_search_decode": (
        "hypothesis length is the Ids array VALUE's buffer capacity — the "
        "step arrays carry no flat metadata to backtrack from"
    ),
}


def _noop_infer(op, block):
    """No build-time inference — see NOOP_INFER_REASONS[op.type] for why
    this op's outputs are genuinely dynamic. The analyzer still infers
    through them via the op's abstract_eval hook."""
    return None


def _copy_meta(block, src_name, dst_name):
    """Copy shape/dtype/lod metadata from one var to another (the identity
    build-time inference shared by print/shrink/reorder)."""
    from .registry import EMPTY_VAR_NAME

    if EMPTY_VAR_NAME in (src_name, dst_name) or src_name == dst_name:
        return
    if not (block.has_var_recursive(src_name) and block.has_var_recursive(dst_name)):
        return
    src = block._var_recursive(src_name)
    dst = block._var_recursive(dst_name)
    if src.shape is not None:
        dst.shape = tuple(src.shape)
    if src.dtype is not None:
        dst.dtype = src.dtype
    dst.lod_level = getattr(src, "lod_level", 0)


def _set_meta(block, name, shape, dtype):
    from .registry import EMPTY_VAR_NAME

    if name == EMPTY_VAR_NAME or not block.has_var_recursive(name):
        return
    v = block._var_recursive(name)
    if shape is not None:
        v.shape = tuple(shape)
    if dtype is not None:
        v.dtype = dtype


def _vf(**kw):
    # lazy: analysis imports ops.registry; hooks only run under the analyzer
    from ..analysis.dataflow import VarFact

    return VarFact(**kw)


def _known(f):
    return f is not None and f.kind == "tensor" and f.shape is not None


def _facts_conflict(a, b):
    """True when two facts PROVABLY disagree (kind, dtype, rank, or a pair
    of fully-static dims). Symbolic/unknown dims prove nothing."""
    if a is None or b is None:
        return False
    if a.kind == "opaque" or b.kind == "opaque":
        return False
    if a.kind != b.kind:
        return True
    if a.dtype is not None and b.dtype is not None and a.dtype != b.dtype:
        return True
    if a.shape is None or b.shape is None:
        return False
    if len(a.shape) != len(b.shape):
        return True
    for da, db in zip(a.shape, b.shape):
        if isinstance(da, int) and isinstance(db, int) and da != db:
            return True
    return False


def _scalar_bool(x):
    return jnp.reshape(x, ()).astype(bool)


def _mask_rows(active, new, old):
    """Select per-batch-row between new and old ([B, ...] tensors)."""
    a = active.reshape((-1,) + (1,) * (new.ndim - 1))
    return jnp.where(a, new, old)


def _while_infer(op, block):
    """`while` outputs ALIAS their carried input names (the same variables,
    metadata already propagated by the sub-block's per-op inference as it
    was built), so there are no shapes to write — build-time inference
    instead validates the structural contract the lowering assumes, the
    checks while_op.cc's InferShape did by hand."""
    attrs = op.attrs
    carried = list(attrs.get("carried_names", ()))
    x_names = set(attrs.get("x_names", ()))
    cond = attrs.get("cond_name")
    missing = [n for n in carried if n not in x_names]
    if missing:
        raise ValueError(
            "while op: carried names %s are not in x_names — the lowering "
            "env would have no initial value for them" % missing
        )
    if cond not in carried:
        raise ValueError(
            "while op: condition %r is not loop-carried — the loop could "
            "never terminate" % cond
        )


def _while_abstract(actx, op, ins):
    """Sub-block-aware transfer: interpret the body once with the entry
    facts and require every loop-carried value to be shape/dtype-stable
    (the lax.while_loop/scan carry contract). Out facts are the entry
    facts — the fixed point of a stable carry."""
    attrs = op.attrs
    carried = list(attrs.get("carried_names", ()))
    x_names = list(attrs.get("x_names", ()))
    env = dict(zip(x_names, ins.get("X", ())))
    entry = {n: env.get(n) for n in carried}
    body = dict(env)
    actx.analyze_block(attrs["sub_block"], body)
    outs = []
    for n in carried:
        a, b = entry.get(n), body.get(n)
        if _facts_conflict(a, b):
            actx.problem(
                "loop-carried %r is not shape/dtype-stable across "
                "iterations: entry %r vs body exit %r" % (n, a, b)
            )
        outs.append(a if _known(a) or b is None else b)
    return {"Out": outs}


@register("while", infer_shape=_while_infer, abstract_eval=_while_abstract)
def _while(ctx, ins, attrs):
    sub = attrs["sub_block"]
    carried = list(attrs["carried_names"])
    cond_name = attrs["cond_name"]
    x_names = list(attrs["x_names"])
    max_iters = attrs.get("maximum_iterations") or 0

    env = dict(zip(x_names, ins["X"]))
    closure = {n: v for n, v in env.items() if n not in carried}
    init = tuple(env[n] for n in carried)
    cond_idx = carried.index(cond_name)

    def run_body(key, vals):
        e = dict(closure)
        e.update(zip(carried, vals))
        c = LowerCtx(key, is_test=ctx.is_test, mesh=ctx.mesh)
        lower_ops(c, sub.ops, e)
        return c.key, tuple(e[n] for n in carried)

    if max_iters <= 0:
        # open-ended loop: XLA While. Not reverse-differentiable — training
        # loops should set maximum_iterations or use recurrent/StaticRNN.
        def cond_fn(state):
            return _scalar_bool(state[1][cond_idx])

        def body_fn(state):
            return run_body(*state)

        key, final = lax.while_loop(cond_fn, body_fn, (ctx.next_rng(), init))
    else:
        # bounded loop: masked scan (differentiable). Iterations past the
        # condition going false keep the old carry.
        def scan_body(state, _):
            key, vals = state
            active = _scalar_bool(vals[cond_idx])
            nkey, nvals = run_body(key, vals)
            # tree_map: carries may be tensor-array (buffer, size) tuples
            sel = tuple(
                jax.tree_util.tree_map(
                    lambda a, b: jnp.where(active, a, b), nv, v
                )
                for nv, v in zip(nvals, vals)
            )
            return (nkey, sel), None

        (key, final), _ = lax.scan(
            scan_body, (ctx.next_rng(), init), None, length=int(max_iters)
        )
    ctx.key = key
    return {"Out": list(final)}


def _cond_infer(op, block):
    """conditional_block outputs alias the written parent vars (metadata
    already known); validate the contract instead: every written name must
    also ride x_names, because the false branch rebinds its PRIOR value."""
    attrs = op.attrs
    written = list(attrs.get("written_names", ()))
    x_names = set(attrs.get("x_names", ()))
    missing = [n for n in written if n not in x_names]
    if missing:
        raise ValueError(
            "conditional_block op: written names %s are not in x_names — "
            "the false branch would have no prior value to rebind" % missing
        )


def _cond_abstract(actx, op, ins):
    """Interpret the branch body with the entry facts; both branches of the
    lax.cond must agree, so a provable shape change in the taken branch is
    a problem. Out dtype follows the PRIOR value (the lowering casts the
    branch result to it)."""
    attrs = op.attrs
    written = list(attrs.get("written_names", ()))
    x_names = list(attrs.get("x_names", ()))
    env = dict(zip(x_names, ins.get("X", ())))
    prior = {n: env.get(n) for n in written}
    body = dict(env)
    actx.analyze_block(attrs["sub_block"], body)
    outs = []
    for n in written:
        p, b = prior.get(n), body.get(n)
        # dtype divergence is fine — the lowering casts the branch result
        # to the prior dtype; only a provable SHAPE/kind conflict breaks
        # the lax.cond branch agreement
        if p is not None and b is not None and _facts_conflict(
            _vf(shape=p.shape, kind=p.kind), _vf(shape=b.shape, kind=b.kind)
        ):
            actx.problem(
                "conditional_block writes %r with a shape differing from "
                "its prior value: %r vs %r — lax.cond branches would "
                "disagree" % (n, p, b)
            )
        outs.append(p if _known(p) or b is None else b)
    return {"Out": outs}


@register(
    "conditional_block", infer_shape=_cond_infer, abstract_eval=_cond_abstract
)
def _conditional_block(ctx, ins, attrs):
    sub = attrs["sub_block"]
    written = list(attrs["written_names"])
    x_names = list(attrs["x_names"])

    env = dict(zip(x_names, ins["X"]))
    conds = [_scalar_bool(c) for c in ins["Cond"]]
    pred = conds[0]
    for c in conds[1:]:
        pred = jnp.logical_and(pred, c)

    prior = tuple(env[n] for n in written)

    def true_fn(key):
        e = dict(env)
        c = LowerCtx(key, is_test=ctx.is_test, mesh=ctx.mesh)
        lower_ops(c, sub.ops, e)
        # tree_map: written vars may be tensor-array (buffer, size) tuples;
        # cast each leaf to the prior leaf's dtype so both branches match
        return c.key, tuple(
            jax.tree_util.tree_map(lambda v, pl: v.astype(pl.dtype), e[n], p)
            for n, p in zip(written, prior)
        )

    def false_fn(key):
        return key, prior

    key, outs = lax.cond(pred, true_fn, false_fn, ctx.next_rng())
    ctx.key = key
    return {"Out": list(outs)}


def _recurrent_infer(op, block):
    """Real sub-block-aware build-time inference: recompute the stacked
    output shapes from the sub-block's per-step out vars plus the time
    extent of the stacked X input, and the FinalState metadata from Boot —
    previously hand-computed only in layers/control_flow._RNNBase._complete,
    now re-derived here so raw append_op callers get the same metadata."""
    attrs = op.attrs
    sub = attrs.get("sub_block")
    if sub is None:
        return
    tm = bool(attrs.get("time_major", False))
    taxis = 0 if tm else 1
    t = None
    xs = op.inputs.get("X", ())
    if xs and block.has_var_recursive(xs[0]):
        v = block._var_recursive(xs[0])
        if v.shape is not None and len(v.shape) > taxis:
            t = v.shape[taxis]
    if t is None:
        t = int(attrs.get("length", 0)) or -1
    for step_name, out_name in zip(
        attrs.get("out_names", ()), op.outputs.get("Out", ())
    ):
        if not sub.has_var_recursive(step_name):
            continue
        o = sub._var_recursive(step_name)
        if o.shape is None:
            continue
        s = list(o.shape)
        stacked = [t] + s if tm else s[:1] + [t] + s[1:]
        _set_meta(block, out_name, stacked, o.dtype)
    for boot_name, final_name in zip(
        op.inputs.get("Boot", ()), op.outputs.get("FinalState", ())
    ):
        _copy_meta(block, boot_name, final_name)


def _recurrent_abstract(actx, op, ins):
    """Transfer for the scan: per-step facts (time axis dropped from the
    stacked X) flow through one interpretation of the sub-block; outputs
    stack the time axis back on, and FinalState must be shape-stable
    against Boot (the scan carry contract)."""
    attrs = op.attrs
    tm = bool(attrs.get("time_major", False))
    taxis = 0 if tm else 1
    xs = ins.get("X", ())
    boot = ins.get("Boot", ())
    env = dict(zip(attrs.get("closure_names", ()), ins.get("C", ())))
    env.update(zip(attrs.get("pre_state_names", ()), boot))
    t = None
    for n, f in zip(attrs.get("x_names", ()), xs):
        if _known(f) and len(f.shape) > taxis:
            if t is None:
                t = f.shape[taxis]
            env[n] = _vf(
                shape=f.shape[:taxis] + f.shape[taxis + 1:], dtype=f.dtype
            )
        else:
            env[n] = _vf(kind="opaque")
    if t is None:
        t = int(attrs.get("length", 0)) or None
    actx.analyze_block(attrs["sub_block"], env)
    outs = []
    for n in attrs.get("out_names", ()):
        f = env.get(n)
        if _known(f) and t is not None and (tm or len(f.shape) >= 1):
            stacked = (
                (t,) + f.shape if tm else f.shape[:1] + (t,) + f.shape[1:]
            )
            outs.append(_vf(shape=stacked, dtype=f.dtype))
        else:
            outs.append(actx.opaque())
    finals = []
    for n, b in zip(attrs.get("new_state_names", ()), boot):
        f = env.get(n)
        if _facts_conflict(f, b):
            actx.problem(
                "recurrent state %r is not shape-stable across steps: boot "
                "%r vs step exit %r" % (n, b, f)
            )
        finals.append(b if _known(b) or f is None else f)
    return {"Out": outs, "FinalState": finals}


@register(
    "recurrent", infer_shape=_recurrent_infer, abstract_eval=_recurrent_abstract
)
def _recurrent(ctx, ins, attrs):
    """scan over time. Inputs: X stacked sequence inputs, Boot initial states,
    C closure (params etc.), SeqLen optional per-row lengths. See layer classes
    StaticRNN / DynamicRNN (layers/control_flow.py)."""
    sub = attrs["sub_block"]
    x_names = list(attrs["x_names"])  # per-step names inside the block
    pre_names = list(attrs["pre_state_names"])
    new_names = list(attrs["new_state_names"])
    out_names = list(attrs["out_names"])
    closure_names = list(attrs.get("closure_names", []))
    time_major = bool(attrs.get("time_major", False))
    reverse = bool(attrs.get("reverse", False))

    seq = [v if time_major else jnp.swapaxes(v, 0, 1) for v in ins.get("X", [])]
    boot = tuple(ins.get("Boot", []))
    closure = dict(zip(closure_names, ins.get("C", [])))
    seqlen = ins.get("SeqLen", [None])[0]
    if seqlen is not None:
        seqlen = seqlen.reshape(-1).astype(jnp.int32)
    T = seq[0].shape[0] if seq else int(attrs["length"])
    tidx = jnp.arange(T, dtype=jnp.int32)

    def step(carry, scanned):
        key, states = carry
        t, xt = scanned
        e = dict(closure)
        e.update(zip(pre_names, states))
        e.update(zip(x_names, xt))
        c = LowerCtx(key, is_test=ctx.is_test, mesh=ctx.mesh)
        lower_ops(c, sub.ops, e)
        new_states = tuple(
            e[n].astype(s.dtype).reshape(s.shape)
            for n, s in zip(new_names, states)
        )
        outs = tuple(e[n] for n in out_names)
        if seqlen is not None:
            active = t < seqlen  # (B,)
            new_states = tuple(
                _mask_rows(active, ns, s) for ns, s in zip(new_states, states)
            )
            outs = tuple(
                _mask_rows(active, o, jnp.zeros_like(o)) for o in outs
            )
        return (c.key, new_states), outs

    (key, final), ys = lax.scan(
        step, (ctx.next_rng(), boot), (tidx, tuple(seq)), reverse=reverse
    )
    ctx.key = key
    ys = [y if time_major else jnp.swapaxes(y, 0, 1) for y in ys]
    return {"Out": list(ys), "FinalState": list(final)}


# ---------------------------------------------------------------------------
# tensor arrays: (buffer[cap, ...], size) pairs
# ---------------------------------------------------------------------------


def _canon_dtype(dtype):
    from ..framework import convert_np_dtype

    try:
        return convert_np_dtype(dtype)
    except ValueError:
        return None


def _create_array_abstract(actx, op, ins):
    shape = op.attrs.get("shape")
    if not shape:
        return {"Out": [_vf(kind="array")]}  # buffer shape set by first write
    return {
        "Out": [
            _vf(
                shape=tuple(shape),
                dtype=_canon_dtype(op.attrs.get("dtype", "float32")),
                kind="array",
            )
        ]
    }


def _write_to_array_abstract(actx, op, ins):
    """Mirror the lowering's capacity bookkeeping on buffer-shape facts."""
    x = ins["X"][0]
    arr = (ins.get("Array") or [None])[0]
    if arr is None or arr.kind != "array" or arr.shape is None:
        if not _known(x):
            return {"Out": [_vf(kind="array")]}
        cap = int(op.attrs.get("init_cap", 1))
        return {"Out": [_vf(shape=(cap,) + x.shape, dtype=x.dtype, kind="array")]}
    grow = int(op.attrs.get("grow_slots", 0))
    cap = arr.shape[0]
    if grow and isinstance(cap, int):
        cap = cap + grow
    return {"Out": [_vf(shape=(cap,) + arr.shape[1:], dtype=arr.dtype, kind="array")]}


def _read_from_array_abstract(actx, op, ins):
    arr = ins["X"][0]
    if arr is None or arr.kind != "array" or arr.shape is None:
        return {"Out": [actx.opaque()]}
    return {"Out": [_vf(shape=arr.shape[1:], dtype=arr.dtype)]}


def _array_length_abstract(actx, op, ins):
    return {"Out": [_vf(shape=(1,), dtype="int64")]}


def _lod_tensor_to_array_abstract(actx, op, ins):
    x = ins["X"][0]
    if not _known(x) or len(x.shape) < 2:
        return {"Out": [_vf(kind="array")]}
    buf = (x.shape[1], x.shape[0]) + x.shape[2:]
    return {"Out": [_vf(shape=buf, dtype=x.dtype, kind="array")]}


def _array_to_lod_tensor_abstract(actx, op, ins):
    arr = ins["X"][0]
    if arr is None or arr.kind != "array" or arr.shape is None or len(arr.shape) < 2:
        return {"Out": [actx.opaque()]}
    out = (arr.shape[1], arr.shape[0]) + arr.shape[2:]
    return {"Out": [_vf(shape=out, dtype=arr.dtype)]}


@register(
    "create_array", infer_shape=_noop_infer, abstract_eval=_create_array_abstract
)
def _create_array(ctx, ins, attrs):
    shape = attrs.get("shape")
    if not shape:
        # capacity-less array: first write_to_array creates the buffer
        return {"Out": [None]}
    dtype = jnp.dtype(attrs.get("dtype", "float32"))
    buf = jnp.zeros(tuple(shape), dtype)
    return {"Out": [(buf, jnp.asarray(0, jnp.int32))]}


@register(
    "write_to_array",
    infer_shape=_noop_infer,
    abstract_eval=_write_to_array_abstract,
)
def _write_to_array(ctx, ins, attrs):
    """Growable writes carry static capacity bookkeeping from the layer
    (layers/control_flow.py array_write): ``init_cap`` sizes the buffer of a
    first write, ``grow_slots`` appends exactly enough rows that the write
    index (statically known at build time) is in range — arbitrary-index
    writes land correctly, like the reference write_to_array."""
    (x,) = ins["X"]
    (i,) = ins["I"]
    i = jnp.reshape(i, ()).astype(jnp.int32)
    arr = ins.get("Array", [None])[0]
    if arr is None:
        cap = int(attrs.get("init_cap", 1))
        buf = jnp.zeros((cap,) + x.shape, x.dtype)
        start = (i,) + (0,) * x.ndim
        buf = lax.dynamic_update_slice(buf, x[None], start)
        size = jnp.maximum(i + 1, 1)
    else:
        buf, size = arr
        grow = int(attrs.get("grow_slots", 0))
        if grow:
            pad = jnp.zeros((grow,) + x.shape, buf.dtype)
            buf = jnp.concatenate([buf, pad], axis=0)
        start = (i,) + (0,) * x.ndim
        buf = lax.dynamic_update_slice(buf, x[None].astype(buf.dtype), start)
        size = jnp.maximum(size, i + 1)
    return {"Out": [(buf, size)]}


@register(
    "read_from_array",
    infer_shape=_noop_infer,
    abstract_eval=_read_from_array_abstract,
)
def _read_from_array(ctx, ins, attrs):
    (arr,) = ins["X"]
    (i,) = ins["I"]
    buf, _ = arr
    i = jnp.reshape(i, ()).astype(jnp.int32)
    return {"Out": [lax.dynamic_index_in_dim(buf, i, 0, keepdims=False)]}


def _scalar_i64_infer(op, block):
    for n in op.outputs.get("Out", ()):
        _set_meta(block, n, (1,), "int64")


@register(
    "lod_array_length",
    no_grad=True,
    infer_shape=_scalar_i64_infer,
    abstract_eval=_array_length_abstract,
)
def _array_length(ctx, ins, attrs):
    (arr,) = ins["X"]
    _, size = arr
    return {"Out": [jnp.reshape(size, (1,)).astype(jnp.int64)]}


@register(
    "lod_tensor_to_array",
    infer_shape=_noop_infer,
    abstract_eval=_lod_tensor_to_array_abstract,
)
def _lod_tensor_to_array(ctx, ins, attrs):
    """Padded-dense [B, T, ...] -> time-major array buffer [T, B, ...] with
    size=T (reference lod_tensor_to_array_op.cc scattered per-rank-table rows;
    masking replaces the shrinking-batch reorder, SURVEY.md §5.7)."""
    (x,) = ins["X"]
    buf = jnp.swapaxes(x, 0, 1)
    return {"Out": [(buf, jnp.asarray(buf.shape[0], jnp.int32))]}


@register(
    "array_to_lod_tensor",
    infer_shape=_noop_infer,
    abstract_eval=_array_to_lod_tensor_abstract,
)
def _array_to_lod_tensor(ctx, ins, attrs):
    (arr,) = ins["X"]
    buf, _ = arr
    return {"Out": [jnp.swapaxes(buf, 0, 1)]}


def _identity_infer(op, block):
    """Build-time metadata copy for ops whose output is shaped exactly like
    their X input (identity / row-permutation lowerings)."""
    xs = op.inputs.get("X", ())
    outs = op.outputs.get("Out", ())
    if xs and outs:
        _copy_meta(block, xs[0], outs[0])


@register("shrink_rnn_memory", infer_shape=_identity_infer)
def _shrink_rnn_memory(ctx, ins, attrs):
    # reference shrink_memory drops finished rows from the batch; the padded
    # representation keeps them and masks instead (recurrent op) — identity.
    (x,) = ins["X"]
    return {"Out": [x]}


@register("max_sequence_len", no_grad=True, infer_shape=_scalar_i64_infer)
def _max_sequence_len(ctx, ins, attrs):
    (seqlen,) = ins["X"]
    return {"Out": [jnp.max(seqlen.reshape(-1)).reshape(1).astype(jnp.int64)]}


@register("reorder_lod_tensor_by_rank", infer_shape=_identity_infer)
def _reorder_by_rank(ctx, ins, attrs):
    (x,) = ins["X"]
    (rank,) = ins["RankTable"]
    return {"Out": [jnp.take(x, rank.reshape(-1).astype(jnp.int32), axis=0)]}


def _lod_rank_table_infer(op, block):
    xs = op.inputs.get("X", ())
    outs = op.outputs.get("Out", ())
    if not (xs and outs):
        return
    numel = -1
    if block.has_var_recursive(xs[0]):
        v = block._var_recursive(xs[0])
        if v.shape is not None and all(
            isinstance(d, int) and d >= 0 for d in v.shape
        ):
            numel = 1
            for d in v.shape:
                numel *= d
    _set_meta(block, outs[0], (numel,), "int64")


@register("lod_rank_table", no_grad=True, infer_shape=_lod_rank_table_infer)
def _lod_rank_table(ctx, ins, attrs):
    """Row indices sorted by sequence length, descending (reference
    lod_rank_table.h). Input is the SeqLen companion vector."""
    (seqlen,) = ins["X"]
    order = jnp.argsort(-seqlen.reshape(-1))
    return {"Out": [order.astype(jnp.int64)]}


def _print_abstract(actx, op, ins):
    return {"Out": [ins["X"][0]]}  # value passthrough; side effect only


@register(
    "print",
    no_grad=False,
    infer_shape=_identity_infer,
    abstract_eval=_print_abstract,
)
def _print(ctx, ins, attrs):
    (x,) = ins["X"]
    msg = attrs.get("message", "")
    first_n = int(attrs.get("summarize", 20) or 20)
    # reference print_op: summarize=-1 means print every element
    flat = x.reshape(-1) if first_n < 0 else x.reshape(-1)[: max(first_n, 1)]
    fmt = "%s shape=%s mean={m} first={f}" % (msg, tuple(x.shape))
    jax.debug.print(fmt, m=jnp.mean(x.astype(jnp.float32)), f=flat)
    return {"Out": [x]}


def _parallel_do_infer(op, block):
    sub = op.attrs.get("sub_block")
    if sub is None:
        return
    for step_name, out_name in zip(
        op.attrs.get("out_names", ()), op.outputs.get("Out", ())
    ):
        if sub.has_var_recursive(step_name):
            src = sub._var_recursive(step_name)
            _set_meta(block, out_name, src.shape, src.dtype)


def _parallel_do_abstract(actx, op, ins):
    attrs = op.attrs
    env = dict(zip(attrs.get("x_names", ()), ins.get("X", ())))
    actx.analyze_block(attrs["sub_block"], env)
    return {
        "Out": [
            env.get(n) or actx.opaque() for n in attrs.get("out_names", ())
        ]
    }


@register(
    "parallel_do",
    infer_shape=_parallel_do_infer,
    abstract_eval=_parallel_do_abstract,
)
def _parallel_do(ctx, ins, attrs):
    """Deprecated intra-graph data-parallel islands (reference
    controlflow/parallel_do_op.cc: split the batch across places, run the
    sub-block per device, gather). Under SPMD compilation the whole program
    is already sharded over the mesh (parallel_executor.py), so the correct
    TPU lowering is: run the sub-block once on the full batch — XLA's GSPMD
    partitioner does the splitting the reference did manually."""
    sub = attrs["sub_block"]
    x_names = list(attrs.get("x_names", []))
    out_names = list(attrs.get("out_names", []))
    env = dict(zip(x_names, ins.get("X", [])))
    c = LowerCtx(ctx.next_rng(), is_test=ctx.is_test, mesh=ctx.mesh)
    lower_ops(c, sub.ops, env)
    return {"Out": [env[n] for n in out_names]}
