"""Control-flow ops: while / conditional_block / recurrent (scan) / tensor
arrays / print.

Reference analog: paddle/fluid/operators/controlflow/ — while_op.cc:36 runs its
sub-block via a nested Executor once per iteration, saving per-step scopes
(StepScopes) for the hand-written while_grad (while_op.cc:112);
conditional_block_op.cc likewise nests an Executor. The TPU-first redesign
lowers the sub-block *into the same XLA computation*:

- ``while``   -> jax.lax.while_loop over a carry of the loop-written outer vars
  (with ``maximum_iterations`` set, a masked lax.scan instead, which XLA can
  reverse-differentiate — replacing the reference's StepScopes grad machinery
  with jax.vjp through scan).
- ``conditional_block`` -> jax.lax.cond; the false branch returns the prior
  values of the written vars (the reference leaves them untouched in the scope;
  rebinding the old value is the functional equivalent).
- ``recurrent`` -> jax.lax.scan; this is the engine under StaticRNN/DynamicRNN
  (reference recurrent_op.cc + layers/control_flow.py:429,1546). Variable-length
  sequences use a SeqLen companion and per-row masking instead of the
  reference's shrinking-batch LoD reordering (SURVEY.md §5.7).
- tensor arrays (write_to_array / read_from_array, lod_tensor_to_array /
  array_to_lod_tensor, reference controlflow/tensor_array_read_write_op.cc,
  lod_tensor_to_array_op.cc) are (buffer[T, ...], size) pairs — a fixed-
  capacity time-major buffer plus a logical length, static shapes for XLA.

Carries in while/scan must be fixed-shape: arrays written inside a loop must be
pre-allocated (create_array(shape=...) or lod_tensor_to_array); outside loops
writes grow the buffer by concatenation (each call site is its own trace).
"""

import jax
import jax.numpy as jnp
from jax import lax

from .registry import LowerCtx, lower_ops, register


def _noop_infer(op, block):
    """Output shapes are set at layer-build time (layers/control_flow.py);
    array values are (buffer, size) tuples jax.eval_shape cannot abstract
    from flat var metadata, and while/cond outputs alias their input names
    whose shapes are already known."""
    return None


def _scalar_bool(x):
    return jnp.reshape(x, ()).astype(bool)


def _mask_rows(active, new, old):
    """Select per-batch-row between new and old ([B, ...] tensors)."""
    a = active.reshape((-1,) + (1,) * (new.ndim - 1))
    return jnp.where(a, new, old)


@register("while", infer_shape=_noop_infer)
def _while(ctx, ins, attrs):
    sub = attrs["sub_block"]
    carried = list(attrs["carried_names"])
    cond_name = attrs["cond_name"]
    x_names = list(attrs["x_names"])
    max_iters = attrs.get("maximum_iterations") or 0

    env = dict(zip(x_names, ins["X"]))
    closure = {n: v for n, v in env.items() if n not in carried}
    init = tuple(env[n] for n in carried)
    cond_idx = carried.index(cond_name)

    def run_body(key, vals):
        e = dict(closure)
        e.update(zip(carried, vals))
        c = LowerCtx(key, is_test=ctx.is_test, mesh=ctx.mesh)
        lower_ops(c, sub.ops, e)
        return c.key, tuple(e[n] for n in carried)

    if max_iters <= 0:
        # open-ended loop: XLA While. Not reverse-differentiable — training
        # loops should set maximum_iterations or use recurrent/StaticRNN.
        def cond_fn(state):
            return _scalar_bool(state[1][cond_idx])

        def body_fn(state):
            return run_body(*state)

        key, final = lax.while_loop(cond_fn, body_fn, (ctx.next_rng(), init))
    else:
        # bounded loop: masked scan (differentiable). Iterations past the
        # condition going false keep the old carry.
        def scan_body(state, _):
            key, vals = state
            active = _scalar_bool(vals[cond_idx])
            nkey, nvals = run_body(key, vals)
            # tree_map: carries may be tensor-array (buffer, size) tuples
            sel = tuple(
                jax.tree_util.tree_map(
                    lambda a, b: jnp.where(active, a, b), nv, v
                )
                for nv, v in zip(nvals, vals)
            )
            return (nkey, sel), None

        (key, final), _ = lax.scan(
            scan_body, (ctx.next_rng(), init), None, length=int(max_iters)
        )
    ctx.key = key
    return {"Out": list(final)}


@register("conditional_block", infer_shape=_noop_infer)
def _conditional_block(ctx, ins, attrs):
    sub = attrs["sub_block"]
    written = list(attrs["written_names"])
    x_names = list(attrs["x_names"])

    env = dict(zip(x_names, ins["X"]))
    conds = [_scalar_bool(c) for c in ins["Cond"]]
    pred = conds[0]
    for c in conds[1:]:
        pred = jnp.logical_and(pred, c)

    prior = tuple(env[n] for n in written)

    def true_fn(key):
        e = dict(env)
        c = LowerCtx(key, is_test=ctx.is_test, mesh=ctx.mesh)
        lower_ops(c, sub.ops, e)
        # tree_map: written vars may be tensor-array (buffer, size) tuples;
        # cast each leaf to the prior leaf's dtype so both branches match
        return c.key, tuple(
            jax.tree_util.tree_map(lambda v, pl: v.astype(pl.dtype), e[n], p)
            for n, p in zip(written, prior)
        )

    def false_fn(key):
        return key, prior

    key, outs = lax.cond(pred, true_fn, false_fn, ctx.next_rng())
    ctx.key = key
    return {"Out": list(outs)}


@register("recurrent", infer_shape=_noop_infer)
def _recurrent(ctx, ins, attrs):
    """scan over time. Inputs: X stacked sequence inputs, Boot initial states,
    C closure (params etc.), SeqLen optional per-row lengths. See layer classes
    StaticRNN / DynamicRNN (layers/control_flow.py)."""
    sub = attrs["sub_block"]
    x_names = list(attrs["x_names"])  # per-step names inside the block
    pre_names = list(attrs["pre_state_names"])
    new_names = list(attrs["new_state_names"])
    out_names = list(attrs["out_names"])
    closure_names = list(attrs.get("closure_names", []))
    time_major = bool(attrs.get("time_major", False))
    reverse = bool(attrs.get("reverse", False))

    seq = [v if time_major else jnp.swapaxes(v, 0, 1) for v in ins.get("X", [])]
    boot = tuple(ins.get("Boot", []))
    closure = dict(zip(closure_names, ins.get("C", [])))
    seqlen = ins.get("SeqLen", [None])[0]
    if seqlen is not None:
        seqlen = seqlen.reshape(-1).astype(jnp.int32)
    T = seq[0].shape[0] if seq else int(attrs["length"])
    tidx = jnp.arange(T, dtype=jnp.int32)

    def step(carry, scanned):
        key, states = carry
        t, xt = scanned
        e = dict(closure)
        e.update(zip(pre_names, states))
        e.update(zip(x_names, xt))
        c = LowerCtx(key, is_test=ctx.is_test, mesh=ctx.mesh)
        lower_ops(c, sub.ops, e)
        new_states = tuple(
            e[n].astype(s.dtype).reshape(s.shape)
            for n, s in zip(new_names, states)
        )
        outs = tuple(e[n] for n in out_names)
        if seqlen is not None:
            active = t < seqlen  # (B,)
            new_states = tuple(
                _mask_rows(active, ns, s) for ns, s in zip(new_states, states)
            )
            outs = tuple(
                _mask_rows(active, o, jnp.zeros_like(o)) for o in outs
            )
        return (c.key, new_states), outs

    (key, final), ys = lax.scan(
        step, (ctx.next_rng(), boot), (tidx, tuple(seq)), reverse=reverse
    )
    ctx.key = key
    ys = [y if time_major else jnp.swapaxes(y, 0, 1) for y in ys]
    return {"Out": list(ys), "FinalState": list(final)}


# ---------------------------------------------------------------------------
# tensor arrays: (buffer[cap, ...], size) pairs
# ---------------------------------------------------------------------------


@register("create_array", infer_shape=_noop_infer)
def _create_array(ctx, ins, attrs):
    shape = attrs.get("shape")
    if not shape:
        # capacity-less array: first write_to_array creates the buffer
        return {"Out": [None]}
    dtype = jnp.dtype(attrs.get("dtype", "float32"))
    buf = jnp.zeros(tuple(shape), dtype)
    return {"Out": [(buf, jnp.asarray(0, jnp.int32))]}


@register("write_to_array", infer_shape=_noop_infer)
def _write_to_array(ctx, ins, attrs):
    """Growable writes carry static capacity bookkeeping from the layer
    (layers/control_flow.py array_write): ``init_cap`` sizes the buffer of a
    first write, ``grow_slots`` appends exactly enough rows that the write
    index (statically known at build time) is in range — arbitrary-index
    writes land correctly, like the reference write_to_array."""
    (x,) = ins["X"]
    (i,) = ins["I"]
    i = jnp.reshape(i, ()).astype(jnp.int32)
    arr = ins.get("Array", [None])[0]
    if arr is None:
        cap = int(attrs.get("init_cap", 1))
        buf = jnp.zeros((cap,) + x.shape, x.dtype)
        start = (i,) + (0,) * x.ndim
        buf = lax.dynamic_update_slice(buf, x[None], start)
        size = jnp.maximum(i + 1, 1)
    else:
        buf, size = arr
        grow = int(attrs.get("grow_slots", 0))
        if grow:
            pad = jnp.zeros((grow,) + x.shape, buf.dtype)
            buf = jnp.concatenate([buf, pad], axis=0)
        start = (i,) + (0,) * x.ndim
        buf = lax.dynamic_update_slice(buf, x[None].astype(buf.dtype), start)
        size = jnp.maximum(size, i + 1)
    return {"Out": [(buf, size)]}


@register("read_from_array", infer_shape=_noop_infer)
def _read_from_array(ctx, ins, attrs):
    (arr,) = ins["X"]
    (i,) = ins["I"]
    buf, _ = arr
    i = jnp.reshape(i, ()).astype(jnp.int32)
    return {"Out": [lax.dynamic_index_in_dim(buf, i, 0, keepdims=False)]}


@register("lod_array_length", no_grad=True, infer_shape=_noop_infer)
def _array_length(ctx, ins, attrs):
    (arr,) = ins["X"]
    _, size = arr
    return {"Out": [jnp.reshape(size, (1,)).astype(jnp.int64)]}


@register("lod_tensor_to_array", infer_shape=_noop_infer)
def _lod_tensor_to_array(ctx, ins, attrs):
    """Padded-dense [B, T, ...] -> time-major array buffer [T, B, ...] with
    size=T (reference lod_tensor_to_array_op.cc scattered per-rank-table rows;
    masking replaces the shrinking-batch reorder, SURVEY.md §5.7)."""
    (x,) = ins["X"]
    buf = jnp.swapaxes(x, 0, 1)
    return {"Out": [(buf, jnp.asarray(buf.shape[0], jnp.int32))]}


@register("array_to_lod_tensor", infer_shape=_noop_infer)
def _array_to_lod_tensor(ctx, ins, attrs):
    (arr,) = ins["X"]
    buf, _ = arr
    return {"Out": [jnp.swapaxes(buf, 0, 1)]}


@register("shrink_rnn_memory", infer_shape=_noop_infer)
def _shrink_rnn_memory(ctx, ins, attrs):
    # reference shrink_memory drops finished rows from the batch; the padded
    # representation keeps them and masks instead (recurrent op) — identity.
    (x,) = ins["X"]
    return {"Out": [x]}


@register("max_sequence_len", no_grad=True, infer_shape=_noop_infer)
def _max_sequence_len(ctx, ins, attrs):
    (seqlen,) = ins["X"]
    return {"Out": [jnp.max(seqlen.reshape(-1)).reshape(1).astype(jnp.int64)]}


@register("reorder_lod_tensor_by_rank", infer_shape=_noop_infer)
def _reorder_by_rank(ctx, ins, attrs):
    (x,) = ins["X"]
    (rank,) = ins["RankTable"]
    return {"Out": [jnp.take(x, rank.reshape(-1).astype(jnp.int32), axis=0)]}


@register("lod_rank_table", no_grad=True, infer_shape=_noop_infer)
def _lod_rank_table(ctx, ins, attrs):
    """Row indices sorted by sequence length, descending (reference
    lod_rank_table.h). Input is the SeqLen companion vector."""
    (seqlen,) = ins["X"]
    order = jnp.argsort(-seqlen.reshape(-1))
    return {"Out": [order.astype(jnp.int64)]}


@register("print", no_grad=False, infer_shape=_noop_infer)
def _print(ctx, ins, attrs):
    (x,) = ins["X"]
    msg = attrs.get("message", "")
    first_n = int(attrs.get("summarize", 20) or 20)
    # reference print_op: summarize=-1 means print every element
    flat = x.reshape(-1) if first_n < 0 else x.reshape(-1)[: max(first_n, 1)]
    fmt = "%s shape=%s mean={m} first={f}" % (msg, tuple(x.shape))
    jax.debug.print(fmt, m=jnp.mean(x.astype(jnp.float32)), f=flat)
    return {"Out": [x]}


@register("parallel_do", infer_shape=_noop_infer)
def _parallel_do(ctx, ins, attrs):
    """Deprecated intra-graph data-parallel islands (reference
    controlflow/parallel_do_op.cc: split the batch across places, run the
    sub-block per device, gather). Under SPMD compilation the whole program
    is already sharded over the mesh (parallel_executor.py), so the correct
    TPU lowering is: run the sub-block once on the full batch — XLA's GSPMD
    partitioner does the splitting the reference did manually."""
    sub = attrs["sub_block"]
    x_names = list(attrs.get("x_names", []))
    out_names = list(attrs.get("out_names", []))
    env = dict(zip(x_names, ins.get("X", [])))
    c = LowerCtx(ctx.next_rng(), is_test=ctx.is_test, mesh=ctx.mesh)
    lower_ops(c, sub.ops, env)
    return {"Out": [env[n] for n in out_names]}
