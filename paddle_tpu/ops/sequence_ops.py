"""Sequence (ragged) ops and recurrent blocks.

Reference analog: paddle/fluid/operators/sequence_ops/ (46 files operating on
LoD tensors — packed ragged rows) and lstm_op.cc / gru_op.cc with
math/sequence2batch.h reordering. TPU-first redesign (SURVEY.md §5.7): ragged
batches are PADDED DENSE tensors (batch, time, ...) with an explicit `SeqLen`
(batch,) int32 companion — static shapes for XLA — and every op masks padding
explicitly. Recurrence is jax.lax.scan over the time axis (compiled XLA While)
instead of the reference's sequence2batch + per-step kernel launches; grads
come from the registry's generic vjp, which differentiates through scan.

Gate layouts match the reference kernels so checkpoints interchange:
dynamic_lstm gates are (c, i, f, o) [candidate, input, forget, output] —
operators/math/detail/lstm_cpu_kernel.h lays out value_in (candidate, tanh)
first, then value_ig/value_fg/value_og; dynamic_gru gates are (u, r, c) with
h = (1-u)*h_prev + u*c (gru_kernel.h gru_finalOutput).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import register


def _valid_mask(x, seqlen):
    """(b, t) boolean validity mask broadcastable against (b, t, ...)."""
    t = x.shape[1]
    return (jnp.arange(t)[None, :] < seqlen.reshape(-1, 1)).astype(x.dtype)


def _masked(x, seqlen):
    m = _valid_mask(x, seqlen)
    return x * m.reshape(m.shape + (1,) * (x.ndim - 2))


@register("sequence_pool")
def _sequence_pool(ctx, ins, attrs):
    (x,) = ins["X"]
    (seqlen,) = ins["SeqLen"]
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    lens = seqlen.reshape(-1).astype(jnp.int32)
    m = _valid_mask(x, lens)
    mexp = m.reshape(m.shape + (1,) * (x.ndim - 2))
    if ptype == "SUM":
        out = jnp.sum(x * mexp, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(x * mexp, axis=1) / jnp.maximum(lens, 1).reshape(-1, 1).astype(
            x.dtype
        )
    elif ptype == "SQRT":
        out = jnp.sum(x * mexp, axis=1) / jnp.sqrt(
            jnp.maximum(lens, 1).astype(x.dtype)
        ).reshape(-1, 1)
    elif ptype == "MAX":
        neg = jnp.asarray(jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating) else -(2**30), x.dtype)
        out = jnp.max(jnp.where(mexp > 0, x, neg), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(lens - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape(-1, 1, *([1] * (x.ndim - 2))), axis=1
        ).squeeze(1)
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError("unknown pooltype %r" % ptype)
    return {"Out": [out]}


@register("sequence_softmax")
def _sequence_softmax(ctx, ins, attrs):
    (x,) = ins["X"]
    (seqlen,) = ins["SeqLen"]
    lens = seqlen.reshape(-1).astype(jnp.int32)
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    v = x.reshape(x.shape[:2]) if squeeze else x
    m = _valid_mask(v, lens)
    neg = jnp.asarray(-1e9, v.dtype)
    logits = jnp.where(m > 0, v, neg)
    sm = jax.nn.softmax(logits, axis=1) * m
    sm = sm / jnp.maximum(jnp.sum(sm, axis=1, keepdims=True), 1e-9)
    out = sm.reshape(x.shape) if squeeze else sm
    return {"Out": [out]}


@register("sequence_conv")
def _sequence_conv(ctx, ins, attrs):
    """Context-window projection over time (reference
    sequence_ops/sequence_conv_op.cc + math/context_project.h): for each
    position, concat context_length timesteps starting at context_start and
    project with Filter (ctx_len*d_in, d_out). Zero padding outside sequence
    bounds, matching the reference's trainable-padding-disabled mode."""
    (x,) = ins["X"]
    (w,) = ins["Filter"]
    (seqlen,) = ins["SeqLen"]
    ctx_len = int(attrs.get("contextLength", attrs.get("context_length", 3)))
    ctx_start = int(attrs.get("contextStart", attrs.get("context_start", -((ctx_len - 1) // 2))))
    lens = seqlen.reshape(-1).astype(jnp.int32)
    xm = _masked(x, lens)
    b, t, d = xm.shape
    cols = []
    for k in range(ctx_len):
        off = ctx_start + k
        shifted = jnp.roll(xm, -off, axis=1)
        idx = jnp.arange(t) + off
        ok = ((idx >= 0) & (idx < t)).astype(x.dtype).reshape(1, t, 1)
        cols.append(shifted * ok)
    ctx_mat = jnp.concatenate(cols, axis=-1)  # (b, t, ctx_len*d)
    out = jnp.einsum("btd,do->bto", ctx_mat, w)
    out = _masked(out, lens)
    return {"Out": [out]}


@register("sequence_reverse")
def _sequence_reverse(ctx, ins, attrs):
    (x,) = ins["X"]
    (seqlen,) = ins["SeqLen"]
    lens = seqlen.reshape(-1).astype(jnp.int32)
    t = x.shape[1]
    # position i maps to len-1-i within the valid prefix; padding stays put
    pos = jnp.arange(t)[None, :]
    src = jnp.where(pos < lens[:, None], lens[:, None] - 1 - pos, pos)
    out = jnp.take_along_axis(x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)
    return {"Y": [out]}


@register("sequence_expand")
def _sequence_expand(ctx, ins, attrs):
    """Padded-dense analog of sequence_expand (reference
    sequence_ops/sequence_expand_op.cc): tile each row of X along a new/existing
    time axis to Y's time length."""
    (x,) = ins["X"]
    (y,) = ins["Y"]
    if x.ndim == y.ndim - 1:
        out = jnp.broadcast_to(x[:, None], (x.shape[0], y.shape[1]) + x.shape[1:])
    else:
        out = jnp.broadcast_to(x, y.shape[:2] + x.shape[2:])
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# recurrent blocks
# ---------------------------------------------------------------------------


def _scan_time(step, carry, xs, reverse=False):
    carry, ys = lax.scan(step, carry, xs, reverse=reverse)
    return carry, ys


@register("dynamic_lstm")
def _dynamic_lstm(ctx, ins, attrs):
    """LSTM over padded (b,t,4h) gate pre-activations (reference lstm_op.cc;
    input already projected by an fc, as in fluid's dynamic_lstm API).
    Peepholes supported (use_peepholes attr, bias then holds 7h)."""
    (x,) = ins["Input"]
    (w,) = ins["Weight"]  # (h, 4h) recurrent weights
    (seqlen,) = ins["SeqLen"]
    bias = ins["Bias"][0] if "Bias" in ins else None
    use_peepholes = bool(attrs.get("use_peepholes", True))
    is_reverse = bool(attrs.get("is_reverse", False))
    b, t, h4 = x.shape
    h = h4 // 4
    lens = seqlen.reshape(-1).astype(jnp.int32)

    gate_bias = None
    w_ic = w_fc = w_oc = None
    if bias is not None:
        flat = bias.reshape(-1)
        gate_bias = flat[: 4 * h]
        if use_peepholes and flat.shape[0] >= 7 * h:
            w_ic = flat[4 * h : 5 * h]
            w_fc = flat[5 * h : 6 * h]
            w_oc = flat[6 * h : 7 * h]

    xs = jnp.moveaxis(x, 1, 0)  # (t, b, 4h)
    tidx = jnp.arange(t)

    def step(carry, inp):
        h_prev, c_prev = carry
        xt, ti = inp
        gates = xt + h_prev @ w
        if gate_bias is not None:
            gates = gates + gate_bias
        # reference layout: candidate, input gate, forget gate, output gate
        gc, gi, gf, go = jnp.split(gates, 4, axis=-1)
        if w_ic is not None:
            gi = gi + c_prev * w_ic
            gf = gf + c_prev * w_fc
        i = jax.nn.sigmoid(gi)
        f = jax.nn.sigmoid(gf)
        cand = jnp.tanh(gc)
        c_new = f * c_prev + i * cand
        if w_oc is not None:
            go = go + c_new * w_oc
        o = jax.nn.sigmoid(go)
        h_new = o * jnp.tanh(c_new)
        mask = (ti < lens).astype(x.dtype).reshape(-1, 1)
        h_out = mask * h_new + (1 - mask) * h_prev
        c_out = mask * c_new + (1 - mask) * c_prev
        return (h_out, c_out), (h_out, c_out)

    # with reverse=True the scan hits padding (t >= len) first; it is masked
    h0 = ins["H0"][0] if ins.get("H0") and ins["H0"][0] is not None else jnp.zeros((b, h), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") and ins["C0"][0] is not None else jnp.zeros((b, h), x.dtype)
    init = (h0.astype(x.dtype), c0.astype(x.dtype))
    _, (hs, cs) = _scan_time(step, init, (xs, tidx), reverse=is_reverse)
    hidden = jnp.moveaxis(hs, 0, 1)
    cell = jnp.moveaxis(cs, 0, 1)
    hidden = _masked(hidden, lens)
    cell = _masked(cell, lens)
    return {"Hidden": [hidden], "Cell": [cell]}


@register("dynamic_gru")
def _dynamic_gru(ctx, ins, attrs):
    """GRU over padded (b,t,3h) pre-activations (reference gru_op.cc). Weight
    is (h, 3h): [:, :2h] update/reset recurrent weights, [:, 2h:] candidate."""
    (x,) = ins["Input"]
    (w,) = ins["Weight"]
    (seqlen,) = ins["SeqLen"]
    bias = ins["Bias"][0] if "Bias" in ins else None
    is_reverse = bool(attrs.get("is_reverse", False))
    b, t, h3 = x.shape
    h = h3 // 3
    lens = seqlen.reshape(-1).astype(jnp.int32)
    w_ur = w[:, : 2 * h]
    w_c = w[:, 2 * h :]

    xs = jnp.moveaxis(x, 1, 0)
    tidx = jnp.arange(t)

    def step(h_prev, inp):
        xt, ti = inp
        if bias is not None:
            xt = xt + bias.reshape(-1)
        g_ur = xt[:, : 2 * h] + h_prev @ w_ur
        u = jax.nn.sigmoid(g_ur[:, :h])
        r = jax.nn.sigmoid(g_ur[:, h:])
        c = jnp.tanh(xt[:, 2 * h :] + (r * h_prev) @ w_c)
        # reference gru_finalOutput: h = (1-u)*h_prev + u*c
        h_new = (1 - u) * h_prev + u * c
        mask = (ti < lens).astype(x.dtype).reshape(-1, 1)
        h_out = mask * h_new + (1 - mask) * h_prev
        return h_out, h_out

    init = (
        ins["H0"][0].astype(x.dtype)
        if ins.get("H0") and ins["H0"][0] is not None
        else jnp.zeros((b, h), x.dtype)
    )
    _, hs = _scan_time(step, init, (xs, tidx), reverse=is_reverse)
    hidden = _masked(jnp.moveaxis(hs, 0, 1), lens)
    return {"Hidden": [hidden]}


@register("lstm_unit")
def _lstm_unit(ctx, ins, attrs):
    """Single LSTM step (reference lstm_unit_op.h:63-66, gate layout
    (i, f, o, g)): X (b,4h) pre-activations, C_prev (b,h) → C, H."""
    (x,) = ins["X"]
    (c_prev,) = ins["C_prev"]
    forget_bias = attrs.get("forget_bias", 0.0)
    gi, gf, go, gg = jnp.split(x, 4, axis=-1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + forget_bias)
    c = f * c_prev + i * jnp.tanh(gg)
    hidden = jax.nn.sigmoid(go) * jnp.tanh(c)
    return {"C": [c], "H": [hidden]}


@register("gru_unit")
def _gru_unit(ctx, ins, attrs):
    """Single GRU step (reference gru_unit_op.cc)."""
    (x,) = ins["Input"]
    (h_prev,) = ins["HiddenPrev"]
    (w,) = ins["Weight"]
    bias = ins["Bias"][0] if "Bias" in ins else None
    h = h_prev.shape[-1]
    if bias is not None:
        x = x + bias.reshape(-1)
    g_ur = x[:, : 2 * h] + h_prev @ w[:, : 2 * h]
    u = jax.nn.sigmoid(g_ur[:, :h])
    r = jax.nn.sigmoid(g_ur[:, h:])
    c = jnp.tanh(x[:, 2 * h :] + (r * h_prev) @ w[:, 2 * h :])
    # reference gru_unit_op.h:116: h = u*(c - h_prev) + h_prev
    h_new = (1 - u) * h_prev + u * c
    return {"Hidden": [h_new], "ResetHiddenPrev": [r * h_prev], "Gate": [jnp.concatenate([u, r, c], -1)]}


# ---------------------------------------------------------------------------
# padding / reshaping / editing ops (reference sequence_ops/: sequence_pad_op,
# sequence_unpad_op, sequence_mask_op, sequence_concat_op,
# sequence_expand_as_op, sequence_slice_op, sequence_erase_op,
# sequence_reshape_op, sequence_scatter_op, sequence_enumerate_op,
# im2sequence_op.cc, row_conv_op.cc). In the padded-dense representation
# several of these become masked gathers instead of LoD re-packing.
# ---------------------------------------------------------------------------


@register("sequence_pad")
def _sequence_pad(ctx, ins, attrs):
    """Already-padded rep: adjust capacity to padded_length and fill padding
    with PadValue (reference sequence_pad_op.cc also emits Length)."""
    (x,) = ins["X"]
    (pad_value,) = ins["PadValue"]
    (seqlen,) = ins["SeqLen"]
    lens = seqlen.reshape(-1).astype(jnp.int32)
    maxlen = int(attrs.get("padded_length", -1))
    t = x.shape[1]
    if maxlen > 0 and maxlen != t:
        if maxlen < t:
            x = x[:, :maxlen]
            # rows longer than the new capacity are truncated; keep Length
            # consistent with the data actually present (the reference op
            # rejects padded_length < max length outright — lengths here are
            # runtime values, so clamping is the static-shape equivalent)
            lens = jnp.minimum(lens, maxlen)
        else:
            pad_shape = (x.shape[0], maxlen - t) + x.shape[2:]
            x = jnp.concatenate([x, jnp.zeros(pad_shape, x.dtype)], axis=1)
    t = x.shape[1]
    m = (jnp.arange(t)[None, :] < lens[:, None])
    mexp = m.reshape(m.shape + (1,) * (x.ndim - 2))
    # PadValue: scalar, or feature-shaped (broadcast over batch and time) —
    # reference sequence_pad_op.cc accepts both
    if pad_value.size == 1:
        pv = pad_value.reshape((1,) * x.ndim)
    else:
        pv = pad_value.reshape((1, 1) + tuple(pad_value.shape))
    out = jnp.where(mexp, x, pv.astype(x.dtype))
    return {"Out": [out], "Length": [lens]}


@register("sequence_unpad")
def _sequence_unpad(ctx, ins, attrs):
    """Inverse of sequence_pad: zero out padding and re-attach Length as the
    SeqLen companion (layer side)."""
    (x,) = ins["X"]
    (length,) = ins["Length"]
    lens = length.reshape(-1).astype(jnp.int32)
    return {"Out": [_masked(x, lens)]}


@register("sequence_mask", no_grad=True)
def _sequence_mask(ctx, ins, attrs):
    (x,) = ins["X"]  # lengths
    maxlen = int(attrs.get("maxlen", -1))
    dtype = jnp.dtype(attrs.get("out_dtype", "int64"))
    lens = x.reshape(-1).astype(jnp.int32)
    if maxlen <= 0:
        raise ValueError(
            "sequence_mask requires a static maxlen in the XLA lowering"
        )
    m = jnp.arange(maxlen)[None, :] < lens[:, None]
    return {"Y": [m.astype(dtype)]}


@register("sequence_concat")
def _sequence_concat(ctx, ins, attrs):
    """Concatenate along time per row (reference sequence_concat_op.cc):
    row b = x1[b,:l1] ++ x2[b,:l2] ++ ..., then padding."""
    xs = ins["X"]
    lens_list = [l.reshape(-1).astype(jnp.int32) for l in ins["SeqLen"]]
    b = xs[0].shape[0]
    t_out = sum(x.shape[1] for x in xs)
    pos = jnp.arange(t_out, dtype=jnp.int32)[None, :]  # [1, T_out]
    out = jnp.zeros((b, t_out) + xs[0].shape[2:], xs[0].dtype)
    offset = jnp.zeros((b, 1), jnp.int32)
    for x, lens in zip(xs, lens_list):
        # positions [offset, offset+len) take x[pos - offset]
        rel = pos - offset
        inside = (rel >= 0) & (rel < lens[:, None])
        src = jnp.clip(rel, 0, x.shape[1] - 1)
        gathered = jnp.take_along_axis(
            x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1
        )
        sel = inside.reshape(inside.shape + (1,) * (x.ndim - 2))
        out = jnp.where(sel, gathered, out)
        offset = offset + lens[:, None]
    return {"Out": [out], "OutLen": [offset.reshape(-1)]}


@register("sequence_expand_as")
def _sequence_expand_as(ctx, ins, attrs):
    """Each row of X repeated along a time axis to Y's length (reference
    sequence_expand_as_op.cc), padding-masked."""
    (x,) = ins["X"]
    (seqlen,) = ins["SeqLen"]  # lengths of Y
    (y,) = ins["Y"]
    lens = seqlen.reshape(-1).astype(jnp.int32)
    t = y.shape[1]
    out = jnp.broadcast_to(x[:, None], (x.shape[0], t) + x.shape[1:])
    return {"Out": [_masked(out, lens)]}


@register("sequence_slice")
def _sequence_slice(ctx, ins, attrs):
    """Per-row [offset, offset+length) slice (reference
    sequence_slice_op.h), re-compacted to position 0 of each row."""
    (x,) = ins["X"]
    (offset,) = ins["Offset"]
    (length,) = ins["Length"]
    off = offset.reshape(-1).astype(jnp.int32)
    ln = length.reshape(-1).astype(jnp.int32)
    t = x.shape[1]
    pos = jnp.arange(t, dtype=jnp.int32)[None, :]
    src = jnp.clip(pos + off[:, None], 0, t - 1)
    gathered = jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1
    )
    inside = pos < ln[:, None]
    out = jnp.where(
        inside.reshape(inside.shape + (1,) * (x.ndim - 2)),
        gathered,
        jnp.zeros((), x.dtype),
    )
    return {"Out": [out], "OutLen": [ln]}


@register("sequence_erase")
def _sequence_erase(ctx, ins, attrs):
    """Drop listed tokens and re-compact each row (reference
    sequence_erase_op.cc)."""
    (x,) = ins["X"]  # [B, T] or [B, T, 1] int
    (seqlen,) = ins["SeqLen"]
    tokens = list(attrs.get("tokens", []))
    lens = seqlen.reshape(-1).astype(jnp.int32)
    squeeze = x.ndim == 3
    v = x.reshape(x.shape[:2]) if squeeze else x
    b, t = v.shape
    pos = jnp.arange(t, dtype=jnp.int32)[None, :]
    keep = pos < lens[:, None]
    for tok in tokens:
        keep = keep & (v != tok)
    order = jnp.argsort(~keep, axis=1, stable=True)
    compacted = jnp.take_along_axis(v, order, axis=1)
    out_len = keep.sum(axis=1).astype(jnp.int32)
    out = jnp.where(pos < out_len[:, None], compacted, 0)
    if squeeze:
        out = out[:, :, None]
    return {"Out": [out.astype(x.dtype)], "OutLen": [out_len]}


@register("sequence_reshape")
def _sequence_reshape(ctx, ins, attrs):
    """Regroup each row's (len, d) payload as (len*d/new_dim, new_dim)
    (reference sequence_reshape_op.cc; lengths must divide evenly)."""
    (x,) = ins["X"]
    (seqlen,) = ins["SeqLen"]
    new_dim = int(attrs["new_dim"])
    b, t, d = x.shape
    lens = seqlen.reshape(-1).astype(jnp.int32)
    xm = _masked(x, lens)
    out = xm.reshape(b, t * d // new_dim, new_dim)
    out_len = lens * d // new_dim
    return {"Out": [out], "OutLen": [out_len]}


@register("sequence_scatter")
def _sequence_scatter(ctx, ins, attrs):
    """out[b, ids[b, j]] += updates[b, j] for valid j (reference
    sequence_scatter_op.cc)."""
    (x,) = ins["X"]  # [B, N]
    (ids,) = ins["Ids"]  # [B, L] or [B, L, 1]
    (upd,) = ins["Updates"]  # same layout as ids
    (seqlen,) = ins["SeqLen"]  # lengths of ids
    lens = seqlen.reshape(-1).astype(jnp.int32)
    b = x.shape[0]
    iv = ids.reshape(b, -1).astype(jnp.int32)
    uv = upd.reshape(b, -1).astype(x.dtype)
    l = iv.shape[1]
    valid = jnp.arange(l, dtype=jnp.int32)[None, :] < lens[:, None]
    uv = jnp.where(valid, uv, 0.0)
    iv = jnp.where(valid, iv, 0)
    rows = jnp.broadcast_to(jnp.arange(b, dtype=jnp.int32)[:, None], iv.shape)
    return {"Out": [x.at[rows, iv].add(uv)]}


@register("sequence_enumerate", no_grad=True)
def _sequence_enumerate(ctx, ins, attrs):
    """Sliding windows of ids (reference sequence_enumerate_op.cc): out[b,t]
    = [x[b,t], ..., x[b,t+w-1]], pad_value past the row length."""
    (x,) = ins["X"]  # [B, T] or [B, T, 1]
    (seqlen,) = ins["SeqLen"]
    win = int(attrs["win_size"])
    pad = int(attrs.get("pad_value", 0))
    lens = seqlen.reshape(-1).astype(jnp.int32)
    squeeze = x.ndim == 3
    v = x.reshape(x.shape[:2]) if squeeze else x
    b, t = v.shape
    pos = jnp.arange(t, dtype=jnp.int32)[None, :]
    cols = []
    for k in range(win):
        src = jnp.clip(pos + k, 0, t - 1)
        g = jnp.take_along_axis(v, src, axis=1)
        ok = (pos + k) < lens[:, None]
        cols.append(jnp.where(ok, g, pad))
    out = jnp.stack(cols, axis=2)  # [B, T, win]
    valid = pos < lens[:, None]
    out = jnp.where(valid[:, :, None], out, pad)
    return {"Out": [out.astype(x.dtype)]}


@register("im2sequence")
def _im2sequence(ctx, ins, attrs):
    """Image → patch sequence (reference im2sequence_op.cc): each output row
    is the flattened kernel window, row-major over (out_h, out_w).

    Real-size mode (reference im2sequence_op.h:52-110): with Y holding per-
    image (real_h, real_w) and the out_stride attr, each image keeps only its
    top-left oh_i×ow_i patch sub-grid where oh_i/ow_i derive from
    ceil(real/out_stride) through the output-size formula. Padded-dense
    analog: the static full grid is computed, each row's valid sub-grid is
    compacted to a row-major prefix by gather, the tail is zeroed, and the
    per-row lengths are emitted as OutLen (the LoD companion)."""
    (x,) = ins["X"]  # [B, C, H, W]
    kh, kw = [int(k) for k in attrs["kernels"]]
    sh, sw = [int(s) for s in attrs.get("strides", [1, 1])]
    pads = [int(p) for p in attrs.get("paddings", [0, 0, 0, 0])]
    patches = lax.conv_general_dilated_patches(
        x,
        filter_shape=(kh, kw),
        window_strides=(sh, sw),
        padding=[(pads[0], pads[2]), (pads[1], pads[3])],
    )  # [B, C*kh*kw, OH, OW]
    b, ckk, oh, ow = patches.shape
    out = jnp.moveaxis(patches.reshape(b, ckk, oh * ow), 1, 2)
    y = ins.get("Y", [None])[0]
    if y is None:
        return {"Out": [out]}
    # Reference kernel (im2sequence_op.h:51) only enters real-size mode when
    # batch_size > 1; for a single image it ignores Y and emits the full
    # static grid. Replicated verbatim for parity (upstream quirk).
    if b == 1:
        full = jnp.full((b,), oh * ow, dtype=jnp.int32)
        return {"Out": [out], "OutLen": [full]}

    osh, osw = [int(s) for s in attrs.get("out_stride", [1, 1])]
    real = y.reshape(b, 2).astype(jnp.int32)
    # reference: ceil-divide real sizes by out_stride, then the standard
    # output-size formula per image, clamped to the static grid
    rh = -(-real[:, 0] // osh)
    rw = -(-real[:, 1] // osw)
    oh_i = jnp.clip((rh + pads[0] + pads[2] - kh) // sh + 1, 0, oh)
    ow_i = jnp.clip((rw + pads[1] + pads[3] - kw) // sw + 1, 0, ow)
    lens = (oh_i * ow_i).astype(jnp.int32)
    p = jnp.arange(oh * ow, dtype=jnp.int32)[None, :]  # (1, OH*OW)
    ow_safe = jnp.maximum(ow_i, 1)[:, None]
    src = jnp.where(
        p < lens[:, None], (p // ow_safe) * ow + p % ow_safe, p
    )
    out = jnp.take_along_axis(out, src[..., None], axis=1)
    out = out * (p < lens[:, None])[..., None].astype(out.dtype)
    return {"Out": [out], "OutLen": [lens]}


@register("row_conv")
def _row_conv(ctx, ins, attrs):
    """Lookahead (row) convolution (reference row_conv_op.cc):
    out[b,t] = sum_{k<future_ctx} x[b,t+k] * filter[k]."""
    (x,) = ins["X"]  # [B, T, D]
    (w,) = ins["Filter"]  # [future_ctx, D]
    (seqlen,) = ins["SeqLen"]
    lens = seqlen.reshape(-1).astype(jnp.int32)
    xm = _masked(x, lens)
    t = x.shape[1]
    out = jnp.zeros_like(xm)
    pos = jnp.arange(t, dtype=jnp.int32)[None, :, None]
    for k in range(w.shape[0]):
        shifted = jnp.roll(xm, -k, axis=1)
        ok = (pos + k) < t
        out = out + jnp.where(ok, shifted, 0.0) * w[k][None, None, :]
    return {"Out": [_masked(out, lens)]}
