"""Framework-level parity ops: graph-native checkpoint (save/load), scope
management, IfElse row split/merge, tensor-array export, sharded-id plumbing.

Reference analogs: operators/save_op.cc, load_op.cc, save_combine_op.cc,
load_combine_op.cc (checkpointing as ops executed by io.py-built programs,
SURVEY.md §5.4), delete_var_op.cc, controlflow/get_places_op.cc, csp/go_op.cc,
split_lod_tensor_op.cc / merge_lod_tensor_op.cc (the reference IfElse's
row-scatter — here masked selects, static shapes), tensor_array_to_tensor_op.cc,
rnn_memory_helper_op.cc, distributed_ops/split_ids_op.cc / merge_ids_op.cc /
split_byref_op.cc, distributed_ops/prefetch_op.cc + distributed/
parameter_prefetch.cc:26 (remote sparse-table row fetch), distributed_ops/
gen_nccl_id_op.cc.

NOT replicated: split_selected_rows / merge_selected_rows /
get_tensor_from_selected_rows / lookup_sparse_table — this framework has no
SelectedRows runtime type; sparse embedding gradients are dense scatter-adds
and sharded tables live in parallel/sharded_embedding.py (SURVEY.md §7 hard
part 5), so those ops have no value to operate on.
"""

import os
import threading

import jax.numpy as jnp
import numpy as np

from .registry import register, register_host


# ---------------------------------------------------------------------------
# graph-native checkpoint ops (reference save_op.cc / load_op.cc; io.py's
# save/load build programs of these in the reference — our io.py writes
# directly, these ops make user programs that embed save/load runnable)
# ---------------------------------------------------------------------------


def _save_path(op):
    path = op.attrs["file_path"]
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    return path


@register_host("save")
def _save(op, scope):
    from .. import io as fluid_io

    (name,) = op.input("X")
    val = scope.find_var(name)
    if val is None:
        raise RuntimeError("save: variable %r has no value in scope" % name)
    arr, orig = fluid_io._bf16_safe_save(val)
    path = _save_path(op)
    if op.attrs.get("save_as_fp16", False):
        arr = arr.astype(np.float16)
    np.save(path, arr)
    if orig:
        with open(path + ".dtype", "w") as f:
            f.write(orig)


@register_host("load")
def _load(op, scope):
    path = op.attrs["file_path"]
    arr = np.load(path if path.endswith(".npy") else path + ".npy")
    (name,) = op.output("Out")
    if os.path.exists(path + ".dtype"):
        with open(path + ".dtype") as f:
            orig = f.read().strip()
        arr = jnp.asarray(arr).astype(orig)
    scope.set_var(name, jnp.asarray(arr))


@register_host("save_combine")
def _save_combine(op, scope):
    from .. import io as fluid_io

    path = _save_path(op)
    arrays = {}
    dtypes = {}
    for name in op.input("X"):
        val = scope.find_var(name)
        if val is None:
            raise RuntimeError("save_combine: variable %r has no value" % name)
        arr, orig = fluid_io._bf16_safe_save(val)
        arrays[name] = arr
        if orig:
            dtypes[name] = orig
    np.savez(path, __dtypes__=np.array([repr(dtypes)]), **arrays)


@register_host("load_combine")
def _load_combine(op, scope):
    path = op.attrs["file_path"]
    data = np.load(path if path.endswith(".npz") else path + ".npz", allow_pickle=False)
    dtypes = {}
    if "__dtypes__" in data:
        import ast

        dtypes = ast.literal_eval(str(data["__dtypes__"][0]))
    for name in op.output("Out"):
        arr = jnp.asarray(data[name])
        if name in dtypes:
            arr = arr.astype(dtypes[name])
        scope.set_var(name, arr)


@register_host("delete_var")
def _delete_var(op, scope):
    """Eager scope cleanup (reference delete_var_op.cc; the executor's GC
    analog for explicitly-programmed deletion)."""
    for name in op.input("X"):
        scope.vars.pop(name, None)


@register_host("get_places")
def _get_places(op, scope):
    """Device enumeration (reference controlflow/get_places_op.cc, feeds
    parallel_do). Stores the device count; SPMD placement itself is mesh-
    driven (parallel/mesh.py), not place-list driven."""
    import jax

    kind = op.attrs.get("device_type", "")
    devs = jax.devices()
    count = int(op.attrs.get("device_count", 0) or 0) or len(devs)
    (out,) = op.output("Out")
    scope.set_var(out, jnp.arange(count, dtype=jnp.int32))


@register_host("go")
def _go(op, scope):
    """Fire-and-forget async block launch (reference csp/go_op.cc spawns a
    detached thread running the sub-block on a child scope)."""
    from ..executor import _SegmentedBlock

    sub = op.attrs["sub_block"]
    program = op.block.program

    def run():
        seg = _SegmentedBlock(program, sub, [], [])
        seg(scope, {})

    t = threading.Thread(target=run, daemon=True)
    t.start()
    # keep handles so callers/tests can join deterministically
    threads = scope.find_var("__go_threads__")
    if not isinstance(threads, list):
        threads = []
        scope.vars["__go_threads__"] = threads
    threads.append(t)


# ---------------------------------------------------------------------------
# IfElse row scatter/gather + array export + StaticRNN memory plumbing
# ---------------------------------------------------------------------------


@register("split_lod_tensor")
def _split_lod_tensor(ctx, ins, attrs):
    """Reference split_lod_tensor_op.cc compacts true/false rows into two
    smaller tensors; XLA needs static shapes, so both outputs keep the full
    batch with non-selected rows zeroed — merge_lod_tensor composes exactly
    (the reference IfElse contract is split→branch→merge, and per-row
    branches commute with the masking)."""
    (x,) = ins["X"]
    (mask,) = ins["Mask"]
    m = mask.reshape(-1).astype(bool)
    shape = (-1,) + (1,) * (x.ndim - 1)
    mf = m.reshape(shape)
    return {"OutTrue": [jnp.where(mf, x, 0)], "OutFalse": [jnp.where(mf, 0, x)]}


@register("merge_lod_tensor")
def _merge_lod_tensor(ctx, ins, attrs):
    (in_true,) = ins["InTrue"]
    (in_false,) = ins["InFalse"]
    (mask,) = ins["Mask"]
    m = mask.reshape((-1,) + (1,) * (in_true.ndim - 1)).astype(bool)
    return {"Out": [jnp.where(m, in_true, in_false)]}


@register("tensor_array_to_tensor", infer_shape=lambda op, block: None)
def _tensor_array_to_tensor(ctx, ins, attrs):
    """Concat/stack the (buffer, size) tensor-array along `axis` (reference
    tensor_array_to_tensor_op.cc). Static-capacity semantics: all buffer
    slots participate (writes past `size` never happen under the layers API)."""
    (arr,) = ins["X"]
    buf, _size = arr
    axis = int(attrs.get("axis", 0))
    if attrs.get("use_stack", False):
        out = jnp.moveaxis(buf, 0, axis)
        # reference OutIndex under stack: one slot contributed per input
        per_slot = 1
    else:
        pieces = [buf[i] for i in range(buf.shape[0])]
        out = jnp.concatenate(pieces, axis=axis)
        # reference OutIndex holds each input's extent along the concat axis;
        # buf slots are uniform, so that's slot-shape[axis]
        per_slot = pieces[0].shape[axis] if pieces[0].ndim else 1
    idx = jnp.full((buf.shape[0],), per_slot, jnp.int32)
    return {"Out": [out], "OutIndex": [idx]}


@register("rnn_memory_helper")
def _rnn_memory_helper(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [x]}


@register("rnn_memory_helper_grad", no_grad=True)
def _rnn_memory_helper_grad(ctx, ins, attrs):
    (g,) = ins["Out@GRAD"]
    return {"X@GRAD": [g]}


# ---------------------------------------------------------------------------
# sharded-id plumbing for distributed sparse tables (reference
# distributed_ops/split_ids_op.cc: shard = id % n; merge_ids_op.cc restores
# original order from the per-shard results)
# ---------------------------------------------------------------------------


@register("split_ids", no_grad=True)
def _split_ids(ctx, ins, attrs):
    """Static-shape redesign: each of the N outputs keeps the full id vector
    with other shards' slots masked to -1 (dense analog of the reference's
    compaction; lookup results are gathered back by position, so masked slots
    never surface)."""
    (ids,) = ins["Ids"]
    flat = ids.reshape(-1)
    # shard count = declared output arity, carried as an attr by the layer /
    # transpiler (lowerings see slots, not the OpDesc's output list)
    n = int(attrs.get("num_shards") or attrs.get("n_parts") or 1)
    outs = []
    for shard in range(n):
        keep = (flat % n) == shard
        outs.append(jnp.where(keep, flat, -1))
    return {"Out": outs}


@register("merge_ids", no_grad=True)
def _merge_ids(ctx, ins, attrs):
    """Rows[i] holds shard i's lookup result aligned to the original id
    positions (split_ids' masked layout); merge selects per position."""
    (ids,) = ins["Ids"]
    rows = ins["X"]
    flat = ids.reshape(-1).astype(jnp.int32)
    n = len(rows)
    out = rows[0]
    for shard in range(1, n):
        sel = ((flat % n) == shard).reshape((-1,) + (1,) * (rows[0].ndim - 1))
        out = jnp.where(sel, rows[shard], out)
    return {"Out": [out]}


@register("split_byref")
def _split_byref(ctx, ins, attrs):
    """Row-section split (reference split_byref_op.cc — zero-copy slices of
    the param for per-pserver send; XLA slices fuse into the send staging)."""
    (x,) = ins["X"]
    sections = [int(s) for s in attrs["sections"]]
    outs = []
    start = 0
    for s in sections:
        outs.append(x[start : start + s])
        start += s
    return {"Out": outs}


@register_host("prefetch")
def _prefetch(op, scope):
    """Remote sparse-table row fetch (reference distributed_ops/prefetch_op.cc
    + parameter_prefetch.cc:26): send the id vector, receive the rows. Served
    by the pserver's __prefetch__ GET channel (distributed/listen_and_serv.py)."""
    from ..distributed.rpc import RPCClient

    client = RPCClient.instance(int(op.attrs.get("trainer_id", 0)))
    in_names = op.input("X")
    out_names = op.output("Out")
    epmap = op.attrs["epmap"]
    table = op.attrs.get("table_name")
    if not table:
        names = op.attrs.get("table_names")
        table = names[0] if isinstance(names, (list, tuple)) and names else ""
    for ids_name, out_name, ep in zip(in_names, out_names, epmap):
        ids = np.asarray(scope.find_var(ids_name)).reshape(-1)
        client.async_send_var(ep, "__prefetch_ids__:%s:%s" % (table, out_name), ids)
    client.wait()
    futures = [
        (out_name, ep, client.async_get_var(ep, "__prefetch_out__:%s:%s" % (table, out_name)))
        for out_name, ep in zip(out_names, epmap)
    ]
    for out_name, ep, f in futures:
        rows = f.result(timeout=client.timeout)
        if rows is None:
            raise RuntimeError("prefetch: pserver %s returned no rows" % ep)
        scope.set_var(out_name, jnp.asarray(rows))


@register_host("gen_nccl_id")
def _gen_nccl_id(op, scope):
    """Collective rendezvous (reference gen_nccl_id_op.cc gossiped an
    ncclUniqueId over a temporary gRPC server). On TPU the XLA runtime's
    coordination service owns rendezvous — jax.distributed.initialize at
    process start (parallel/multihost.py) — so this op is a checked no-op
    kept so transpiled NCCL2-mode startup programs execute."""
    slot = "NCCLID" if op.outputs.get("NCCLID") else "Out"
    for out in op.output(slot):
        scope.set_var(out, jnp.zeros((1,), jnp.int32))


# program-compat registrations for reader ops: this framework's py_reader
# path stages batches in Executor.run directly (executor.py pulls
# program._py_readers), so `read`/`create_*_reader` nodes in imported
# reference programs are markers, not compute (reference reader/read_op.cc)
from .registry import register_no_lower

for _t in (
    "read",
    "create_custom_reader",
    "create_recordio_file_reader",
    "create_shuffle_reader",
    "create_batch_reader",
    "create_double_buffer_reader",
    "create_py_reader",
    "open_files",
):
    register_no_lower(_t)
