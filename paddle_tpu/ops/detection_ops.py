"""Detection ops (reference paddle/fluid/operators/detection/ + roi_pool/
roi_align/yolov3_loss at operators/ top level — 35 files, §2.5 of SURVEY.md).

TPU-first notes: everything is fixed-shape. Variable-count results (NMS
keeps, proposals) come out as fixed-capacity tensors padded with -1 plus an
explicit count (the reference used LoD); selection loops (NMS, bipartite
match) are lax.scan/fori_loop with masking, not data-dependent host loops.
RoIs ride as padded [B, R, 4] + RoisLen instead of LoD.
"""

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

# plain float, NOT a jnp array: module-level device values would
# initialize the jax backend at import time, freezing the platform
# before tests/drivers can flip it to CPU (see platform_setup.py)
NEG = -1e9


def _expand_aspect_ratios(aspect_ratios, flip):
    """reference prior_box_op.h:25 ExpandAspectRatios (starts from 1.0)."""
    out = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - o) < 1e-6 for o in out):
            continue
        out.append(ar)
        if flip:
            out.append(1.0 / ar)
    return out


@register("prior_box", no_grad=True)
def _prior_box(ctx, ins, attrs):
    """SSD prior boxes (reference detection/prior_box_op.h:33-190). Output
    Boxes/Variances are [H, W, num_priors, 4]."""
    (feat,) = ins["Input"]  # [B, C, H, W]
    (image,) = ins["Image"]  # [B, C, IH, IW]
    min_sizes = [float(v) for v in attrs["min_sizes"]]
    max_sizes = [float(v) for v in attrs.get("max_sizes", [])]
    ars = _expand_aspect_ratios(
        [float(v) for v in attrs.get("aspect_ratios", [1.0])],
        bool(attrs.get("flip", False)),
    )
    variances = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    clip = bool(attrs.get("clip", False))
    mmao = bool(attrs.get("min_max_aspect_ratios_order", False))
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = float(attrs.get("step_w", 0.0)) or iw / fw
    step_h = float(attrs.get("step_h", 0.0)) or ih / fh
    offset = float(attrs.get("offset", 0.5))

    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w  # [W]
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h  # [H]

    # per-cell prior (w/2, h/2) list in the reference's emission order
    half_sizes = []
    for s, mn in enumerate(min_sizes):
        if mmao:
            half_sizes.append((mn / 2.0, mn / 2.0))
            if max_sizes:
                m = (mn * max_sizes[s]) ** 0.5 / 2.0
                half_sizes.append((m, m))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                half_sizes.append((mn * ar**0.5 / 2.0, mn / ar**0.5 / 2.0))
        else:
            for ar in ars:
                half_sizes.append((mn * ar**0.5 / 2.0, mn / ar**0.5 / 2.0))
            if max_sizes:
                m = (mn * max_sizes[s]) ** 0.5 / 2.0
                half_sizes.append((m, m))
    hw = jnp.asarray([p[0] for p in half_sizes], jnp.float32)  # [P]
    hh = jnp.asarray([p[1] for p in half_sizes], jnp.float32)

    gx = cx[None, :, None]  # [1, W, 1]
    gy = cy[:, None, None]  # [H, 1, 1]
    full = (fh, fw, hw.shape[0])
    boxes = jnp.stack(
        [
            jnp.broadcast_to((gx - hw) / iw, full),
            jnp.broadcast_to((gy - hh) / ih, full),
            jnp.broadcast_to((gx + hw) / iw, full),
            jnp.broadcast_to((gy + hh) / ih, full),
        ],
        axis=-1,
    )  # [H, W, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), (fh, fw, hw.shape[0], 4)
    )
    return {"Boxes": [boxes], "Variances": [var]}


@register("density_prior_box", no_grad=True)
def _density_prior_box(ctx, ins, attrs):
    """reference detection/density_prior_box_op.h: dense grid of square
    priors per (fixed_size, density) pair, shifted within the cell."""
    (feat,) = ins["Input"]
    (image,) = ins["Image"]
    fixed_sizes = [float(v) for v in attrs["fixed_sizes"]]
    fixed_ratios = [float(v) for v in attrs.get("fixed_ratios", [1.0])]
    densities = [int(v) for v in attrs["densities"]]
    variances = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    clip = bool(attrs.get("clip", False))
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = float(attrs.get("step_w", 0.0)) or iw / fw
    step_h = float(attrs.get("step_h", 0.0)) or ih / fh
    offset = float(attrs.get("offset", 0.5))

    # per-cell (dx, dy, w/2, h/2) in emission order
    entries = []
    for s, fs in enumerate(fixed_sizes):
        density = densities[s]
        shift = step_w / density
        for ar in fixed_ratios:
            bw = fs * ar**0.5
            bh = fs / ar**0.5
            for di in range(density):
                for dj in range(density):
                    dx = -step_w / 2.0 + shift / 2.0 + dj * shift
                    dy = -step_h / 2.0 + shift / 2.0 + di * shift
                    entries.append((dx, dy, bw / 2.0, bh / 2.0))
    dx = jnp.asarray([e[0] for e in entries], jnp.float32)
    dy = jnp.asarray([e[1] for e in entries], jnp.float32)
    hw = jnp.asarray([e[2] for e in entries], jnp.float32)
    hh = jnp.asarray([e[3] for e in entries], jnp.float32)

    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
    gx = cx[None, :, None] + dx
    gy = cy[:, None, None] + dy
    full = (fh, fw, hw.shape[0])
    boxes = jnp.stack(
        [
            jnp.broadcast_to((gx - hw) / iw, full),
            jnp.broadcast_to((gy - hh) / ih, full),
            jnp.broadcast_to((gx + hw) / iw, full),
            jnp.broadcast_to((gy + hh) / ih, full),
        ],
        axis=-1,
    )
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), (fh, fw, hw.shape[0], 4)
    )
    return {"Boxes": [boxes], "Variances": [var]}


@register("anchor_generator", no_grad=True)
def _anchor_generator(ctx, ins, attrs):
    """reference detection/anchor_generator_op.h: RPN anchors in input-image
    coordinates, [H, W, num_anchors, 4]."""
    (feat,) = ins["Input"]
    sizes = [float(v) for v in attrs["anchor_sizes"]]
    ratios = [float(v) for v in attrs["aspect_ratios"]]
    variances = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    stride = [float(v) for v in attrs["stride"]]
    offset = float(attrs.get("offset", 0.5))
    fh, fw = feat.shape[2], feat.shape[3]

    hs = []
    for r in ratios:
        for s in sizes:
            area = stride[0] * stride[1]
            area_ratios = area / r
            base_w = round(area_ratios**0.5)
            base_h = round(base_w * r)
            scale_w = s / stride[0]
            scale_h = s / stride[1]
            hs.append((scale_w * base_w / 2.0, scale_h * base_h / 2.0))
    hw = jnp.asarray([p[0] for p in hs], jnp.float32)
    hh = jnp.asarray([p[1] for p in hs], jnp.float32)

    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * stride[0]
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * stride[1]
    gx = cx[None, :, None]
    gy = cy[:, None, None]
    full = (fh, fw, hw.shape[0])
    anchors = jnp.stack(
        [
            jnp.broadcast_to(gx - hw + 0.0, full),
            jnp.broadcast_to(gy - hh + 0.0, full),
            jnp.broadcast_to(gx + hw - 1.0, full),
            jnp.broadcast_to(gy + hh - 1.0, full),
        ],
        axis=-1,
    )
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), (fh, fw, hw.shape[0], 4)
    )
    return {"Anchors": [anchors], "Variances": [var]}


def _center_size(box, normalized):
    """(x1,y1,x2,y2) -> (cx, cy, w, h); +1 when unnormalized (reference
    box_coder_op.h pixel convention)."""
    plus = 0.0 if normalized else 1.0
    w = box[..., 2] - box[..., 0] + plus
    h = box[..., 3] - box[..., 1] + plus
    cx = (box[..., 0] + box[..., 2]) / 2.0
    cy = (box[..., 1] + box[..., 3]) / 2.0
    return cx, cy, w, h


@register("box_coder", no_grad=True)
def _box_coder(ctx, ins, attrs):
    """reference detection/box_coder_op.h. encode: [row,4]x[col,4]->[row,col,4];
    decode: target [row,col,4] (or [row,4] broadcast) -> [row,col,4]."""
    (prior,) = ins["PriorBox"]  # [col, 4]
    (target,) = ins["TargetBox"]
    pb_var = ins.get("PriorBoxVar", [None])[0]
    code_type = attrs.get("code_type", "encode_center_size")
    normalized = bool(attrs.get("box_normalized", True))

    pcx, pcy, pw, ph = _center_size(prior, normalized)  # [col]
    if pb_var is not None:
        v = pb_var  # [col, 4]
    else:
        v = None

    if code_type == "encode_center_size":
        tcx, tcy, tw, th = _center_size(target, normalized)  # [row]
        ex = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        ey = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ew = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        eh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out = jnp.stack([ex, ey, ew, eh], axis=-1)  # [row, col, 4]
        if v is not None:
            out = out / v[None, :, :]
    else:  # decode_center_size
        t = target if target.ndim == 3 else target[:, None, :]
        if v is not None:
            t = t * v[None, :, :]
        dcx = t[..., 0] * pw[None, :] + pcx[None, :]
        dcy = t[..., 1] * ph[None, :] + pcy[None, :]
        dw = jnp.exp(t[..., 2]) * pw[None, :]
        dh = jnp.exp(t[..., 3]) * ph[None, :]
        plus = 0.0 if normalized else 1.0
        out = jnp.stack(
            [
                dcx - dw / 2.0,
                dcy - dh / 2.0,
                dcx + dw / 2.0 - plus,
                dcy + dh / 2.0 - plus,
            ],
            axis=-1,
        )
    return {"OutputBox": [out]}


def _iou_matrix(a, b, normalized=True):
    """pairwise IoU: a [..., N, 4], b [..., M, 4] -> [..., N, M]."""
    plus = 0.0 if normalized else 1.0
    ax1, ay1, ax2, ay2 = [a[..., i] for i in range(4)]
    bx1, by1, bx2, by2 = [b[..., i] for i in range(4)]
    ix1 = jnp.maximum(ax1[..., :, None], bx1[..., None, :])
    iy1 = jnp.maximum(ay1[..., :, None], by1[..., None, :])
    ix2 = jnp.minimum(ax2[..., :, None], bx2[..., None, :])
    iy2 = jnp.minimum(ay2[..., :, None], by2[..., None, :])
    iw = jnp.maximum(ix2 - ix1 + plus, 0.0)
    ih = jnp.maximum(iy2 - iy1 + plus, 0.0)
    inter = iw * ih
    area_a = (ax2 - ax1 + plus) * (ay2 - ay1 + plus)
    area_b = (bx2 - bx1 + plus) * (by2 - by1 + plus)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register("iou_similarity", no_grad=True)
def _iou_similarity(ctx, ins, attrs):
    """reference detection/iou_similarity_op.h."""
    (x,) = ins["X"]  # [N, 4]
    (y,) = ins["Y"]  # [M, 4]
    normalized = bool(attrs.get("box_normalized", True))
    return {"Out": [_iou_matrix(x, y, normalized)]}


def _bipartite_match_single(dist):
    """Greedy global-max matching (reference bipartite_match_op.cc:65-139):
    repeatedly take the largest entry among unmatched rows/cols. Returns
    (col->row indices [M] int32 with -1, col dists [M])."""
    n, m = dist.shape

    def body(state, _):
        d, col_idx, col_dist = state
        flat = jnp.argmax(d)
        i, j = flat // m, flat % m
        ok = d[i, j] > 1e-6
        col_idx = jnp.where(
            ok, col_idx.at[j].set(i.astype(jnp.int32)), col_idx
        )
        col_dist = jnp.where(ok, col_dist.at[j].set(d[i, j]), col_dist)
        # retire row i and column j
        d = jnp.where(ok, d.at[i, :].set(NEG).at[:, j].set(NEG), d)
        return (d, col_idx, col_dist), None

    init = (
        dist.astype(jnp.float32),
        jnp.full((m,), -1, jnp.int32),
        jnp.zeros((m,), jnp.float32),
    )
    (d, col_idx, col_dist), _ = lax.scan(body, init, None, length=min(n, m))
    return col_idx, col_dist


@register("bipartite_match", no_grad=True)
def _bipartite_match(ctx, ins, attrs):
    (dist,) = ins["DistMat"]  # [B, N, M] or [N, M]
    match_type = attrs.get("match_type", "bipartite")
    overlap_threshold = float(attrs.get("dist_threshold", 0.5))
    batched = dist.ndim == 3
    d = dist if batched else dist[None]

    idx, dst = jax.vmap(_bipartite_match_single)(d)
    if match_type == "per_prediction":
        # additionally match unmatched cols to their argmax row if above the
        # threshold (reference ArgMaxMatch, bipartite_match_op.cc:141)
        am = jnp.argmax(d, axis=1).astype(jnp.int32)  # [B, M]
        amd = jnp.max(d, axis=1)
        take = (idx == -1) & (amd >= overlap_threshold)
        idx = jnp.where(take, am, idx)
        dst = jnp.where(take, amd, dst)
    if not batched:
        idx, dst = idx[0], dst[0]
    return {"ColToRowMatchIndices": [idx], "ColToRowMatchDist": [dst]}


@register("target_assign", no_grad=True)
def _target_assign(ctx, ins, attrs):
    """reference detection/target_assign_op.h: out[i,j] = X[i, match[i,j]]
    where match >= 0 else mismatch_value; weights 1/0 alike."""
    (x,) = ins["X"]  # [B, N, K] (gt rows per image, padded)
    (match,) = ins["MatchIndices"]  # [B, M] int32
    mismatch = attrs.get("mismatch_value", 0)
    neg = ins.get("NegIndices", [None])[0]
    m = match.astype(jnp.int32)
    safe = jnp.maximum(m, 0)
    out = jnp.take_along_axis(x, safe[:, :, None], axis=1)  # [B, M, K]
    matched = (m >= 0)[:, :, None]
    out = jnp.where(matched, out, jnp.asarray(mismatch, x.dtype))
    w = matched.astype(jnp.float32)
    if neg is not None:
        # rows listed in NegIndices also get weight 1 (classification targets
        # for mined negatives), padded entries are -1
        nmask = jnp.zeros(match.shape, jnp.float32)
        ni = neg.reshape(neg.shape[0], -1).astype(jnp.int32)
        valid = ni >= 0
        rows = jnp.broadcast_to(
            jnp.arange(match.shape[0], dtype=jnp.int32)[:, None], ni.shape
        )
        nmask = nmask.at[rows, jnp.maximum(ni, 0)].max(
            valid.astype(jnp.float32)
        )
        w = jnp.maximum(w, nmask[:, :, None])
    return {"Out": [out], "OutWeight": [w]}


@register("mine_hard_examples", no_grad=True)
def _mine_hard_examples(ctx, ins, attrs):
    """reference detection/mine_hard_examples_op.cc (max_negative mining):
    pick the top neg_pos_ratio * num_pos unmatched priors by loss. Output is
    fixed [B, M] of selected negative prior indices, -1 padded."""
    (cls_loss,) = ins["ClsLoss"]  # [B, M, 1] or [B, M]
    (match,) = ins["MatchIndices"]  # [B, M]
    loc_loss = ins.get("LocLoss", [None])[0]
    neg_pos_ratio = float(attrs.get("neg_pos_ratio", 3.0))
    neg_dist_threshold = float(attrs.get("neg_dist_threshold", 0.5))
    b, m = match.shape
    loss = cls_loss.reshape(b, m)
    if loc_loss is not None and bool(attrs.get("mining_type_hard", False)):
        loss = loss + loc_loss.reshape(b, m)
    matched = match >= 0
    num_pos = matched.sum(axis=1)  # [B]
    num_neg = jnp.minimum(
        (num_pos.astype(jnp.float32) * neg_pos_ratio).astype(jnp.int32),
        m - num_pos,
    )
    cand = jnp.where(matched, NEG, loss)
    order = jnp.argsort(-cand, axis=1).astype(jnp.int32)  # best-loss first
    rank = jnp.arange(m, dtype=jnp.int32)[None, :]
    sel = jnp.where(rank < num_neg[:, None], order, -1)
    return {"NegIndices": [sel]}


def _nms_single_class(boxes, scores, iou_thr, score_thr, top_k, normalized):
    """Iterative NMS: top_k rounds of pick-max + suppress. Returns
    (scores_kept [top_k], idx [top_k]) with -1/-inf padding."""
    s = jnp.where(scores > score_thr, scores, NEG)

    def body(state, _):
        s_cur = state
        i = jnp.argmax(s_cur)
        ok = s_cur[i] > NEG / 2
        iou = _iou_matrix(boxes[i][None], boxes, normalized)[0]
        keep_score = s_cur[i]
        s_new = jnp.where(iou > iou_thr, NEG, s_cur)
        s_new = s_new.at[i].set(NEG)
        s_new = jnp.where(ok, s_new, s_cur)
        return s_new, (
            jnp.where(ok, keep_score, NEG),
            jnp.where(ok, i.astype(jnp.int32), -1),
        )

    _, (kept_scores, kept_idx) = lax.scan(body, s, None, length=top_k)
    return kept_scores, kept_idx


@register("multiclass_nms", no_grad=True)
def _multiclass_nms(ctx, ins, attrs):
    """reference detection/multiclass_nms_op.cc. Output is fixed-shape
    [B, keep_top_k, 6] (label, score, x1, y1, x2, y2) padded with -1, plus
    OutLen (the reference encodes counts in LoD)."""
    (bboxes,) = ins["BBoxes"]  # [B, M, 4]
    (scores,) = ins["Scores"]  # [B, C, M]
    bg = int(attrs.get("background_label", 0))
    score_thr = float(attrs.get("score_threshold", 0.0))
    nms_thr = float(attrs.get("nms_threshold", 0.3))
    nms_top_k = int(attrs.get("nms_top_k", 64))
    keep_top_k = int(attrs.get("keep_top_k", 16))
    normalized = bool(attrs.get("normalized", True))
    b, c, m = scores.shape
    top_k = min(nms_top_k, m) if nms_top_k > 0 else m
    if keep_top_k <= 0:
        keep_top_k = c * top_k

    def per_image(boxes_i, scores_i):
        def per_class(cls_scores):
            return _nms_single_class(
                boxes_i, cls_scores, nms_thr, score_thr, top_k, normalized
            )

        ks, ki = jax.vmap(per_class)(scores_i)  # [C, top_k]
        cls_ids = jnp.broadcast_to(
            jnp.arange(c, dtype=jnp.int32)[:, None], (c, top_k)
        )
        # drop background detections
        ks = jnp.where(cls_ids == bg, NEG, ks)
        flat_s = ks.reshape(-1)
        flat_i = ki.reshape(-1)
        flat_c = cls_ids.reshape(-1)
        k = min(keep_top_k, flat_s.shape[0])
        top_s, sel = lax.top_k(flat_s, k)
        sel_box = boxes_i[jnp.maximum(flat_i[sel], 0)]
        sel_cls = flat_c[sel]
        valid = top_s > NEG / 2
        det = jnp.concatenate(
            [
                jnp.where(valid, sel_cls, -1).astype(jnp.float32)[:, None],
                jnp.where(valid, top_s, -1.0)[:, None],
                jnp.where(valid[:, None], sel_box, -1.0),
            ],
            axis=1,
        )  # [k, 6]
        return det, valid.sum().astype(jnp.int32)

    det, cnt = jax.vmap(per_image)(bboxes, scores)
    return {"Out": [det], "OutLen": [cnt]}


@register("polygon_box_transform", no_grad=True)
def _polygon_box_transform(ctx, ins, attrs):
    """reference detection/polygon_box_transform_op.cc: at active cells
    (input > 0 means offset), output = 4*grid_coord + input offset."""
    (x,) = ins["Input"]  # [B, 8k, H, W] offsets
    b, c, h, w = x.shape
    gx = jnp.broadcast_to(jnp.arange(w, dtype=x.dtype)[None, :], (h, w))
    gy = jnp.broadcast_to(jnp.arange(h, dtype=x.dtype)[:, None], (h, w))
    grid = jnp.stack([gx, gy], 0)  # [2, H, W]
    grid_full = jnp.tile(grid, (c // 2, 1, 1))  # [C, H, W] alternating x/y
    return {"Output": [jnp.where(x != 0, 4.0 * grid_full[None] + x, 0.0)]}


# ---------------------------------------------------------------------------
# RoI ops (reference operators/roi_pool_op.h, roi_align_op.h). RoIs are
# padded [B, R, 4] + RoisLen; batch mapping is positional, replacing LoD.
# ---------------------------------------------------------------------------


@register("roi_pool")
def _roi_pool(ctx, ins, attrs):
    (x,) = ins["X"]  # [B, C, H, W]
    (rois,) = ins["ROIs"]  # [B, R, 4]
    (rois_len,) = ins["RoisLen"]
    ph = int(attrs["pooled_height"])
    pw = int(attrs["pooled_width"])
    scale = float(attrs.get("spatial_scale", 1.0))
    b, c_dim, h, w = x.shape
    r = rois.shape[1]

    def one_roi(feat, roi):
        x1 = jnp.round(roi[0] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[2] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        bin_h = rh.astype(jnp.float32) / ph
        bin_w = rw.astype(jnp.float32) / pw

        ys = jnp.arange(h, dtype=jnp.int32)
        xs = jnp.arange(w, dtype=jnp.int32)
        # bin index of each pixel, -1 outside the roi
        yy = jnp.floor((ys - y1) / bin_h).astype(jnp.int32)
        xx = jnp.floor((xs - x1) / bin_w).astype(jnp.int32)
        y_in = (ys >= y1) & (ys <= y2)
        x_in = (xs >= x1) & (xs <= x2)
        yy = jnp.clip(yy, 0, ph - 1)
        xx = jnp.clip(xx, 0, pw - 1)
        bin_idx = yy[:, None] * pw + xx[None, :]  # [H, W]
        inside = y_in[:, None] & x_in[None, :]
        onehot = jax.nn.one_hot(
            jnp.where(inside, bin_idx, ph * pw), ph * pw + 1, dtype=feat.dtype
        )[..., : ph * pw]  # [H, W, ph*pw]
        vals = jnp.where(
            onehot > 0, feat[:, :, :, None], jnp.asarray(NEG, feat.dtype)
        )  # [C, H, W, ph*pw]
        pooled = jnp.max(vals, axis=(1, 2))  # [C, ph*pw]
        pooled = jnp.where(pooled <= NEG / 2, 0.0, pooled)
        return pooled.reshape(c_dim, ph, pw)

    def per_image(feat, rois_i, n_i):
        out = jax.vmap(lambda rr: one_roi(feat, rr))(rois_i)  # [R, C, ph, pw]
        valid = (jnp.arange(r) < n_i).reshape(r, 1, 1, 1)
        return jnp.where(valid, out, 0.0)

    out = jax.vmap(per_image)(x, rois, rois_len.reshape(-1))
    return {"Out": [out]}


@register("roi_align")
def _roi_align(ctx, ins, attrs):
    (x,) = ins["X"]
    (rois,) = ins["ROIs"]
    (rois_len,) = ins["RoisLen"]
    ph = int(attrs["pooled_height"])
    pw = int(attrs["pooled_width"])
    scale = float(attrs.get("spatial_scale", 1.0))
    sampling = int(attrs.get("sampling_ratio", -1))
    # XLA needs a static sampling count; the reference's adaptive
    # ceil(roi/bin) becomes a fixed default of 2 (detectron convention)
    s = sampling if sampling > 0 else 2
    b, c_dim, h, w = x.shape
    r = rois.shape[1]

    def bilinear(feat, yy, xx):
        y0 = jnp.floor(yy).astype(jnp.int32)
        x0 = jnp.floor(xx).astype(jnp.int32)
        y1, x1 = y0 + 1, x0 + 1
        wy1 = yy - y0
        wx1 = xx - x0
        y0c = jnp.clip(y0, 0, h - 1)
        y1c = jnp.clip(y1, 0, h - 1)
        x0c = jnp.clip(x0, 0, w - 1)
        x1c = jnp.clip(x1, 0, w - 1)
        v = (
            feat[:, y0c, x0c] * (1 - wy1) * (1 - wx1)
            + feat[:, y1c, x0c] * wy1 * (1 - wx1)
            + feat[:, y0c, x1c] * (1 - wy1) * wx1
            + feat[:, y1c, x1c] * wy1 * wx1
        )
        inb = (yy >= -1) & (yy <= h) & (xx >= -1) & (xx <= w)
        return jnp.where(inb, v, 0.0)

    def one_roi(feat, roi):
        x1 = roi[0] * scale
        y1 = roi[1] * scale
        x2 = roi[2] * scale
        y2 = roi[3] * scale
        rh = jnp.maximum(y2 - y1, 1.0)
        rw = jnp.maximum(x2 - x1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        py = jnp.arange(ph, dtype=jnp.float32)
        px = jnp.arange(pw, dtype=jnp.float32)
        sy = jnp.arange(s, dtype=jnp.float32)
        # sample grid [ph, s] x [pw, s]
        yy = y1 + py[:, None] * bin_h + (sy[None, :] + 0.5) * bin_h / s
        xx = x1 + px[:, None] * bin_w + (sy[None, :] + 0.5) * bin_w / s
        yv = yy.reshape(-1)  # [ph*s]
        xv = xx.reshape(-1)  # [pw*s]
        grid_y = jnp.repeat(yv, pw * s)
        grid_x = jnp.tile(xv, ph * s)
        vals = bilinear(feat, grid_y, grid_x)  # [C, ph*s*pw*s]
        vals = vals.reshape(c_dim, ph, s, pw, s)
        return vals.mean(axis=(2, 4))

    def per_image(feat, rois_i, n_i):
        out = jax.vmap(lambda rr: one_roi(feat, rr))(rois_i)
        valid = (jnp.arange(r) < n_i).reshape(r, 1, 1, 1)
        return jnp.where(valid, out, 0.0)

    out = jax.vmap(per_image)(x, rois, rois_len.reshape(-1))
    return {"Out": [out]}


@register("yolov3_loss")
def _yolov3_loss(ctx, ins, attrs):
    """reference operators/yolov3_loss_op.h: per-anchor sigmoid xy + raw wh
    regression, BCE objectness with ignore threshold, BCE class loss. Targets
    built by assigning each gt box to its best shape-matched anchor at the
    gt's grid cell."""
    (x,) = ins["X"]  # [B, A*(5+cls), H, W]
    (gtbox,) = ins["GTBox"]  # [B, G, 4] relative (cx, cy, w, h)
    (gtlabel,) = ins["GTLabel"]  # [B, G]
    anchors = [float(v) for v in attrs["anchors"]]
    class_num = int(attrs["class_num"])
    ignore_thresh = float(attrs.get("ignore_thresh", 0.7))
    b, ch, h, w = x.shape
    a = len(anchors) // 2
    g = gtbox.shape[1]
    aw = jnp.asarray(anchors[0::2], jnp.float32)  # anchor widths (pixels)
    ah = jnp.asarray(anchors[1::2], jnp.float32)
    in_w = w * 32.0  # downsample factor 32, reference yolov3_loss_op.h
    in_h = h * 32.0

    p = x.reshape(b, a, 5 + class_num, h, w)
    px = jax.nn.sigmoid(p[:, :, 0])
    py = jax.nn.sigmoid(p[:, :, 1])
    pw_ = p[:, :, 2]
    ph_ = p[:, :, 3]
    pobj = jax.nn.sigmoid(p[:, :, 4])
    pcls = jax.nn.sigmoid(p[:, :, 5:])  # [B, A, cls, H, W]

    valid_gt = (gtbox[..., 2] > 1e-6) & (gtbox[..., 3] > 1e-6)  # [B, G]
    # best anchor per gt by shape IoU (centered boxes)
    gw = gtbox[..., 2] * in_w  # [B, G]
    gh = gtbox[..., 3] * in_h
    inter = jnp.minimum(gw[..., None], aw) * jnp.minimum(gh[..., None], ah)
    union = gw[..., None] * gh[..., None] + aw * ah - inter
    best_a = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=-1)  # [B, G]

    gi = jnp.clip((gtbox[..., 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gtbox[..., 1] * h).astype(jnp.int32), 0, h - 1)
    tx = gtbox[..., 0] * w - gi
    ty = gtbox[..., 1] * h - gj
    tw = jnp.log(jnp.maximum(gw / aw[best_a], 1e-9))
    th = jnp.log(jnp.maximum(gh / ah[best_a], 1e-9))
    # loss weight: bigger boxes get smaller weight (2 - w*h), ref scale
    box_w = 2.0 - gtbox[..., 2] * gtbox[..., 3]

    # scatter gt targets into [B, A, H, W] grids
    def scatter(vals, fill=0.0):
        buf = jnp.full((b, a, h, w), fill, jnp.float32)
        bi = jnp.broadcast_to(jnp.arange(b)[:, None], (b, g))
        return buf.at[bi, best_a, gj, gi].set(
            jnp.where(valid_gt, vals, buf[bi, best_a, gj, gi])
        )

    obj_mask = scatter(jnp.ones((b, g)), 0.0)
    tx_t, ty_t = scatter(tx), scatter(ty)
    tw_t, th_t = scatter(tw), scatter(th)
    w_t = scatter(box_w)

    # class target one-hot [B, A, cls, H, W]
    cls_buf = jnp.zeros((b, a, class_num, h, w), jnp.float32)
    bi = jnp.broadcast_to(jnp.arange(b)[:, None], (b, g))
    lab = jnp.clip(gtlabel.astype(jnp.int32), 0, class_num - 1)
    cls_buf = cls_buf.at[bi, best_a, lab, gj, gi].set(
        jnp.where(valid_gt, 1.0, cls_buf[bi, best_a, lab, gj, gi])
    )

    # ignore mask: predicted boxes with IoU > thresh vs any gt are not
    # penalized as background
    grid_x = (jnp.arange(w, dtype=jnp.float32) + 0.0)[None, None, None, :]
    grid_y = (jnp.arange(h, dtype=jnp.float32) + 0.0)[None, None, :, None]
    bx = (px + grid_x) / w
    by = (py + grid_y) / h
    bw = jnp.exp(pw_) * aw[None, :, None, None] / in_w
    bh = jnp.exp(ph_) * ah[None, :, None, None] / in_h
    pred_boxes = jnp.stack(
        [bx - bw / 2, by - bh / 2, bx + bw / 2, by + bh / 2], axis=-1
    ).reshape(b, a * h * w, 4)
    gt_corners = jnp.stack(
        [
            gtbox[..., 0] - gtbox[..., 2] / 2,
            gtbox[..., 1] - gtbox[..., 3] / 2,
            gtbox[..., 0] + gtbox[..., 2] / 2,
            gtbox[..., 1] + gtbox[..., 3] / 2,
        ],
        axis=-1,
    )  # [B, G, 4]
    iou = _iou_matrix(pred_boxes, gt_corners)  # [B, A*H*W, G]
    iou = jnp.where(valid_gt[:, None, :], iou, 0.0)
    best_iou = iou.max(axis=2).reshape(b, a, h, w)
    noobj_mask = (best_iou < ignore_thresh).astype(jnp.float32) * (1 - obj_mask)

    def bce(pred, tgt, mask):
        pred = jnp.clip(pred, 1e-7, 1 - 1e-7)
        return -(tgt * jnp.log(pred) + (1 - tgt) * jnp.log(1 - pred)) * mask

    loss_xy = (
        bce(px, tx_t, obj_mask * w_t) + bce(py, ty_t, obj_mask * w_t)
    ).sum(axis=(1, 2, 3))
    loss_wh = (
        jnp.square(pw_ - tw_t) * obj_mask * w_t
        + jnp.square(ph_ - th_t) * obj_mask * w_t
    ).sum(axis=(1, 2, 3))
    loss_obj = (
        bce(pobj, obj_mask, obj_mask) + bce(pobj, obj_mask, noobj_mask)
    ).sum(axis=(1, 2, 3))
    loss_cls = bce(pcls, cls_buf, obj_mask[:, :, None]).sum(axis=(1, 2, 3, 4))
    return {"Loss": [loss_xy + loss_wh + loss_obj + loss_cls]}


@register("generate_proposals", no_grad=True)
def _generate_proposals(ctx, ins, attrs):
    """reference detection/generate_proposals_op.cc: decode anchor deltas,
    clip to the image, filter small boxes, topk + NMS. Fixed-capacity output
    [B, post_nms_topN, 4] + count (reference emits LoD)."""
    (scores,) = ins["Scores"]  # [B, A, H, W]
    (deltas,) = ins["BboxDeltas"]  # [B, A*4, H, W]
    (im_info,) = ins["ImInfo"]  # [B, 3] (h, w, scale)
    (anchors,) = ins["Anchors"]  # [H, W, A, 4]
    variances = ins.get("Variances", [None])[0]
    pre_n = int(attrs.get("pre_nms_topN", 256))
    post_n = int(attrs.get("post_nms_topN", 64))
    nms_thr = float(attrs.get("nms_thresh", 0.7))
    min_size = float(attrs.get("min_size", 0.0))
    b, a, h, w = scores.shape

    anc = anchors.reshape(h * w * a, 4)
    var = variances.reshape(h * w * a, 4) if variances is not None else None

    def per_image(sc, dl, info):
        s = jnp.transpose(sc, (1, 2, 0)).reshape(-1)  # HWA order
        d = dl.reshape(a, 4, h, w)
        d = jnp.transpose(d, (2, 3, 0, 1)).reshape(-1, 4)
        if var is not None:
            d = d * var
        pcx, pcy, pw_, ph_ = _center_size(anc, True)
        cx = d[:, 0] * pw_ + pcx
        cy = d[:, 1] * ph_ + pcy
        bw = jnp.exp(jnp.minimum(d[:, 2], 10.0)) * pw_
        bh = jnp.exp(jnp.minimum(d[:, 3], 10.0)) * ph_
        boxes = jnp.stack(
            [cx - bw / 2, cy - bh / 2, cx + bw / 2, cy + bh / 2], axis=1
        )
        boxes = jnp.clip(
            boxes,
            0.0,
            jnp.stack([info[1] - 1, info[0] - 1, info[1] - 1, info[0] - 1]),
        )
        ok = (
            (boxes[:, 2] - boxes[:, 0] >= min_size)
            & (boxes[:, 3] - boxes[:, 1] >= min_size)
        )
        s = jnp.where(ok, s, NEG)
        k = min(pre_n, s.shape[0])
        top_s, top_i = lax.top_k(s, k)
        top_boxes = boxes[top_i]
        kept_s, kept_i = _nms_single_class(
            top_boxes, top_s, nms_thr, NEG / 2, min(post_n, k), False
        )
        out_boxes = top_boxes[jnp.maximum(kept_i, 0)]
        valid = kept_i >= 0
        out_boxes = jnp.where(valid[:, None], out_boxes, -1.0)
        if out_boxes.shape[0] < post_n:
            pad = jnp.full((post_n - out_boxes.shape[0], 4), -1.0)
            out_boxes = jnp.concatenate([out_boxes, pad], 0)
            kept_s = jnp.concatenate(
                [kept_s, jnp.full((post_n - kept_s.shape[0],), NEG)], 0
            )
        return out_boxes, jnp.where(kept_s > NEG / 2, kept_s, -1.0), valid.sum(
        ).astype(jnp.int32)

    boxes, probs, cnt = jax.vmap(per_image)(scores, deltas, im_info)
    return {"RpnRois": [boxes], "RpnRoiProbs": [probs], "RoisLen": [cnt]}


@register("ssd_loss")
def _ssd_loss(ctx, ins, attrs):
    """Fused SSD loss (reference python layers/detection.py ssd_loss, which
    composes iou_similarity → bipartite_match → target_assign →
    mine_hard_examples → smooth_l1 + softmax CE; here one lowering so XLA
    fuses the whole pipeline). Returns per-image loss [B, 1]."""
    (loc,) = ins["Location"]  # [B, M, 4]
    (conf,) = ins["Confidence"]  # [B, M, C]
    (gtbox,) = ins["GTBox"]  # [B, G, 4]
    (gtlabel,) = ins["GTLabel"]  # [B, G, 1] or [B, G]
    (gtlen,) = ins["GTLen"]  # [B]
    (prior,) = ins["PriorBox"]  # [M, 4]
    pb_var = ins.get("PriorBoxVar", [None])[0]
    bg = int(attrs.get("background_label", 0))
    overlap_t = float(attrs.get("overlap_threshold", 0.5))
    neg_ratio = float(attrs.get("neg_pos_ratio", 3.0))
    loc_w = float(attrs.get("loc_loss_weight", 1.0))
    conf_w = float(attrs.get("conf_loss_weight", 1.0))
    match_type = attrs.get("match_type", "per_prediction")
    b, m, _ = loc.shape
    g = gtbox.shape[1]
    c = conf.shape[2]
    glabel = gtlabel.reshape(b, g).astype(jnp.int32)
    glen = gtlen.reshape(-1).astype(jnp.int32)

    pcx, pcy, pw, ph = _center_size(prior, True)

    def per_image(loc_i, conf_i, gt_i, gl_i, n_i):
        gt_valid = jnp.arange(g) < n_i
        iou = _iou_matrix(gt_i, prior)  # [G, M]
        iou = jnp.where(gt_valid[:, None], iou, 0.0)
        match, mdist = _bipartite_match_single(iou)
        if match_type == "per_prediction":
            am = jnp.argmax(iou, axis=0).astype(jnp.int32)
            amd = jnp.max(iou, axis=0)
            take = (match == -1) & (amd >= overlap_t)
            match = jnp.where(take, am, match)
        pos = match >= 0  # [M]
        num_pos = pos.sum()

        # confidence loss
        tgt_label = jnp.where(pos, jnp.take(gl_i, jnp.maximum(match, 0)), bg)
        logp = jax.nn.log_softmax(conf_i, axis=1)  # [M, C]
        cls_loss = -jnp.take_along_axis(
            logp, tgt_label[:, None], axis=1
        ).reshape(m)
        # hard-negative mining
        num_neg = jnp.minimum(
            (num_pos.astype(jnp.float32) * neg_ratio).astype(jnp.int32),
            m - num_pos,
        )
        neg_cand = jnp.where(pos, NEG, cls_loss)
        order = jnp.argsort(-neg_cand)
        rank = jnp.zeros((m,), jnp.int32).at[order].set(jnp.arange(m, dtype=jnp.int32))
        neg = (~pos) & (rank < num_neg)
        conf_loss = jnp.where(pos | neg, cls_loss, 0.0).sum()

        # localization loss (smooth l1 on encoded targets)
        mgt = jnp.take(gt_i, jnp.maximum(match, 0), axis=0)  # [M, 4]
        tcx = (mgt[:, 0] + mgt[:, 2]) / 2
        tcy = (mgt[:, 1] + mgt[:, 3]) / 2
        tw = jnp.maximum(mgt[:, 2] - mgt[:, 0], 1e-8)
        th = jnp.maximum(mgt[:, 3] - mgt[:, 1], 1e-8)
        enc = jnp.stack(
            [
                (tcx - pcx) / pw,
                (tcy - pcy) / ph,
                jnp.log(tw / pw),
                jnp.log(th / ph),
            ],
            axis=1,
        )
        if pb_var is not None:
            enc = enc / pb_var
        diff = jnp.abs(loc_i - enc)
        sl1 = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5).sum(axis=1)
        loc_loss = jnp.where(pos, sl1, 0.0).sum()

        denom = jnp.maximum(num_pos.astype(jnp.float32), 1.0)
        return (conf_w * conf_loss + loc_w * loc_loss) / denom

    loss = jax.vmap(per_image)(loc, conf, gtbox, glabel, glen)
    return {"Loss": [loss.reshape(b, 1)]}


# ---------------------------------------------------------------------------
# training-time target assignment (reference detection/rpn_target_assign_op.cc,
# generate_proposal_labels_op.cc) — fixed-capacity redesign: the reference
# randomly subsamples fg/bg to a quota with dynamic-size index outputs; here
# every anchor/roi gets a label in place (-1 ignore, 0 bg, 1..C fg) and
# per-row weights carry the subsampling quota deterministically (score-ranked
# instead of randomly drawn), so shapes stay static for XLA
# ---------------------------------------------------------------------------


def _box_deltas(src, gt):
    """Encode gt relative to src (the reference's BoxToDelta)."""
    scx, scy, sw, sh = _center_size(src, True)
    gcx, gcy, gw, gh = _center_size(gt, True)
    return jnp.stack(
        [
            (gcx - scx) / jnp.maximum(sw, 1e-6),
            (gcy - scy) / jnp.maximum(sh, 1e-6),
            jnp.log(jnp.maximum(gw, 1e-6) / jnp.maximum(sw, 1e-6)),
            jnp.log(jnp.maximum(gh, 1e-6) / jnp.maximum(sh, 1e-6)),
        ],
        axis=1,
    )


@register("rpn_target_assign", no_grad=True, stochastic=True)
def _rpn_target_assign(ctx, ins, attrs):
    """Per-anchor RPN labels/targets. Inputs: Anchor [N,4], GtBox [B,G,4],
    GtLen [B]. Outputs: TargetLabel [B,N] (-1 ignore / 0 bg / 1 fg),
    TargetBBox [B,N,4] deltas, ScoreWeight/LocWeight [B,N] marking the
    sampled quota rows."""
    (anchors,) = ins["Anchor"]
    (gtboxes,) = ins["GtBox"]
    (gtlen,) = ins["GtLen"]
    pos_thr = float(attrs.get("rpn_positive_overlap", 0.7))
    neg_thr = float(attrs.get("rpn_negative_overlap", 0.3))
    quota = int(attrs.get("rpn_batch_size_per_im", 256))
    fg_frac = float(attrs.get("rpn_fg_fraction", 0.5))
    n = anchors.shape[0]

    def per_image(gt, g_len):
        gmask = jnp.arange(gt.shape[0]) < g_len
        iou = _iou_matrix(anchors, gt) * gmask[None, :].astype(anchors.dtype)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        # anchors that are the best for some VALID gt are fg too (ref :167);
        # accumulate with .max so a padded gt row (argmax lands on anchor 0)
        # can never overwrite a real gt's forced-fg write
        best_per_gt = jnp.argmax(iou, axis=0)
        forced_fg = jnp.zeros((n,), jnp.bool_).at[best_per_gt].max(gmask)
        is_fg = forced_fg | (best_iou >= pos_thr)
        label = jnp.where(is_fg, 1, -1)
        label = jnp.where((best_iou < neg_thr) & ~is_fg, 0, label)
        deltas = _box_deltas(anchors, gt[best_gt])
        n_fg = int(quota * fg_frac)
        fg_rank = lax.top_k(jnp.where(label == 1, best_iou, -1.0), min(n_fg, n))[0]
        fg_cut = fg_rank[-1]
        fg_w = (label == 1) & (best_iou >= jnp.maximum(fg_cut, 0.0))
        n_bg = quota - n_fg
        bg_score = jnp.where(label == 0, -best_iou, -2.0)  # prefer low overlap
        bg_rank = lax.top_k(bg_score, min(n_bg, n))[0]
        bg_w = (label == 0) & (bg_score >= bg_rank[-1])
        return label, deltas, (fg_w | bg_w).astype(anchors.dtype), fg_w.astype(
            anchors.dtype
        )

    label, deltas, sw, lw = jax.vmap(per_image)(
        gtboxes, gtlen.reshape(-1).astype(jnp.int32)
    )
    return {
        "TargetLabel": [label.astype(jnp.int32)],
        "TargetBBox": [deltas],
        "ScoreWeight": [sw],
        "LocWeight": [lw],
    }


@register("generate_proposal_labels", no_grad=True, stochastic=True)
def _generate_proposal_labels(ctx, ins, attrs):
    """Assign class labels + box targets to RoIs (reference
    generate_proposal_labels_op.cc). Inputs: RpnRois [B,R,4], GtClasses
    [B,G], GtBoxes [B,G,4], GtLen [B]. Outputs Rois (passthrough),
    LabelsInt32 [B,R], BboxTargets [B,R,4], BboxInsideWeights /
    BboxOutsideWeights [B,R,4], SampleWeight [B,R]."""
    (rois,) = ins["RpnRois"]
    (gtcls,) = ins["GtClasses"]
    (gtboxes,) = ins["GtBoxes"]
    (gtlen,) = ins["GtLen"]
    fg_thr = float(attrs.get("fg_thresh", 0.5))
    bg_hi = float(attrs.get("bg_thresh_hi", 0.5))
    bg_lo = float(attrs.get("bg_thresh_lo", 0.0))
    quota = int(attrs.get("batch_size_per_im", 512))
    fg_frac = float(attrs.get("fg_fraction", 0.25))
    r = rois.shape[1]

    def per_image(rs, gcls, gbx, g_len):
        gmask = jnp.arange(gbx.shape[0]) < g_len
        valid_roi = rs[:, 2] > rs[:, 0]
        iou = _iou_matrix(rs, gbx) * gmask[None, :].astype(rs.dtype)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        is_fg = (best_iou >= fg_thr) & valid_roi
        is_bg = (best_iou < bg_hi) & (best_iou >= bg_lo) & valid_roi
        labels = jnp.where(is_fg, gcls[best_gt].astype(jnp.int32), 0)
        deltas = _box_deltas(rs, gbx[best_gt])
        n_fg = int(quota * fg_frac)
        fg_rank = lax.top_k(jnp.where(is_fg, best_iou, -1.0), min(n_fg, r))[0]
        fg_w = is_fg & (best_iou >= jnp.maximum(fg_rank[-1], 0.0))
        n_bg = quota - n_fg
        bg_score = jnp.where(is_bg, -best_iou, -2.0)
        bg_rank = lax.top_k(bg_score, min(n_bg, r))[0]
        bg_w = is_bg & (bg_score >= bg_rank[-1])
        inside = jnp.where(fg_w[:, None], 1.0, 0.0) * jnp.ones((1, 4))
        sample_w = (fg_w | bg_w).astype(rs.dtype)
        return labels, deltas, inside, sample_w

    labels, deltas, inside, sample_w = jax.vmap(per_image)(
        rois, gtcls, gtboxes, gtlen.reshape(-1).astype(jnp.int32)
    )
    return {
        "Rois": [rois],
        "LabelsInt32": [labels],
        "BboxTargets": [deltas],
        "BboxInsideWeights": [inside],
        "BboxOutsideWeights": [inside],
        "SampleWeight": [sample_w],
    }


@register("roi_perspective_transform")
def _roi_perspective_transform(ctx, ins, attrs):
    """Warp quadrilateral text regions to axis-aligned crops (reference
    detection/roi_perspective_transform_op.cc): per ROI of 8 coords
    (x1..y4 clockwise), solve the homography mapping the output rect onto the
    quad and bilinear-sample. ROIs ride as [B, R, 8] + RoisLen."""
    (x,) = ins["X"]  # [B, C, H, W]
    (rois,) = ins["ROIs"]  # [B, R, 8]
    oh = int(attrs.get("transformed_height", 8))
    ow = int(attrs.get("transformed_width", 8))
    scale = float(attrs.get("spatial_scale", 1.0))
    b, c, h, w = x.shape

    # output-rect corners in (col,row), clockwise from top-left
    dst = jnp.asarray(
        [[0.0, 0.0], [ow - 1.0, 0.0], [ow - 1.0, oh - 1.0], [0.0, oh - 1.0]]
    )

    def homography(quad):
        # solve the 8 projective params a..h with i=1 from 4 correspondences
        rows = []
        rhs = []
        for k in range(4):
            sx, sy = dst[k, 0], dst[k, 1]
            tx, ty = quad[2 * k] * scale, quad[2 * k + 1] * scale
            rows.append(
                jnp.stack([sx, sy, 1.0, 0.0 * sx, 0.0 * sx, 0.0 * sx, -sx * tx, -sy * tx])
            )
            rhs.append(tx)
            rows.append(
                jnp.stack([0.0 * sx, 0.0 * sx, 0.0 * sx, sx, sy, 1.0, -sx * ty, -sy * ty])
            )
            rhs.append(ty)
        A = jnp.stack(rows)
        bvec = jnp.stack(rhs)
        p = jnp.linalg.solve(A + 1e-8 * jnp.eye(8), bvec)
        return jnp.concatenate([p, jnp.ones((1,))]).reshape(3, 3)

    gy, gx = jnp.meshgrid(jnp.arange(oh, dtype=jnp.float32), jnp.arange(ow, dtype=jnp.float32), indexing="ij")
    ones = jnp.ones_like(gx)
    grid = jnp.stack([gx, gy, ones], axis=-1)  # (oh, ow, 3)

    def warp_one(img, quad):
        m = homography(quad)
        src = grid @ m.T  # (oh, ow, 3)
        sx = src[..., 0] / jnp.maximum(src[..., 2], 1e-8)
        sy = src[..., 1] / jnp.maximum(src[..., 2], 1e-8)
        x0 = jnp.floor(sx)
        y0 = jnp.floor(sy)
        out = jnp.zeros((c, oh, ow), img.dtype)
        for dx in (0, 1):
            for dy in (0, 1):
                xi = x0 + dx
                yi = y0 + dy
                wgt = (1 - jnp.abs(sx - xi)) * (1 - jnp.abs(sy - yi))
                inb = (xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1)
                xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
                yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
                out = out + img[:, yc, xc] * (wgt * inb)[None]
        return out

    def per_image(img, img_rois):
        return jax.vmap(lambda q: warp_one(img, q))(img_rois)

    out = jax.vmap(per_image)(x, rois)  # [B, R, C, oh, ow]
    return {"Out": [out]}


# detection_map runs on the HOST (reference registers it CPU-only too —
# detection/detection_map_op.cc has no CUDA kernel): mAP is a metric over
# variable-length match lists, a poor fit for static-shape XLA, and never on
# the training hot path. Inputs ride padded: DetectRes [B,N,6]
# ([label, score, x1, y1, x2, y2], rows with label<0 ignored), Label
# [B,G,5] ([label, x1, y1, x2, y2], label<0 padding).


def _detection_map_host(op, scope):
    import numpy as np

    from ..evaluator import DetectionMAP as _MAP

    dets = np.asarray(scope.find_var(op.input("DetectRes")[0]))
    labels = np.asarray(scope.find_var(op.input("Label")[0]))
    ev = _MAP(
        class_num=int(op.attrs.get("class_num", 0) or 0) or None,
        background_label=int(op.attrs.get("background_label", 0)),
        overlap_threshold=float(op.attrs.get("overlap_threshold", 0.5)),
        ap_version=op.attrs.get("ap_type", op.attrs.get("ap_version", "integral")),
    )
    for img_dets, img_gts in zip(dets, labels):
        valid_d = img_dets[img_dets[:, 0] >= 0]
        valid_g = img_gts[img_gts[:, 0] >= 0]
        ev.update(valid_d, valid_g[:, 0], valid_g[:, 1:5])
    import jax.numpy as jnp

    scope.set_var(op.output("MAP")[0], jnp.asarray([ev.eval()], jnp.float32))


from .registry import register_host as _register_host  # noqa: E402

_register_host("detection_map")(_detection_map_host)
