"""Beam-search decode ops.

Reference analogs: paddle/fluid/operators/beam_search_op.cc (select beam_size
best candidates per source from each beam's top-K expansions, retiring beams
that emit end_id) and beam_search_decode_op.cc (walk the per-step selection
arrays backward to reconstruct full hypotheses).

TPU-first redesign: the reference threads parentage through LoD levels on
CPU-side tensors; here beams live in a dense [batch*beam, ...] layout and
`beam_search` emits an explicit ParentIdx tensor (flat indices into the
batch*beam axis). Callers gather their decoder state with it each step and
write ids/scores/parents into tensor arrays; `beam_search_decode` backtracks
those arrays inside the same XLA computation — no host round-trips in the
decode loop.

First-step convention: all beams of a source start identical, so initialize
pre_scores to [0, -inf, -inf, ...] per source (kInitialScore trick, matching
the reference's single-active-beam initial LoD).
"""

import jax.numpy as jnp
from jax import lax

from .registry import register


def _noop_infer(op, block):
    """Tensor-array inputs are (buffer, size) pairs that flat var metadata
    cannot describe; output shapes come from the first trace. (Documented in
    control_flow_ops.NOOP_INFER_REASONS with the other array-kind escapes.)"""
    return None


def _beam_search_decode_abstract(actx, op, ins):
    """Analyzer transfer: recover [B, beam, T] from the Ids ARRAY fact's
    buffer shape, mirroring the lowering's reshape arithmetic."""
    from .control_flow_ops import _vf

    arr = ins["Ids"][0]
    beam = int(op.attrs["beam_size"])
    shape = arr.shape if arr is not None and arr.kind == "array" else None
    if (
        shape is None
        or len(shape) < 2
        or not isinstance(shape[0], int)
        or not isinstance(shape[1], int)
    ):
        return {
            "SentenceIds": [actx.opaque()],
            "SentenceScores": [actx.opaque()],
            "SentenceLength": [actx.opaque()],
        }
    t_cap, n = shape[0], shape[1]
    b = n // beam
    return {
        "SentenceIds": [_vf(shape=(b, beam, t_cap), dtype="int64")],
        "SentenceScores": [_vf(shape=(b, beam), dtype="float32")],
        "SentenceLength": [_vf(shape=(b, beam), dtype="int32")],
    }


NEG_INF = -1e9


@register("beam_search", no_grad=True)
def _beam_search(ctx, ins, attrs):
    (pre_ids,) = ins["pre_ids"]  # [N, 1] int
    (pre_scores,) = ins["pre_scores"]  # [N, 1] float
    (ids,) = ins["ids"]  # [N, K] int candidate tokens per beam
    (scores,) = ins["scores"]  # [N, K] float ACCUMULATED scores
    beam_size = int(attrs["beam_size"])
    end_id = int(attrs["end_id"])
    n, k = ids.shape
    b = n // beam_size

    pre_id = pre_ids.reshape(n).astype(jnp.int32)
    pre_score = pre_scores.reshape(n).astype(jnp.float32)
    finished = pre_id == end_id

    col = jnp.arange(k, dtype=jnp.int32)[None, :]
    # a finished beam contributes exactly one candidate: (end_id, pre_score)
    cand_scores = jnp.where(
        finished[:, None],
        jnp.where(col == 0, pre_score[:, None], NEG_INF),
        scores.astype(jnp.float32),
    )
    cand_ids = jnp.where(finished[:, None], end_id, ids.astype(jnp.int32))

    flat_scores = cand_scores.reshape(b, beam_size * k)
    flat_ids = cand_ids.reshape(b, beam_size * k)
    top_scores, top_idx = lax.top_k(flat_scores, beam_size)  # [B, beam]
    sel_ids = jnp.take_along_axis(flat_ids, top_idx, axis=1)
    parent_beam = top_idx // k
    parent_global = parent_beam + jnp.arange(b, dtype=jnp.int32)[:, None] * beam_size

    return {
        "selected_ids": [sel_ids.reshape(n, 1).astype(jnp.int64)],
        "selected_scores": [top_scores.reshape(n, 1)],
        "parent_idx": [parent_global.reshape(n)],
    }


@register(
    "beam_search_decode",
    no_grad=True,
    infer_shape=_noop_infer,
    abstract_eval=_beam_search_decode_abstract,
)
def _beam_search_decode(ctx, ins, attrs):
    """Backtrack (ids, parents) step arrays into [B, beam, T] hypotheses,
    best beam first per source."""
    (ids_arr,) = ins["Ids"]  # tensor array: (buffer [T, N, 1], size)
    (scores_arr,) = ins["Scores"]  # (buffer [T, N, 1], size)
    parents_in = ins.get("Parents", [None])[0]  # (buffer [T, N], size) | None
    beam_size = int(attrs["beam_size"])
    end_id = int(attrs["end_id"])

    ids_buf, size = ids_arr
    scores_buf, _ = scores_arr
    t_cap, n = ids_buf.shape[0], ids_buf.shape[1]
    b = n // beam_size
    ids_buf = ids_buf.reshape(t_cap, n).astype(jnp.int32)
    scores_buf = scores_buf.reshape(t_cap, n).astype(jnp.float32)
    if parents_in is None:
        parents_buf = jnp.broadcast_to(
            jnp.arange(n, dtype=jnp.int32)[None, :], (t_cap, n)
        )
    else:
        parents_buf = parents_in[0].reshape(t_cap, n).astype(jnp.int32)

    size = jnp.asarray(size, jnp.int32).reshape(())
    t_idx = jnp.arange(t_cap, dtype=jnp.int32)

    # walk backward from the last valid step; steps >= size pass through
    def back(carry, sc):
        beam_idx = carry  # [N] flat slot each output row currently tracks
        t, step_ids, step_parents = sc
        valid = t < size
        tok = jnp.where(valid, step_ids[beam_idx], end_id)
        nxt = jnp.where(valid, step_parents[beam_idx], beam_idx)
        return nxt, tok

    init = jnp.arange(n, dtype=jnp.int32)
    _, toks = lax.scan(
        back, init, (t_idx, ids_buf, parents_buf), reverse=True
    )  # toks: [T, N]
    seq = jnp.swapaxes(toks, 0, 1).reshape(b, beam_size, t_cap)

    last = jnp.maximum(size - 1, 0)
    final_scores = scores_buf[last].reshape(b, beam_size)

    # rank beams best-first per source
    order = jnp.argsort(-final_scores, axis=1)
    seq = jnp.take_along_axis(seq, order[:, :, None], axis=1)
    final_scores = jnp.take_along_axis(final_scores, order, axis=1)

    # hypothesis length: position of first end_id (inclusive) among the VALID
    # steps, else size (backtracking fills steps >= size with end_id)
    is_end = (seq == end_id) & (t_idx[None, None, :] < size)
    first_end = jnp.argmax(is_end, axis=2).astype(jnp.int32)
    has_end = is_end.any(axis=2)
    lens = jnp.where(has_end, first_end + 1, size)

    return {
        "SentenceIds": [seq.astype(jnp.int64)],
        "SentenceScores": [final_scores],
        "SentenceLength": [lens],
    }
