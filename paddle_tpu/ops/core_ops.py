"""Core operator lowerings (dense math, NN, tensor manipulation, optimizers).

Reference analog: paddle/fluid/operators/*.cc/.cu (336 registered ops, §2.5 of
SURVEY.md). Each lowering is a pure JAX function over slot-keyed arrays; the
executor stitches a whole block of them into ONE jitted XLA computation, so
elementwise chains fuse into the adjacent matmuls/convs on the MXU instead of
being separate kernel launches as in the reference's per-op dispatch loop
(reference framework/executor.cc:389-396).

Gradients: nearly all ops rely on the registry's generic jax.vjp grad
(registry._make_generic_grad). Custom grads exist only where vjp-replay is
wrong (dropout must reuse its sampled Mask).

Dtype policy (TPU-first): float64→float32 and int64→int32 are canonicalized at
the framework boundary (TPUs have no fast f64/i64 path), mirroring JAX's own
default dtype canonicalization.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import framework
from .registry import (
    LowerCtx,
    bcast_y,
    register,
    register_no_lower,
)

# ---------------------------------------------------------------------------
# executor-handled markers
# ---------------------------------------------------------------------------

register_no_lower("feed")
register_no_lower("fetch")


def _dtype(attr_dtype):
    return jnp.dtype(framework.convert_np_dtype(attr_dtype))


def _rng(ctx, attrs):
    seed = int(attrs.get("seed", 0) or 0)
    if seed:
        return jax.random.key(seed)
    return ctx.next_rng()


# ---------------------------------------------------------------------------
# creation / random ops (reference: fill_constant_op.cc, uniform_random_op.cc,
# gaussian_random_op.cc, truncated_gaussian_random_op.cc)
# ---------------------------------------------------------------------------


@register("fill_constant", no_grad=True)
def _fill_constant(ctx, ins, attrs):
    shape = [int(s) for s in attrs["shape"]]
    dt = _dtype(attrs.get("dtype", "float32"))
    return {"Out": [jnp.full(shape, attrs.get("value", 0.0), dtype=dt)]}


@register("fill_constant_batch_size_like", no_grad=True)
def _fill_constant_bsl(ctx, ins, attrs):
    (ref,) = ins["Input"]
    shape = [int(s) for s in attrs["shape"]]
    in_idx = int(attrs.get("input_dim_idx", 0))
    out_idx = int(attrs.get("output_dim_idx", 0))
    shape[out_idx] = ref.shape[in_idx]
    dt = _dtype(attrs.get("dtype", "float32"))
    return {"Out": [jnp.full(shape, attrs.get("value", 0.0), dtype=dt)]}


@register("fill_zeros_like", no_grad=True)
def _fill_zeros_like(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [jnp.zeros_like(x)]}


@register("uniform_random", no_grad=True, stochastic=True)
def _uniform_random(ctx, ins, attrs):
    shape = [int(s) for s in attrs["shape"]]
    dt = _dtype(attrs.get("dtype", "float32"))
    out = jax.random.uniform(
        _rng(ctx, attrs),
        shape,
        dtype=jnp.float32,
        minval=attrs.get("min", -1.0),
        maxval=attrs.get("max", 1.0),
    )
    return {"Out": [out.astype(dt)]}


@register("gaussian_random", no_grad=True, stochastic=True)
def _gaussian_random(ctx, ins, attrs):
    shape = [int(s) for s in attrs["shape"]]
    dt = _dtype(attrs.get("dtype", "float32"))
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * jax.random.normal(
        _rng(ctx, attrs), shape, dtype=jnp.float32
    )
    return {"Out": [out.astype(dt)]}


@register("truncated_gaussian_random", no_grad=True, stochastic=True)
def _truncated_gaussian_random(ctx, ins, attrs):
    shape = [int(s) for s in attrs["shape"]]
    dt = _dtype(attrs.get("dtype", "float32"))
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * jax.random.truncated_normal(
        _rng(ctx, attrs), -2.0, 2.0, shape, dtype=jnp.float32
    )
    return {"Out": [out.astype(dt)]}


@register("assign_value", no_grad=True)
def _assign_value(ctx, ins, attrs):
    dt = _dtype(attrs.get("dtype", "float32"))
    vals = np.asarray(attrs["values"]).reshape([int(s) for s in attrs["shape"]])
    return {"Out": [jnp.asarray(vals, dtype=dt)]}


@register("assign")
def _assign(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [x]}


@register("cast")
def _cast(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [x.astype(_dtype(attrs["out_dtype"]))]}


@register("shape", no_grad=True)
def _shape(ctx, ins, attrs):
    (x,) = ins["Input"]
    return {"Out": [jnp.asarray(x.shape, dtype=jnp.int32)]}


# ---------------------------------------------------------------------------
# dense math (reference: mul_op.cc, matmul_op.cc, operators/math/blas.h — on
# TPU these land on the MXU via XLA dot_general)
# ---------------------------------------------------------------------------


def _fp8_matmul_taken(x, y):
    """FLAGS_fp8_matmul dtype policy for the dense matmul lowerings: floating
    operands contract as float8_e4m3fn with f32 accumulation
    (pallas_kernels.fp8_matmul). Integer/bool operands keep the native path
    regardless of the flag."""
    from .. import flags as _flags

    if not _flags.get_flags("fp8_matmul")["fp8_matmul"]:
        return False
    return jnp.issubdtype(x.dtype, jnp.floating) and jnp.issubdtype(
        y.dtype, jnp.floating
    )


@register("mul")
def _mul(ctx, ins, attrs):
    (x,) = ins["X"]
    (y,) = ins["Y"]
    xnc = int(attrs.get("x_num_col_dims", 1))
    ync = int(attrs.get("y_num_col_dims", 1))
    x2 = x.reshape((int(np.prod(x.shape[:xnc])), -1))
    y2 = y.reshape((int(np.prod(y.shape[:ync])), -1))
    if _fp8_matmul_taken(x2, y2):
        from .pallas_kernels import fp8_matmul

        out = fp8_matmul(x2, y2)
    else:
        out = x2 @ y2
    out_shape = tuple(x.shape[:xnc]) + tuple(y.shape[ync:])
    return {"Out": [out.reshape(out_shape)]}


@register("matmul")
def _matmul(ctx, ins, attrs):
    (x,) = ins["X"]
    (y,) = ins["Y"]
    tx, ty = attrs.get("transpose_X", False), attrs.get("transpose_Y", False)
    alpha = attrs.get("alpha", 1.0)
    if x.ndim == 1:
        x = x[None, :]
    if y.ndim == 1:
        y = y[:, None]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    if _fp8_matmul_taken(x, y):
        from .pallas_kernels import fp8_matmul

        out = fp8_matmul(x, y)
    else:
        out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * jnp.asarray(alpha, out.dtype)
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# elementwise binary with paddle axis-broadcast
# (reference: operators/elementwise/elementwise_op_function.h)
# ---------------------------------------------------------------------------


def _register_elementwise(name, fn):
    @register(name)
    def _lower(ctx, ins, attrs, _fn=fn):
        (x,) = ins["X"]
        (y,) = ins["Y"]
        y = bcast_y(x, y, int(attrs.get("axis", -1)))
        return {"Out": [_fn(x, y)]}


_register_elementwise("elementwise_add", jnp.add)
_register_elementwise("elementwise_sub", jnp.subtract)
_register_elementwise("elementwise_mul", jnp.multiply)
_register_elementwise("elementwise_div", jnp.divide)
_register_elementwise("elementwise_min", jnp.minimum)
_register_elementwise("elementwise_max", jnp.maximum)
_register_elementwise("elementwise_pow", jnp.power)
_register_elementwise("elementwise_mod", jnp.mod)
_register_elementwise("elementwise_floordiv", jnp.floor_divide)


@register("sum")
def _sum(ctx, ins, attrs):
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


@register("scale")
def _scale(ctx, ins, attrs):
    (x,) = ins["X"]
    s = jnp.asarray(attrs.get("scale", 1.0), x.dtype)
    b = jnp.asarray(attrs.get("bias", 0.0), x.dtype)
    if attrs.get("bias_after_scale", True):
        return {"Out": [x * s + b]}
    return {"Out": [(x + b) * s]}


@register("increment")
def _increment(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [x + jnp.asarray(attrs.get("step", 1.0), x.dtype)]}


@register("clip")
def _clip(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [jnp.clip(x, attrs["min"], attrs["max"])]}


@register("clip_by_norm")
def _clip_by_norm(ctx, ins, attrs):
    (x,) = ins["X"]
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(x.astype(jnp.float32) ** 2))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": [(x.astype(jnp.float32) * scale).astype(x.dtype)]}


@register("squared_l2_norm")
def _squared_l2_norm(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [jnp.sum(x.astype(jnp.float32) ** 2).reshape((1,)).astype(x.dtype)]}


# ---------------------------------------------------------------------------
# activations (reference: activation_op.cc — ~20 activations)
# ---------------------------------------------------------------------------


def _register_act(name, fn):
    @register(name)
    def _lower(ctx, ins, attrs, _fn=fn):
        (x,) = ins["X"]
        return {"Out": [_fn(x, attrs)]}


_register_act("relu", lambda x, a: jnp.maximum(x, 0))
_register_act("sigmoid", lambda x, a: jax.nn.sigmoid(x))
_register_act("logsigmoid", lambda x, a: jax.nn.log_sigmoid(x))
_register_act("tanh", lambda x, a: jnp.tanh(x))
_register_act("tanh_shrink", lambda x, a: x - jnp.tanh(x))
_register_act("sqrt", lambda x, a: jnp.sqrt(x))
_register_act("abs", lambda x, a: jnp.abs(x))
_register_act("ceil", lambda x, a: jnp.ceil(x))
_register_act("floor", lambda x, a: jnp.floor(x))
_register_act("cos", lambda x, a: jnp.cos(x))
_register_act("sin", lambda x, a: jnp.sin(x))
_register_act("round", lambda x, a: jnp.round(x))
_register_act("reciprocal", lambda x, a: 1.0 / x)
_register_act("exp", lambda x, a: jnp.exp(x))
_register_act("log", lambda x, a: jnp.log(x))
_register_act("square", lambda x, a: jnp.square(x))
_register_act("softplus", lambda x, a: jax.nn.softplus(x))
_register_act("softsign", lambda x, a: jax.nn.soft_sign(x))
_register_act("softshrink", lambda x, a: jnp.sign(x) * jnp.maximum(jnp.abs(x) - a.get("lambda", 0.5), 0))
_register_act("hard_shrink", lambda x, a: jnp.where(jnp.abs(x) > a.get("threshold", 0.5), x, 0))
_register_act("brelu", lambda x, a: jnp.clip(x, a.get("t_min", 0.0), a.get("t_max", 24.0)))
_register_act("leaky_relu", lambda x, a: jnp.where(x >= 0, x, x * a.get("alpha", 0.02)))
_register_act(
    "soft_relu",
    lambda x, a: jnp.log1p(jnp.exp(jnp.clip(x, -a.get("threshold", 40.0), a.get("threshold", 40.0)))),
)
_register_act("elu", lambda x, a: jnp.where(x >= 0, x, a.get("alpha", 1.0) * (jnp.exp(x) - 1)))
_register_act("relu6", lambda x, a: jnp.clip(x, 0, a.get("threshold", 6.0)))
_register_act("pow", lambda x, a: jnp.power(x, a.get("factor", 1.0)))
_register_act(
    "stanh",
    lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(a.get("scale_a", 0.67) * x),
)
_register_act(
    "hard_sigmoid",
    lambda x, a: jnp.clip(a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0),
)
_register_act("swish", lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x))
_register_act("gelu", lambda x, a: jax.nn.gelu(x, approximate=False))
_register_act(
    "thresholded_relu", lambda x, a: jnp.where(x > a.get("threshold", 1.0), x, 0)
)
_register_act("rsqrt", lambda x, a: lax.rsqrt(x))
_register_act("sign", lambda x, a: jnp.sign(x))


@register("prelu")
def _prelu(ctx, ins, attrs):
    (x,) = ins["X"]
    (alpha,) = ins["Alpha"]
    mode = attrs.get("mode", "all")
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    elif mode == "all":
        alpha = alpha.reshape(())
    return {"Out": [jnp.where(x >= 0, x, x * alpha)]}


# ---------------------------------------------------------------------------
# softmax / losses (reference: softmax_op.cc, softmax_with_cross_entropy_op.cc,
# cross_entropy_op.cc, mean_op.cc, huber/smooth-l1/log/hinge losses)
# ---------------------------------------------------------------------------


@register("softmax")
def _softmax(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [jax.nn.softmax(x, axis=-1)]}


@register("log_softmax")
def _log_softmax(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [jax.nn.log_softmax(x, axis=int(attrs.get("axis", -1)))]}


def _softmax_ce_grad_maker(op, block, grad_map):
    outputs = {}
    logits_g = grad_map.get(op.input("Logits")[0])
    if logits_g:
        outputs["Logits@GRAD"] = [logits_g]
    # soft labels are float and may carry gradient (e.g. via label_smooth)
    lbl_g = (
        grad_map.get(op.input("Label")[0])
        if op.attrs.get("soft_label", False)
        else None
    )
    if lbl_g:
        outputs["Label@GRAD"] = [lbl_g]
    if not outputs:
        return []
    inputs = {
        "Softmax": [op.output("Softmax")[0]],
        "Label": [op.input("Label")[0]],
    }
    # Loss may carry no gradient (e.g. only the Softmax output is consumed
    # downstream); the grad lowering treats a missing dloss as zeros
    loss_g = grad_map.get(op.output("Loss")[0])
    if loss_g:
        inputs["Loss@GRAD"] = [loss_g]
    # a downstream consumer of the Softmax output contributes through the
    # softmax Jacobian as well (grad_map only has the entry when it flows)
    sm_g = grad_map.get(op.output("Softmax")[0])
    if sm_g:
        inputs["Softmax@GRAD"] = [sm_g]
    return [
        {
            "type": "softmax_with_cross_entropy_grad",
            "inputs": inputs,
            "outputs": outputs,
            "attrs": {k: v for k, v in op.attrs.items()},
        }
    ]


@register("softmax_with_cross_entropy", grad=_softmax_ce_grad_maker)
def _softmax_with_ce(ctx, ins, attrs):
    """Numerically-safe CE in the INPUT dtype: under bf16 mixed precision the
    [N, V] tensors stay bf16 in HBM while the log-sum-exp accumulates in f32
    (the f32 intermediates live only inside the XLA fusion). Loss is computed
    from the log-partition z = max + lse and a gather on the raw logits —
    never from a materialized [N, V] log-softmax (for a 32k vocab the f32
    [N, V] passes were ~11 ms/step of pure HBM traffic on the bench chip,
    round-4 per-HLO audit)."""
    (logits,) = ins["Logits"]
    (label,) = ins["Label"]
    m = jnp.max(logits, axis=-1, keepdims=True)
    sh = (logits - m).astype(jnp.float32)
    lse = jnp.log(jnp.sum(jnp.exp(sh), axis=-1, keepdims=True))  # f32 [N,1]
    softmax = jnp.exp(sh - lse).astype(logits.dtype)
    z = m.astype(jnp.float32) + lse  # log partition
    if attrs.get("soft_label", False):
        # sum_j label_j * (z - logit_j), without materializing log-softmax
        s_lbl = jnp.sum(label, axis=-1, keepdims=True, dtype=jnp.float32)
        s_ll = jnp.sum(
            label.astype(jnp.float32) * logits.astype(jnp.float32),
            axis=-1,
            keepdims=True,
        )
        loss = z * s_lbl - s_ll
    else:
        lbl = label.reshape(label.shape[:-1]).astype(jnp.int32)
        picked = jnp.take_along_axis(logits, lbl[..., None], axis=-1).astype(
            jnp.float32
        )
        eps = float(attrs.get("smooth_eps", 0.0) or 0.0)
        if eps:
            # exact uniform label smoothing WITHOUT the [N, V] one-hot the
            # reference pipeline materializes (label_smooth + soft_label CE):
            # sum_j smooth_j·(−logp_j) with smooth = ε/V + (1−ε)δ_y reduces
            # to (1−ε)·(z−logit_y) + ε·(z − mean_j logit_j)
            mean_l = jnp.mean(
                logits.astype(jnp.float32), axis=-1, keepdims=True
            )
            loss = (1.0 - eps) * (z - picked) + eps * (z - mean_l)
        else:
            loss = z - picked
        ignore = int(attrs.get("ignore_index", -100))
        loss = jnp.where(lbl[..., None] == ignore, 0.0, loss)
    return {"Softmax": [softmax], "Loss": [loss.astype(logits.dtype)]}


@register("softmax_with_cross_entropy_grad", no_grad=True)
def _softmax_with_ce_grad(ctx, ins, attrs):
    """Closed-form CE backward from the SAVED Softmax (reference
    softmax_with_cross_entropy_op.h CrossEntropyGrad): dlogits =
    dloss · (softmax − target), no forward recompute. Kept in the softmax
    dtype, one-hot built by iota compare (no scatter), and wrapped in an
    optimization_barrier so XLA materializes the [N, V] gradient ONCE
    instead of recomputing it inside both the dW and dX consumer fusions
    (measured duplication cost ~8 ms/step on the bench transformer,
    round-4 audit)."""
    dloss = ins.get("Loss@GRAD", [None])[0]  # [N, 1] or absent (zeros)
    (softmax,) = ins["Softmax"]  # [N, V]
    (label,) = ins["Label"]
    dsm = ins.get("Softmax@GRAD", [None])[0]
    v = softmax.shape[-1]
    result = {}
    if attrs.get("soft_label", False):
        s_lbl = jnp.sum(label, axis=-1, keepdims=True).astype(softmax.dtype)
        d = softmax * s_lbl - label.astype(softmax.dtype)
        # dloss/dlabel_j = −logp_j, from the saved softmax
        neg_logp = -jnp.log(jnp.maximum(softmax.astype(jnp.float32), 1e-38))
        dl32 = (
            dloss.astype(jnp.float32)
            if dloss is not None
            else jnp.zeros(softmax.shape[:-1] + (1,), jnp.float32)
        )
        result["Label@GRAD"] = [(dl32 * neg_logp).astype(label.dtype)]
    else:
        lbl = label.reshape(label.shape[:-1]).astype(jnp.int32)
        onehot = (
            lax.broadcasted_iota(jnp.int32, softmax.shape, softmax.ndim - 1)
            == lbl[..., None]
        )
        eps = float(attrs.get("smooth_eps", 0.0) or 0.0)
        if eps:
            tgt = (1.0 - eps) * onehot.astype(jnp.float32) + eps / v
            d = (softmax.astype(jnp.float32) - tgt).astype(softmax.dtype)
        else:
            d = softmax - onehot.astype(softmax.dtype)
        ignore = int(attrs.get("ignore_index", -100))
        d = jnp.where((lbl != ignore)[..., None], d, 0)
    out = d * dloss.astype(d.dtype) if dloss is not None else jnp.zeros_like(softmax)
    if dsm is not None:
        # Jacobian of softmax applied to the Softmax output's own cotangent:
        # Jᵀ dS = s ⊙ (dS − ⟨dS, s⟩)
        s32 = softmax.astype(jnp.float32)
        dsm32 = dsm.astype(jnp.float32)
        inner = jnp.sum(dsm32 * s32, axis=-1, keepdims=True)
        out = out + (s32 * (dsm32 - inner)).astype(out.dtype)
    result["Logits@GRAD"] = [lax.optimization_barrier(out)]
    return result


@register("cross_entropy")
def _cross_entropy(ctx, ins, attrs):
    (x,) = ins["X"]
    (label,) = ins["Label"]
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, 1e-20)), axis=-1, keepdims=True)
    else:
        lbl = label.reshape(label.shape[:-1]).astype(jnp.int32)
        picked = jnp.take_along_axis(x, lbl[..., None], axis=-1)
        loss = -jnp.log(jnp.maximum(picked, 1e-20))
    return {"Y": [loss]}


@register("mean")
def _mean(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [jnp.mean(x).reshape((1,))]}


@register("square_error_cost")
def _square_error_cost(ctx, ins, attrs):
    (x,) = ins["X"]
    (y,) = ins["Y"]
    return {"Out": [jnp.square(x - y)]}


@register("smooth_l1_loss")
def _smooth_l1(ctx, ins, attrs):
    (x,) = ins["X"]
    (y,) = ins["Y"]
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    diff = x - y
    if "InsideWeight" in ins:
        diff = diff * ins["InsideWeight"][0]
    ad = jnp.abs(diff)
    val = jnp.where(ad < 1.0 / s2, 0.5 * s2 * diff * diff, ad - 0.5 / s2)
    if "OutsideWeight" in ins:
        val = val * ins["OutsideWeight"][0]
    out = jnp.sum(val.reshape(val.shape[0], -1), axis=1, keepdims=True)
    return {"Out": [out], "Diff": [diff]}


@register("log_loss")
def _log_loss(ctx, ins, attrs):
    (p,) = ins["Predicted"]
    (l,) = ins["Labels"]
    eps = attrs.get("epsilon", 1e-4)
    out = -l * jnp.log(p + eps) - (1 - l) * jnp.log(1 - p + eps)
    return {"Loss": [out]}


@register("huber_loss")
def _huber_loss(ctx, ins, attrs):
    (x,) = ins["X"]
    (y,) = ins["Y"]
    delta = attrs.get("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    out = jnp.where(ar <= delta, 0.5 * r * r, delta * (ar - 0.5 * delta))
    return {"Out": [out], "Residual": [r]}


@register("hinge_loss")
def _hinge_loss(ctx, ins, attrs):
    (logits,) = ins["Logits"]
    (labels,) = ins["Labels"]
    return {"Loss": [jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits)]}


@register("sigmoid_cross_entropy_with_logits")
def _sigmoid_ce(ctx, ins, attrs):
    (x,) = ins["X"]
    (label,) = ins["Label"]
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = attrs.get("ignore_index", -100)
    loss = jnp.where(label == ignore, 0.0, loss)
    return {"Out": [loss]}


# ---------------------------------------------------------------------------
# reductions / argmax / comparisons (reference: reduce_ops/, compare_op.cc,
# logical_op.cc, arg_max_op.cc, top_k_op.cc)
# ---------------------------------------------------------------------------


def _register_reduce(name, fn):
    @register(name)
    def _lower(ctx, ins, attrs, _fn=fn):
        (x,) = ins["X"]
        dims = attrs.get("dim", [0])
        if isinstance(dims, int):
            dims = [dims]
        keep = bool(attrs.get("keep_dim", False))
        if attrs.get("reduce_all", False):
            out = _fn(x, axis=None, keepdims=False).reshape((1,))
        else:
            axes = tuple(d % x.ndim for d in dims)
            out = _fn(x, axis=axes, keepdims=keep)
            if out.ndim == 0:
                out = out.reshape((1,))
        return {"Out": [out]}


_register_reduce("reduce_sum", jnp.sum)
_register_reduce("reduce_mean", jnp.mean)
_register_reduce("reduce_max", jnp.max)
_register_reduce("reduce_min", jnp.min)
_register_reduce("reduce_prod", jnp.prod)


def _register_compare(name, fn):
    @register(name, no_grad=True)
    def _lower(ctx, ins, attrs, _fn=fn):
        (x,) = ins["X"]
        (y,) = ins["Y"]
        y = bcast_y(x, y, int(attrs.get("axis", -1)))
        return {"Out": [_fn(x, y)]}


_register_compare("less_than", jnp.less)
_register_compare("less_equal", jnp.less_equal)
_register_compare("greater_than", jnp.greater)
_register_compare("greater_equal", jnp.greater_equal)
_register_compare("equal", jnp.equal)
_register_compare("not_equal", jnp.not_equal)


def _register_logical(name, fn, unary=False):
    @register(name, no_grad=True)
    def _lower(ctx, ins, attrs, _fn=fn, _unary=unary):
        (x,) = ins["X"]
        if _unary:
            return {"Out": [_fn(x)]}
        (y,) = ins["Y"]
        return {"Out": [_fn(x, y)]}


_register_logical("logical_and", jnp.logical_and)
_register_logical("logical_or", jnp.logical_or)
_register_logical("logical_xor", jnp.logical_xor)
_register_logical("logical_not", jnp.logical_not, unary=True)


@register("arg_max", no_grad=True)
def _arg_max(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [jnp.argmax(x, axis=int(attrs.get("axis", -1))).astype(jnp.int32)]}


@register("arg_min", no_grad=True)
def _arg_min(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [jnp.argmin(x, axis=int(attrs.get("axis", -1))).astype(jnp.int32)]}


@register("top_k", no_grad=True)
def _top_k(ctx, ins, attrs):
    (x,) = ins["X"]
    k = int(attrs["k"])
    vals, idx = lax.top_k(x, k)
    return {"Out": [vals], "Indices": [idx.astype(jnp.int32)]}


@register("argsort", no_grad=True)
def _argsort(ctx, ins, attrs):
    (x,) = ins["X"]
    axis = int(attrs.get("axis", -1))
    idx = jnp.argsort(x, axis=axis).astype(jnp.int32)
    out = jnp.sort(x, axis=axis)
    return {"Out": [out], "Indices": [idx]}


@register("cumsum")
def _cumsum(ctx, ins, attrs):
    (x,) = ins["X"]
    axis = int(attrs.get("axis", -1))
    out = jnp.cumsum(jnp.flip(x, axis) if attrs.get("reverse", False) else x, axis=axis)
    if attrs.get("reverse", False):
        out = jnp.flip(out, axis)
    if attrs.get("exclusive", False):
        pad = [(0, 0)] * x.ndim
        pad[axis] = (1, 0)
        out = jnp.pad(out, pad)[
            tuple(slice(0, x.shape[i]) if i == axis % x.ndim else slice(None) for i in range(x.ndim))
        ]
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# metrics (reference: metrics/accuracy_op.cc, metrics/auc_op.cc)
# ---------------------------------------------------------------------------


@register("accuracy", no_grad=True)
def _accuracy(ctx, ins, attrs):
    (indices,) = ins["Indices"]
    (label,) = ins["Label"]
    correct = jnp.any(indices == label.astype(indices.dtype), axis=-1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    total = indices.shape[0]
    return {
        "Accuracy": [(num_correct / total).reshape((1,))],
        "Correct": [num_correct.astype(jnp.int32).reshape((1,))],
        "Total": [jnp.asarray([total], dtype=jnp.int32)],
    }


@register("auc", no_grad=True)
def _auc(ctx, ins, attrs):
    """Streaming AUC (reference metrics/auc_op.cc): histogram positives and
    negatives into threshold buckets, accumulate into StatPos/StatNeg, compute
    AUC by trapezoidal rule over the cumulative counts."""
    (predict,) = ins["Predict"]
    (label,) = ins["Label"]
    stat_pos, stat_neg = ins["StatPos"][0], ins["StatNeg"][0]
    n = int(attrs.get("num_thresholds", 4095))
    pos_prob = predict[:, -1]
    bucket = jnp.clip((pos_prob * n).astype(jnp.int32), 0, n)
    is_pos = (label.reshape(-1) > 0).astype(jnp.float32)
    pos_hist = jnp.zeros(n + 1, jnp.float32).at[bucket].add(is_pos)
    neg_hist = jnp.zeros(n + 1, jnp.float32).at[bucket].add(1.0 - is_pos)
    sp = stat_pos + pos_hist
    sn = stat_neg + neg_hist
    # descending threshold cumulative TP/FP
    tp = jnp.cumsum(sp[::-1])
    fp = jnp.cumsum(sn[::-1])
    tot_pos, tot_neg = tp[-1], fp[-1]
    tp0 = jnp.concatenate([jnp.zeros(1), tp[:-1]])
    fp0 = jnp.concatenate([jnp.zeros(1), fp[:-1]])
    area = jnp.sum((fp - fp0) * (tp + tp0) / 2.0)
    auc = jnp.where(tot_pos * tot_neg > 0, area / (tot_pos * tot_neg + 1e-12), 0.0)
    return {
        "AUC": [auc.reshape((1,))],
        "StatPosOut": [sp],
        "StatNegOut": [sn],
    }


def _chunk_flags(y, n_types, scheme, excluded, seqlen):
    """Per-position chunk (start, end, type) flags for a padded [b, t] int
    tag grid under one of the conlleval tagging schemes.

    Tag layout matches the reference chunk_eval_op.h: label =
    chunk_type * num_tag_types + tag_type, with tag ids B=0,I=1 (IOB) /
    I=0,E=1 (IOE) / B=0,I=1,E=2,S=3 (IOBES) / the single tag 0 (plain, every
    tagged position its own chunk); any label outside [0, n_types*num_tag)
    is the O tag. A chunk starts where the tag says so OR the type changes
    OR the previous position is O/sequence-start (conlleval's boundary
    rules), and symmetrically for ends.
    """
    ntag = {"plain": 1, "IOB": 2, "IOE": 2, "IOBES": 4}[scheme]
    typ = y // ntag
    tag = y % ntag
    valid = (y >= 0) & (y < n_types * ntag)
    for ex in excluded:
        valid = valid & (typ != int(ex))
    t = y.shape[1]
    if seqlen is not None:
        valid = valid & (
            jnp.arange(t)[None, :] < seqlen.reshape(-1, 1).astype(jnp.int32)
        )
    pad_col = jnp.zeros((y.shape[0], 1), y.dtype)
    pad_f = jnp.zeros((y.shape[0], 1), bool)
    p_valid = jnp.concatenate([pad_f, valid[:, :-1]], 1)
    p_typ = jnp.concatenate([pad_col, typ[:, :-1]], 1)
    p_tag = jnp.concatenate([pad_col, tag[:, :-1]], 1)
    n_valid = jnp.concatenate([valid[:, 1:], pad_f], 1)
    n_typ = jnp.concatenate([typ[:, 1:], pad_col], 1)
    n_tag = jnp.concatenate([tag[:, 1:], pad_col], 1)
    boundary_in = ~p_valid | (p_typ != typ)
    boundary_out = ~n_valid | (n_typ != typ)
    if scheme == "plain":
        start = valid
        end = valid
    elif scheme == "IOB":
        start = valid & ((tag == 0) | boundary_in)
        end = valid & (boundary_out | (n_tag == 0))
    elif scheme == "IOE":
        start = valid & (boundary_in | (p_tag == 1))
        end = valid & ((tag == 1) | boundary_out)
    else:  # IOBES
        start = valid & ((tag == 0) | (tag == 3) | boundary_in | (p_tag >= 2))
        end = valid & ((tag >= 2) | boundary_out | (n_tag == 0) | (n_tag == 3))
    return start, end, typ


def _chunk_endpos(end):
    """For each position, the index of the NEXT chunk end at-or-after it
    (reverse running minimum over end positions) — a chunk starting at i
    spans [i, endpos[i]]."""
    t = end.shape[1]
    cand = jnp.where(end, jnp.arange(t)[None, :], t)
    return jnp.flip(lax.cummin(jnp.flip(cand, 1), axis=1), 1)


@register("chunk_eval", no_grad=True)
def _chunk_eval(ctx, ins, attrs):
    """Chunk-level precision/recall/F1 (reference chunk_eval_op.cc — the
    conlleval metric for NER-style taggers). Sequence layout follows this
    repo's padded-dense convention (sequence_ops.py): Inference/Label are
    [b, t] (or [b, t, 1]) tag grids with an optional SeqLength [b] mask.
    A predicted chunk is correct when a label chunk with the same span AND
    type exists; counting is fully vectorized (start/end boundary flags +
    span-end matching) rather than the reference's per-sequence scan."""
    (inference,) = ins["Inference"]
    (label,) = ins["Label"]
    seqlen = (ins.get("SeqLength") or [None])[0]
    scheme = str(attrs.get("chunk_scheme", "IOB"))
    if scheme not in ("plain", "IOB", "IOE", "IOBES"):
        raise ValueError("chunk_eval: unknown chunk_scheme %r" % scheme)
    n_types = int(attrs["num_chunk_types"])
    excluded = tuple(attrs.get("excluded_chunk_types", ()) or ())
    inf = inference.reshape(inference.shape[0], -1).astype(jnp.int32)
    lab = label.reshape(label.shape[0], -1).astype(jnp.int32)
    i_start, i_end, i_typ = _chunk_flags(inf, n_types, scheme, excluded, seqlen)
    l_start, l_end, l_typ = _chunk_flags(lab, n_types, scheme, excluded, seqlen)
    n_inf = jnp.sum(i_start)
    n_lab = jnp.sum(l_start)
    n_cor = jnp.sum(
        i_start
        & l_start
        & (i_typ == l_typ)
        & (_chunk_endpos(i_end) == _chunk_endpos(l_end))
    )
    fi, fl, fc = (x.astype(jnp.float32) for x in (n_inf, n_lab, n_cor))
    precision = jnp.where(fi > 0, fc / jnp.maximum(fi, 1.0), 0.0)
    recall = jnp.where(fl > 0, fc / jnp.maximum(fl, 1.0), 0.0)
    f1 = jnp.where(
        precision + recall > 0,
        2.0 * precision * recall / jnp.maximum(precision + recall, 1e-38),
        0.0,
    )
    i64 = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return {
        "Precision": [precision.reshape((1,))],
        "Recall": [recall.reshape((1,))],
        "F1-Score": [f1.reshape((1,))],
        "NumInferChunks": [n_inf.astype(i64).reshape((1,))],
        "NumLabelChunks": [n_lab.astype(i64).reshape((1,))],
        "NumCorrectChunks": [n_cor.astype(i64).reshape((1,))],
    }


@register("positive_negative_pair", no_grad=True)
def _positive_negative_pair(ctx, ins, attrs):
    """Pairwise ranking metric (reference positive_negative_pair_op.cc, the
    mq2007/LETOR evaluation): over every within-query item pair with
    differing labels, a pair is positive when the higher-labeled item also
    scores higher, negative when it scores lower, neutral on score ties.
    O(N^2) masked pairwise comparison — N is a batch, not a corpus."""
    (score,) = ins["Score"]
    (label,) = ins["Label"]
    (qid,) = ins["QueryID"]
    col = int(attrs.get("column", -1))
    s = score.reshape(score.shape[0], -1)[:, col].astype(jnp.float32)
    l = label.reshape(-1).astype(jnp.float32)
    q = qid.reshape(-1)
    n = s.shape[0]
    # each unordered pair once: strict upper triangle of the same-query mask
    pair = (
        (q[:, None] == q[None, :])
        & (jnp.arange(n)[:, None] < jnp.arange(n)[None, :])
        & (l[:, None] != l[None, :])
    ).astype(jnp.float32)
    if ins.get("Weight"):
        w = ins["Weight"][0].reshape(-1).astype(jnp.float32)
        pair = pair * 0.5 * (w[:, None] + w[None, :])
    # orient the score difference so positive means ranked like the labels
    d = (s[:, None] - s[None, :]) * jnp.sign(l[:, None] - l[None, :])
    pos = jnp.sum(pair * (d > 0))
    neg = jnp.sum(pair * (d < 0))
    neu = jnp.sum(pair * (d == 0))
    for slot, v in (
        ("AccumulatePositivePair", pos),
        ("AccumulateNegativePair", neg),
        ("AccumulateNeutralPair", neu),
    ):
        if ins.get(slot):
            v = v + ins[slot][0].reshape(())
        if slot == "AccumulatePositivePair":
            pos = v
        elif slot == "AccumulateNegativePair":
            neg = v
        else:
            neu = v
    return {
        "PositivePair": [pos.reshape((1,))],
        "NegativePair": [neg.reshape((1,))],
        "NeutralPair": [neu.reshape((1,))],
    }


# ---------------------------------------------------------------------------
# tensor manipulation (reference: reshape_op.cc, transpose_op.cc, concat_op.cc,
# split_op.cc, stack_op.cc, squeeze/unsqueeze, flatten, slice, gather, scatter,
# pad, expand, one_hot, lod_reset)
# ---------------------------------------------------------------------------


def _reshape_shape(x, shape_attr):
    shape = list(int(s) for s in shape_attr)
    # paddle semantics: 0 means copy input dim at that position
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    return shape


@register("reshape")
def _reshape(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [x.reshape(_reshape_shape(x, attrs["shape"]))]}


@register("reshape2")
def _reshape2(ctx, ins, attrs):
    (x,) = ins["X"]
    out = x.reshape(_reshape_shape(x, attrs["shape"]))
    xshape = jnp.zeros((0,) + x.shape, dtype=x.dtype)
    return {"Out": [out], "XShape": [xshape]}


@register("transpose")
def _transpose(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [jnp.transpose(x, attrs["axis"])]}


@register("transpose2")
def _transpose2(ctx, ins, attrs):
    (x,) = ins["X"]
    out = jnp.transpose(x, attrs["axis"])
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, dtype=x.dtype)]}


@register("concat")
def _concat(ctx, ins, attrs):
    xs = ins["X"]
    return {"Out": [jnp.concatenate(xs, axis=int(attrs.get("axis", 0)))]}


@register("split")
def _split(ctx, ins, attrs):
    (x,) = ins["X"]
    axis = int(attrs.get("axis", 0))
    sections = attrs.get("sections", [])
    num = int(attrs.get("num", 0))
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register("stack")
def _stack(ctx, ins, attrs):
    xs = ins["X"]
    return {"Y": [jnp.stack(xs, axis=int(attrs.get("axis", 0)))]}


@register("unstack")
def _unstack(ctx, ins, attrs):
    (x,) = ins["X"]
    axis = int(attrs.get("axis", 0))
    n = x.shape[axis]
    outs = [jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis)]
    return {"Y": outs}


def _squeeze_axes(x, axes):
    if axes:
        return tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
    return tuple(i for i, d in enumerate(x.shape) if d == 1)


@register("squeeze")
def _squeeze(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [jnp.squeeze(x, axis=_squeeze_axes(x, attrs.get("axes", [])))]}


@register("squeeze2")
def _squeeze2(ctx, ins, attrs):
    (x,) = ins["X"]
    out = jnp.squeeze(x, axis=_squeeze_axes(x, attrs.get("axes", [])))
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, dtype=x.dtype)]}


@register("unsqueeze")
def _unsqueeze(ctx, ins, attrs):
    (x,) = ins["X"]
    out = x
    for a in sorted(attrs["axes"]):
        out = jnp.expand_dims(out, a)
    return {"Out": [out]}


@register("unsqueeze2")
def _unsqueeze2(ctx, ins, attrs):
    (x,) = ins["X"]
    out = x
    for a in sorted(attrs["axes"]):
        out = jnp.expand_dims(out, a)
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, dtype=x.dtype)]}


@register("flatten")
def _flatten(ctx, ins, attrs):
    (x,) = ins["X"]
    axis = int(attrs.get("axis", 1))
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    return {"Out": [x.reshape((lead, -1))]}


@register("flatten2")
def _flatten2(ctx, ins, attrs):
    out = _flatten(ctx, ins, attrs)["Out"]
    (x,) = ins["X"]
    return {"Out": out, "XShape": [jnp.zeros((0,) + x.shape, dtype=x.dtype)]}


@register("slice")
def _slice(ctx, ins, attrs):
    (x,) = ins["Input"]
    axes = attrs["axes"]
    starts = attrs["starts"]
    ends = attrs["ends"]
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    return {"Out": [x[tuple(idx)]]}


@register("gather")
def _gather(ctx, ins, attrs):
    (x,) = ins["X"]
    (idx,) = ins["Index"]
    return {"Out": [jnp.take(x, idx.reshape(-1).astype(jnp.int32), axis=0)]}


@register("scatter")
def _scatter(ctx, ins, attrs):
    (x,) = ins["X"]
    (ids,) = ins["Ids"]
    (updates,) = ins["Updates"]
    ids = ids.reshape(-1).astype(jnp.int32)
    if attrs.get("overwrite", True):
        out = x.at[ids].set(updates)
    else:
        out = x.at[ids].add(updates)
    return {"Out": [out]}


@register("pad")
def _pad(ctx, ins, attrs):
    (x,) = ins["X"]
    p = attrs["paddings"]
    pairs = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {
        "Out": [jnp.pad(x, pairs, constant_values=attrs.get("pad_value", 0.0))]
    }


@register("pad2d")
def _pad2d(ctx, ins, attrs):
    (x,) = ins["X"]
    p = attrs["paddings"]  # [top, bottom, left, right]
    mode = attrs.get("mode", "constant")
    pairs = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if mode == "constant":
        out = jnp.pad(x, pairs, constant_values=attrs.get("pad_value", 0.0))
    elif mode == "reflect":
        out = jnp.pad(x, pairs, mode="reflect")
    else:
        out = jnp.pad(x, pairs, mode="edge")
    return {"Out": [out]}


@register("expand")
def _expand(ctx, ins, attrs):
    (x,) = ins["X"]
    times = attrs["expand_times"]
    return {"Out": [jnp.tile(x, times)]}


@register("one_hot", no_grad=True)
def _one_hot(ctx, ins, attrs):
    (x,) = ins["X"]
    depth = int(attrs["depth"])
    flat = x.reshape(x.shape[:-1]) if x.shape[-1] == 1 else x
    return {"Out": [jax.nn.one_hot(flat.astype(jnp.int32), depth, dtype=jnp.float32)]}


@register("hash", no_grad=True)
def _hash(ctx, ins, attrs):
    """Feature hashing of integer id rows (reference hash_op.cc, the
    "hash trick" front-end of sparse models: ids → num_hash hashed buckets
    in [0, mod_by), each feeding a lookup_table). The reference runs xxHash
    over each row's raw int64 bytes per seed; this is the same XXH32 round
    structure (the <16-byte tail path: per-4-byte-lane mix + avalanche,
    primes 2654435761/2246822519/3266489917/668265263/374761393) in wrapped
    uint32 jnp arithmetic — bit-exact XXH32 for the typical [N, 1] int64 id
    column, lane-chained for wider rows. Each logical id always hashes as 8
    bytes (hi lane 0 under i64→i32 canonicalization) so bucket assignment
    is independent of the executor's dtype policy."""
    (x,) = ins["X"]
    num_hash = int(attrs.get("num_hash", 1))
    mod_by = int(attrs.get("mod_by", 1))
    p1, p2, p3, p4, p5 = (
        jnp.uint32(2654435761),
        jnp.uint32(2246822519),
        jnp.uint32(3266489917),
        jnp.uint32(668265263),
        jnp.uint32(374761393),
    )

    def rotl(v, r):
        return (v << jnp.uint32(r)) | (v >> jnp.uint32(32 - r))

    ids = x.reshape(x.shape[0], -1)
    lanes = []
    for c in range(ids.shape[1]):
        col = ids[:, c]
        lo = col.astype(jnp.uint32)  # wraps mod 2^32 == the low 4 bytes
        hi = (
            (col >> 32).astype(jnp.uint32)
            if np.dtype(col.dtype).itemsize == 8
            else jnp.zeros(col.shape, jnp.uint32)
        )
        lanes += [lo, hi]
    nbytes = jnp.uint32(8 * ids.shape[1])
    outs = []
    for seed in range(num_hash):
        h = jnp.full(ids.shape[:1], jnp.uint32(seed), jnp.uint32) + p5 + nbytes
        for w in lanes:
            h = rotl(h + w * p3, 17) * p4
        h = (h ^ (h >> jnp.uint32(15))) * p2
        h = (h ^ (h >> jnp.uint32(13))) * p3
        h = h ^ (h >> jnp.uint32(16))
        outs.append((h % jnp.uint32(mod_by)).astype(x.dtype))
    return {"Out": [jnp.stack(outs, axis=1).reshape(x.shape[0], num_hash, 1)]}


@register("lookup_table")
def _lookup_table(ctx, ins, attrs):
    (w,) = ins["W"]
    (ids,) = ins["Ids"]
    padding_idx = int(attrs.get("padding_idx", -1))
    flat = ids.reshape(-1).astype(jnp.int32)
    out = jnp.take(w, flat, axis=0)
    # negative ids are padding/masked slots (AsyncExecutor's bucketed batches,
    # split_ids' shard masks): zero rows, zero grad — jnp.take alone would
    # clip them to row 0 and silently contribute it
    out = jnp.where((flat < 0)[:, None], 0.0, out)
    if padding_idx != -1:
        pad = padding_idx if padding_idx >= 0 else padding_idx + w.shape[0]
        out = jnp.where((flat == pad)[:, None], 0.0, out)
    out_shape = tuple(ids.shape[:-1]) + (w.shape[1],)
    if ids.shape[-1] != 1:
        out_shape = tuple(ids.shape) + (w.shape[1],)
    return {"Out": [out.reshape(out_shape)]}


@register("lookup_table_grad", no_grad=True)
def _lookup_table_grad(ctx, ins, attrs):
    """Explicit grad: scatter-add of the cotangent rows ACCUMULATED IN F32,
    cast once to the cotangent's dtype at the end. The f32 accumulator is
    what makes repeated ids safe under bf16 training: adding 1-ulp increments
    into a bf16 row plateaus once the row outgrows the increment's precision
    (the sum of ones stalls at 256 — the r05 advisor's swamping repro, covered
    by tests/test_ops_roundout.py), while one final rounding step loses at
    most 1 ulp. The result still lands in the cotangent's dtype, so the
    bf16-wire saving vs the generic vjp (which scatters in the f32 master
    table's dtype AND hands the f32 grad downstream — 2x 262 MB/step of HBM
    traffic on the MFU-bench transformer, r05 audit) is kept for every
    consumer; XLA fuses the trailing cast into the scatter's output write.
    W is consulted for its SHAPE only, so the transpiler's W@BF16 cast (if
    any) dead-codes away."""
    (w,) = ins["W"]
    (ids,) = ins["Ids"]
    (dout,) = ins["Out@GRAD"]
    padding_idx = int(attrs.get("padding_idx", -1))
    flat = ids.reshape(-1).astype(jnp.int32)
    d2 = dout.reshape(-1, w.shape[1])
    mask = flat >= 0
    if padding_idx != -1:
        pad = padding_idx if padding_idx >= 0 else padding_idx + w.shape[0]
        mask = mask & (flat != pad)
    dw = (
        jnp.zeros(w.shape, jnp.float32)
        .at[jnp.where(mask, flat, 0)]
        .add(jnp.where(mask[:, None], d2, 0).astype(jnp.float32))
        .astype(d2.dtype)
    )
    return {"W@GRAD": [dw]}


@register("embedding")
def _embedding(ctx, ins, attrs):
    return _lookup_table(ctx, ins, attrs)


@register("reverse")
def _reverse(ctx, ins, attrs):
    (x,) = ins["X"]
    axes = attrs["axis"]
    if isinstance(axes, int):
        axes = [axes]
    out = x
    for a in axes:
        out = jnp.flip(out, axis=a)
    return {"Out": [out]}


@register("label_smooth")
def _label_smooth(ctx, ins, attrs):
    (x,) = ins["X"]
    eps = attrs.get("epsilon", 0.1)
    k = x.shape[-1]
    if "PriorDist" in ins:
        prior = ins["PriorDist"][0].reshape(-1)
        out = (1 - eps) * x + eps * prior
    else:
        out = (1 - eps) * x + eps / k
    return {"Out": [out]}


@register("norm")
def _norm(ctx, ins, attrs):
    (x,) = ins["X"]
    axis = int(attrs.get("axis", 1))
    eps = attrs.get("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": [x / norm], "Norm": [norm]}


def _interp_shape(x, attrs):
    return int(attrs["out_h"]), int(attrs["out_w"])


@register("bilinear_interp")
def _bilinear_interp(ctx, ins, attrs):
    (x,) = ins["X"]
    oh, ow = _interp_shape(x, attrs)
    out = jax.image.resize(x, (x.shape[0], x.shape[1], oh, ow), method="bilinear")
    return {"Out": [out]}


@register("nearest_interp")
def _nearest_interp(ctx, ins, attrs):
    (x,) = ins["X"]
    oh, ow = _interp_shape(x, attrs)
    out = jax.image.resize(x, (x.shape[0], x.shape[1], oh, ow), method="nearest")
    return {"Out": [out]}


@register("lod_reset")
def _lod_reset(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [x]}


@register("where", no_grad=False)
def _where(ctx, ins, attrs):
    (cond,) = ins["Condition"]
    (x,) = ins["X"]
    (y,) = ins["Y"]
    return {"Out": [jnp.where(cond, x, y)]}


# ---------------------------------------------------------------------------
# convolution / pooling / normalization (reference: conv_op.cc +
# conv_cudnn_op.cu.cc, pool_op.cc, batch_norm_op.cc, layer_norm_op.cc — these
# are the MXU workhorses; lowered to XLA conv_general_dilated / reduce_window)
# ---------------------------------------------------------------------------


@register("conv2d")
def _conv2d(ctx, ins, attrs):
    (x,) = ins["Input"]
    (w,) = ins["Filter"]
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    paddings = [int(p) for p in attrs.get("paddings", [0, 0])]
    dilations = [int(d) for d in attrs.get("dilations", [1, 1])]
    groups = int(attrs.get("groups", 1) or 1)
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=[(paddings[0], paddings[0]), (paddings[1], paddings[1])],
        rhs_dilation=dilations,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=groups,
    )
    return {"Output": [out]}


@register("depthwise_conv2d")
def _depthwise_conv2d(ctx, ins, attrs):
    return _conv2d(ctx, ins, attrs)


# conv2d_transpose is registered in nn_extra_ops.py beside the other
# _conv_nd(transpose=True) family members (conv3d_transpose,
# depthwise_conv2d_transpose)


@register("pool2d")
def _pool2d(ctx, ins, attrs):
    (x,) = ins["X"]
    ptype = attrs.get("pooling_type", "max")
    ksize = [int(k) for k in attrs.get("ksize", [2, 2])]
    strides = [int(s) for s in attrs.get("strides", ksize)]
    paddings = [int(p) for p in attrs.get("paddings", [0, 0])]
    if attrs.get("global_pooling", False) or attrs.get("adaptive", False) and list(
        attrs.get("ksize")
    ) == [1, 1]:
        ksize = [x.shape[2], x.shape[3]]
        strides = ksize
        paddings = [0, 0]
    window = (1, 1, ksize[0], ksize[1])
    strd = (1, 1, strides[0], strides[1])
    pads = ((0, 0), (0, 0), (paddings[0], paddings[0]), (paddings[1], paddings[1]))
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = lax.reduce_window(x, init, lax.max, window, strd, pads)
    else:
        s = lax.reduce_window(x, 0.0, lax.add, window, strd, pads)
        if attrs.get("exclusive", True) and (paddings[0] or paddings[1]):
            ones = jnp.ones_like(x)
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, strd, pads)
            out = s / cnt
        else:
            out = s / (ksize[0] * ksize[1])
    return {"Out": [out]}


@register("batch_norm")
def _batch_norm(ctx, ins, attrs):
    (x,) = ins["X"]
    (scale,) = ins["Scale"]
    (bias,) = ins["Bias"]
    (mean,) = ins["Mean"]
    (var,) = ins["Variance"]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = bool(attrs.get("is_test", False)) or bool(
        attrs.get("use_global_stats", False)
    )
    layout = attrs.get("data_layout", "NCHW")
    axes = (
        tuple(i for i in range(x.ndim) if i != 1)
        if layout == "NCHW"
        else tuple(range(x.ndim - 1))
    )
    cshape = [1] * x.ndim
    c_axis = 1 if layout == "NCHW" else x.ndim - 1
    cshape[c_axis] = x.shape[c_axis]

    if is_test:
        use_mean, use_var = mean, var
        saved_mean, saved_var = mean, var
        mean_out, var_out = mean, var
    else:
        xf = x.astype(jnp.float32)
        bmean = jnp.mean(xf, axis=axes)
        bvar = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(bmean)
        use_mean, use_var = bmean, bvar
        saved_mean = bmean
        saved_var = 1.0 / jnp.sqrt(bvar + eps)  # reference saves inv-std
        mean_out = mean * momentum + bmean * (1 - momentum)
        var_out = var * momentum + bvar * (1 - momentum)

    inv = lax.rsqrt(use_var.reshape(cshape) + eps)
    y = (x - use_mean.reshape(cshape)) * inv * scale.reshape(cshape) + bias.reshape(
        cshape
    )
    return {
        "Y": [y.astype(x.dtype)],
        "MeanOut": [mean_out],
        "VarianceOut": [var_out],
        "SavedMean": [saved_mean],
        "SavedVariance": [saved_var],
    }


@register("layer_norm")
def _layer_norm(ctx, ins, attrs):
    (x,) = ins["X"]
    eps = attrs.get("epsilon", 1e-5)
    bna = int(attrs.get("begin_norm_axis", 1))
    lead = int(np.prod(x.shape[:bna]))
    x2 = x.reshape((lead, -1)).astype(jnp.float32)
    mean = jnp.mean(x2, axis=1)
    var = jnp.var(x2, axis=1)
    y = (x2 - mean[:, None]) * lax.rsqrt(var[:, None] + eps)
    if "Scale" in ins:
        y = y * ins["Scale"][0].reshape(-1)[None, :]
    if "Bias" in ins:
        y = y + ins["Bias"][0].reshape(-1)[None, :]
    return {
        "Y": [y.reshape(x.shape).astype(x.dtype)],
        "Mean": [mean],
        "Variance": [var],
    }


@register("lrn")
def _lrn(ctx, ins, attrs):
    (x,) = ins["X"]
    n = int(attrs.get("n", 5))
    k = attrs.get("k", 1.0)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i : i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    return {"Out": [x / jnp.power(mid, beta)], "MidOut": [mid]}


# ---------------------------------------------------------------------------
# dropout — custom grad: must reuse the forward-sampled mask, so the generic
# vjp-replay grad does not apply (reference dropout_op.cc keeps Mask for grad)
# ---------------------------------------------------------------------------


def _dropout_grad_maker(op, block, grad_map):
    return [
        {
            "type": "dropout_grad",
            "inputs": {
                "Out@GRAD": [grad_map[op.output("Out")[0]]],
                "Mask": [op.output("Mask")[0]],
            },
            "outputs": {"X@GRAD": [grad_map[op.input("X")[0]]]},
            "attrs": {k: v for k, v in op.attrs.items()},
        }
    ]


@register("dropout", stochastic=True, grad=_dropout_grad_maker)
def _dropout(ctx, ins, attrs):
    (x,) = ins["X"]
    p = attrs.get("dropout_prob", 0.5)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if attrs.get("is_test", False):
        mask = jnp.ones_like(x)
        out = x * (1.0 - p) if impl == "downgrade_in_infer" else x
        return {"Out": [out], "Mask": [mask]}
    keep = jax.random.bernoulli(_rng(ctx, attrs), 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        mask = keep.astype(x.dtype) / (1.0 - p)
    else:
        mask = keep.astype(x.dtype)
    return {"Out": [x * mask], "Mask": [mask]}


@register("dropout_grad", no_grad=True)
def _dropout_grad(ctx, ins, attrs):
    (dout,) = ins["Out@GRAD"]
    (mask,) = ins["Mask"]
    return {"X@GRAD": [dout * mask]}


# ---------------------------------------------------------------------------
# optimizer ops (reference: operators/optimizers/*.cc — sgd, momentum, adam,
# adagrad, rmsprop, adadelta, adamax, decayed_adagrad, ftrl, lars_momentum).
# Each consumes Param (+state) and emits ParamOut (+state outs) under the SAME
# variable names; the executor's env-update model gives in-place semantics and
# the jit donates param buffers.
# ---------------------------------------------------------------------------


def _p(ins, slot):
    return ins[slot][0]


# optimizer-state input slots per op type — the moment/accumulator tensors the
# ZeRO-1 tier (ReduceStrategy.Reduce) stores sharded 1/dp per rank. Scalar
# state (Beta*Pow, LearningRate) is NOT listed: shape [1] cannot shard and its
# update must stay replicated for numerics identical to the all-reduce path.
# Consumed by executor._CompiledBlock to build the sharded in/out_shardings.
ZERO1_STATE_SLOTS = {
    "momentum": ("Velocity",),
    "lars_momentum": ("Velocity",),
    "adam": ("Moment1", "Moment2"),
    "adagrad": ("Moment",),
    "decayed_adagrad": ("Moment",),
    "rmsprop": ("MeanSquare", "Moment", "MeanGrad"),
    "adadelta": ("AvgSquaredGrad", "AvgSquaredUpdate"),
    "adamax": ("Moment", "InfNorm"),
    "ftrl": ("SquaredAccumulator", "LinearAccumulator"),
}


def _opt_f32(fn):
    """Optimizer-lowering dtype fidelity: compute the update in f32 (bf16
    grads upcast; master states already f32 under the train-mode
    Bf16Transpiler), then cast every `<Slot>Out` back to its `<Slot>` input's
    dtype. Without the output casts, f32 promotion (the f32 LearningRate)
    silently retypes the written-back state, which both changes training
    numerics and — because the state dtype is part of the compile-cache
    key — forces a full recompile on the next step (caught by the round-4
    per-HLO MFU audit, PROFILE.md)."""

    @functools.wraps(fn)
    def wrapped(ctx, ins, attrs):
        from ..parallel import sharding_rules as _sr

        # storage-layout constraints (parallel/sharding_rules): rule-sharded
        # params (FSDP/TP) pin param+grad+moments to the declared spec; else
        # the ZeRO-1 tier reduce-scatters the grad and slices param+moments
        # to this rank's 1/dp shard. Either way BEFORE the f32 upcast (the
        # wire carries the grad's native dtype; the upcast then touches only
        # the local shard).
        raw_ins = ins
        ins = _sr.opt_constrain_ins(ctx, ins)
        orig_dt = {}
        ins32 = {}
        for slot, vals in ins.items():
            up = []
            for a in vals:
                if a is not None and jnp.issubdtype(
                    jnp.asarray(a).dtype, jnp.floating
                ):
                    orig_dt.setdefault(slot, jnp.asarray(a).dtype)
                    up.append(jnp.asarray(a).astype(jnp.float32))
                else:
                    up.append(a)
            ins32[slot] = up
        res = fn(ctx, ins32, attrs)
        out = {}
        for slot, vals in res.items():
            base = slot[:-3] if slot.endswith("Out") else slot
            dt = orig_dt.get(base, orig_dt.get("Param"))
            down = []
            for v in vals:
                if (
                    dt is not None
                    and hasattr(v, "dtype")
                    and jnp.issubdtype(v.dtype, jnp.floating)
                    and v.dtype != dt
                ):
                    down.append(v.astype(dt))
                else:
                    down.append(v)
            out[slot] = down
        # rule-sharded: outputs stay in the storage spec (params live
        # sharded, all-gather-on-use). ZeRO-1: ParamOut all-gathers back to
        # every rank; moments stay sharded (stored 1/dp via the executor's
        # state shardings).
        return _sr.opt_constrain_outs(ctx, out, raw_ins)

    return wrapped


@register("sgd", no_grad=True)
@_opt_f32
def _sgd(ctx, ins, attrs):
    p, g, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "LearningRate")
    return {"ParamOut": [p - lr.reshape(()).astype(p.dtype) * g]}


@register("momentum", no_grad=True)
@_opt_f32
def _momentum(ctx, ins, attrs):
    p, g, v, lr = (
        _p(ins, "Param"),
        _p(ins, "Grad"),
        _p(ins, "Velocity"),
        _p(ins, "LearningRate"),
    )
    mu = attrs["mu"]
    lr = lr.reshape(()).astype(p.dtype)
    v_out = mu * v + g
    if attrs.get("use_nesterov", False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": [p_out], "VelocityOut": [v_out]}


@register("lars_momentum", no_grad=True)
@_opt_f32
def _lars_momentum(ctx, ins, attrs):
    p, g, v, lr = (
        _p(ins, "Param"),
        _p(ins, "Grad"),
        _p(ins, "Velocity"),
        _p(ins, "LearningRate"),
    )
    mu = attrs["mu"]
    lars_coeff = attrs.get("lars_coeff", 0.001)
    lars_wd = attrs.get("lars_weight_decay", 0.0005)
    lr = lr.reshape(()).astype(jnp.float32)
    pn = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
    gn = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
    local_lr = jnp.where(
        (pn > 0) & (gn > 0), lr * lars_coeff * pn / (gn + lars_wd * pn), lr
    )
    v_out = mu * v + local_lr * (g + lars_wd * p)
    return {"ParamOut": [p - v_out], "VelocityOut": [v_out]}


@register("adam", no_grad=True)
@_opt_f32
def _adam(ctx, ins, attrs):
    p, g, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "LearningRate")
    m1, m2 = _p(ins, "Moment1"), _p(ins, "Moment2")
    b1p, b2p = _p(ins, "Beta1Pow"), _p(ins, "Beta2Pow")
    b1, b2, eps = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999), attrs.get(
        "epsilon", 1e-8
    )
    lr = lr.reshape(()).astype(jnp.float32)
    m1o = b1 * m1 + (1 - b1) * g
    m2o = b2 * m2 + (1 - b2) * jnp.square(g)
    lr_t = lr * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
    p_out = p - lr_t * m1o / (jnp.sqrt(m2o) + eps)
    return {"ParamOut": [p_out], "Moment1Out": [m1o], "Moment2Out": [m2o]}


@register("adagrad", no_grad=True)
@_opt_f32
def _adagrad(ctx, ins, attrs):
    p, g, lr, mom = (
        _p(ins, "Param"),
        _p(ins, "Grad"),
        _p(ins, "LearningRate"),
        _p(ins, "Moment"),
    )
    eps = attrs.get("epsilon", 1e-6)
    mom_out = mom + jnp.square(g)
    p_out = p - lr.reshape(()) * g / (jnp.sqrt(mom_out) + eps)
    return {"ParamOut": [p_out], "MomentOut": [mom_out]}


@register("decayed_adagrad", no_grad=True)
@_opt_f32
def _decayed_adagrad(ctx, ins, attrs):
    p, g, lr, mom = (
        _p(ins, "Param"),
        _p(ins, "Grad"),
        _p(ins, "LearningRate"),
        _p(ins, "Moment"),
    )
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    mom_out = decay * mom + (1 - decay) * jnp.square(g)
    p_out = p - lr.reshape(()) * g / (jnp.sqrt(mom_out) + eps)
    return {"ParamOut": [p_out], "MomentOut": [mom_out]}


@register("rmsprop", no_grad=True)
@_opt_f32
def _rmsprop(ctx, ins, attrs):
    p, g, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "LearningRate")
    ms, mom = _p(ins, "MeanSquare"), _p(ins, "Moment")
    eps, decay, momentum = (
        attrs.get("epsilon", 1e-10),
        attrs.get("decay", 0.9),
        attrs.get("momentum", 0.0),
    )
    lr = lr.reshape(())
    if attrs.get("centered", False):
        mg = _p(ins, "MeanGrad")
        ms_out = decay * ms + (1 - decay) * jnp.square(g)
        mg_out = decay * mg + (1 - decay) * g
        mom_out = momentum * mom + lr * g / jnp.sqrt(
            ms_out - jnp.square(mg_out) + eps
        )
        return {
            "ParamOut": [p - mom_out],
            "MeanSquareOut": [ms_out],
            "MomentOut": [mom_out],
            "MeanGradOut": [mg_out],
        }
    ms_out = decay * ms + (1 - decay) * jnp.square(g)
    mom_out = momentum * mom + lr * g / jnp.sqrt(ms_out + eps)
    return {"ParamOut": [p - mom_out], "MeanSquareOut": [ms_out], "MomentOut": [mom_out]}


@register("adadelta", no_grad=True)
@_opt_f32
def _adadelta(ctx, ins, attrs):
    p, g = _p(ins, "Param"), _p(ins, "Grad")
    avg_sq_g, avg_sq_u = _p(ins, "AvgSquaredGrad"), _p(ins, "AvgSquaredUpdate")
    rho, eps = attrs.get("rho", 0.95), attrs.get("epsilon", 1e-6)
    asg = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_u + eps) / (asg + eps)) * g
    asu = rho * avg_sq_u + (1 - rho) * jnp.square(update)
    return {
        "ParamOut": [p + update],
        "AvgSquaredGradOut": [asg],
        "AvgSquaredUpdateOut": [asu],
    }


@register("adamax", no_grad=True)
@_opt_f32
def _adamax(ctx, ins, attrs):
    p, g, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "LearningRate")
    mom, inf_norm, b1p = _p(ins, "Moment"), _p(ins, "InfNorm"), _p(ins, "Beta1Pow")
    b1, b2, eps = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999), attrs.get(
        "epsilon", 1e-8
    )
    mom_out = b1 * mom + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf_norm, jnp.abs(g))
    lr_t = lr.reshape(()) / (1 - b1p.reshape(()))
    p_out = p - lr_t * mom_out / (inf_out + eps)
    return {"ParamOut": [p_out], "MomentOut": [mom_out], "InfNormOut": [inf_out]}


@register("ftrl", no_grad=True)
@_opt_f32
def _ftrl(ctx, ins, attrs):
    p, g, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "LearningRate")
    sq_acc, lin_acc = _p(ins, "SquaredAccumulator"), _p(ins, "LinearAccumulator")
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr_power = attrs.get("lr_power", -0.5)
    lr = lr.reshape(())
    new_acc = sq_acc + jnp.square(g)
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_acc) - jnp.sqrt(sq_acc)) / lr
    else:
        sigma = (jnp.power(new_acc, -lr_power) - jnp.power(sq_acc, -lr_power)) / lr
    lin_out = lin_acc + g - sigma * p
    if lr_power == -0.5:
        x_den = l2 + jnp.sqrt(new_acc) / lr
    else:
        x_den = l2 + jnp.power(new_acc, -lr_power) / lr
    pre = jnp.clip(lin_out, -l1, l1) - lin_out
    p_out = pre / x_den
    return {
        "ParamOut": [p_out],
        "SquaredAccumOut": [new_acc],
        "LinearAccumOut": [lin_out],
    }
