"""Secondary NN / vision / tensor ops completing reference op-registry parity.

Reference analogs (paddle/fluid/operators/): conv3d_op.cc, pool_op.cc (pool3d),
pool_with_index_op.{cc,h} + math/pooling.cc:552 (mask = global h*W+w index),
unpool_op.cc + math/unpooling.cc:39 (scatter by global index), spp_op.h:31-51
(pow-of-2 pyramid with ceil kernels), maxout_op.cc + math/maxouting.cc,
group_norm_op.cc, affine_channel_op.cc, bilinear_tensor_product_op.h,
grid_sampler_op.h:34-80 (corners zeroed out of bounds, coords scaled by
(g+1)*0.5*(dim-1)), affine_grid_op.cc, minus_op.cc, l1_norm_op.h,
squared_l2_distance_op.h, selu_op.cc, fill_op.cc, is_empty_op.cc,
multiplex_op.cc, crop_op.cc, pad_constant_like_op.cc, random_crop_op.h,
space_to_depth_op.h:39-57 (channel order (bh, bw, c)), conv_shift_op.cc
(circular correlation), add_position_encoding_op.h:63-76 (half sin / half
cos), mean_iou_op.h:92-110, similarity_focus_op.h:29-130 (greedy row/col
unique selection per selected channel).

All lowerings are whole-block XLA ops; gradients come from the registry's
generic jax.vjp derivation except where a custom grad reuses a saved index
(max-pool masks), matching the reference's Mask-based grad kernels.
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import register


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------


def _norm_list(v, n, default):
    if v is None:
        v = default
    v = [int(x) for x in (v if isinstance(v, (list, tuple)) else [v])]
    if len(v) == 1:
        v = v * n
    return v


def _conv_nd(x, w, attrs, nd, transpose=False, depthwise_groups=None):
    strides = _norm_list(attrs.get("strides"), nd, [1] * nd)
    paddings = _norm_list(attrs.get("paddings"), nd, [0] * nd)
    dilations = _norm_list(attrs.get("dilations"), nd, [1] * nd)
    groups = int(depthwise_groups or attrs.get("groups", 1) or 1)
    sp = "DHW"[-nd:]
    if not transpose:
        return lax.conv_general_dilated(
            x,
            w,
            window_strides=strides,
            padding=[(p, p) for p in paddings],
            rhs_dilation=dilations,
            dimension_numbers=("NC" + sp, "OI" + sp, "NC" + sp),
            feature_group_count=groups,
        )
    # Transposed conv with group support: fractionally-strided conv
    # (lhs_dilation) against the spatially-flipped, IO-swapped kernel. The
    # paddle filter layout for conv_transpose is (C_in, C_out/groups, *k).
    k = w.shape[2:]
    w = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    if groups > 1:
        # (C_in, C_out/g, *k) -> g * (C_in/g, C_out/g, *k) -> (C_out, C_in/g, *k)
        cin = w.shape[0]
        w = w.reshape((groups, cin // groups) + w.shape[1:])
        w = jnp.moveaxis(w, 2, 1).reshape((-1, cin // groups) + k)
    else:
        w = jnp.swapaxes(w, 0, 1)
    pad = [
        (dilations[i] * (k[i] - 1) - paddings[i], dilations[i] * (k[i] - 1) - paddings[i])
        for i in range(nd)
    ]
    return lax.conv_general_dilated(
        x,
        w,
        window_strides=[1] * nd,
        padding=pad,
        lhs_dilation=strides,
        rhs_dilation=dilations,
        dimension_numbers=("NC" + sp, "OI" + sp, "NC" + sp),
        feature_group_count=groups,
    )


def _pool_nd(x, attrs, nd):
    ptype = attrs.get("pooling_type", "max")
    ksize = _norm_list(attrs.get("ksize"), nd, [2] * nd)
    strides = _norm_list(attrs.get("strides"), nd, ksize)
    paddings = _norm_list(attrs.get("paddings"), nd, [0] * nd)
    if attrs.get("global_pooling", False):
        ksize = list(x.shape[2:])
        strides = ksize
        paddings = [0] * nd
    window = (1, 1) + tuple(ksize)
    strd = (1, 1) + tuple(strides)
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in paddings)
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strd, pads)
    s = lax.reduce_window(x, 0.0, lax.add, window, strd, pads)
    if attrs.get("exclusive", True) and any(paddings):
        cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window, strd, pads)
        return s / cnt
    return s / float(np.prod(ksize))


def _window_stack(x, ksize, strides, paddings, pad_value):
    """Stack pooling windows: (N, C, *S) -> (N, C, prod(k), *out), plus the
    per-window-element global flat spatial index of each sample."""
    nd = len(ksize)
    spatial = x.shape[2:]
    out = [
        (spatial[i] + 2 * paddings[i] - ksize[i]) // strides[i] + 1 for i in range(nd)
    ]
    xp = jnp.pad(
        x,
        ((0, 0), (0, 0)) + tuple((p, p) for p in paddings),
        constant_values=pad_value,
    )
    slabs, gidx = [], []
    for offs in itertools.product(*[range(k) for k in ksize]):
        idx = (slice(None), slice(None)) + tuple(
            slice(offs[i], offs[i] + (out[i] - 1) * strides[i] + 1, strides[i])
            for i in range(nd)
        )
        slabs.append(xp[idx])
        # global index of this window element at each output position
        coord = [
            jnp.arange(out[i]) * strides[i] - paddings[i] + offs[i] for i in range(nd)
        ]
        flat = coord[0]
        for i in range(1, nd):
            flat = flat[..., None] * spatial[i] + coord[i]
        gidx.append(flat)
    return jnp.stack(slabs, axis=2), jnp.stack(gidx, axis=0), out


def _max_pool_with_index(ctx, ins, attrs, nd):
    (x,) = ins["X"]
    ksize = _norm_list(attrs.get("ksize"), nd, [2] * nd)
    strides = _norm_list(attrs.get("strides"), nd, ksize)
    paddings = _norm_list(attrs.get("paddings"), nd, [0] * nd)
    if attrs.get("global_pooling", False):
        ksize = list(x.shape[2:])
        strides = ksize
        paddings = [0] * nd
    win, gidx, out = _window_stack(x, ksize, strides, paddings, -jnp.inf)
    amax = jnp.argmax(win, axis=2)
    val = jnp.max(win, axis=2)
    # gidx is (K, *out) shared across N,C: pick the winning window element's
    # global spatial index per flattened output position
    gflat = gidx.reshape(gidx.shape[0], -1)  # (K, P)
    aflat = amax.reshape(amax.shape[0], amax.shape[1], -1)  # (N, C, P)
    mask = gflat[aflat, jnp.arange(gflat.shape[1])[None, None, :]].reshape(val.shape)
    return {"Out": [val], "Mask": [mask.astype(jnp.int32)]}


def _mask_scatter_grad(dout, mask, spatial_numel):
    """Scatter pooled grads back through saved global indices (reference
    math/pooling.cc MaxPool2dWithIndexGradFunctor)."""
    n, c = dout.shape[:2]
    d2 = dout.reshape(n * c, -1)
    m2 = mask.reshape(n * c, -1)

    def scat(g, m):
        return jnp.zeros((spatial_numel,), g.dtype).at[m].add(g)

    return jax.vmap(scat)(d2, m2)


def _pool_index_grad_maker(op, block, grad_map):
    return [
        {
            "type": op.type + "_grad",
            "inputs": {
                "X": [op.input("X")[0]],
                "Mask": [op.output("Mask")[0]],
                "Out@GRAD": [grad_map[op.output("Out")[0]]],
            },
            "outputs": {"X@GRAD": [grad_map[op.input("X")[0]]]},
            "attrs": dict(op.attrs),
        }
    ]


# ---------------------------------------------------------------------------
# conv3d / pool3d family
# ---------------------------------------------------------------------------


@register("conv3d")
def _conv3d(ctx, ins, attrs):
    out = _conv_nd(ins["Input"][0], ins["Filter"][0], attrs, 3)
    return {"Output": [out]}


@register("conv3d_transpose")
def _conv3d_transpose(ctx, ins, attrs):
    out = _conv_nd(ins["Input"][0], ins["Filter"][0], attrs, 3, transpose=True)
    return {"Output": [out]}


@register("conv2d_transpose")
def _conv2d_transpose(ctx, ins, attrs):
    """Reference conv2d_transpose_op.cc semantics — the gradient of conv2d
    w.r.t. its input: out[oc, i*s+ki-p, j*s+kj-p] += x[ic,i,j]*w[ic,oc,ki,kj].
    (lax.conv_transpose's transpose_kernel=False form is NOT this op: it
    neither flips the kernel nor produces the (in-1)*stride+k-2p output
    extent for stride>1 — caught by the round-2 OpTest sweep.)"""
    out = _conv_nd(ins["Input"][0], ins["Filter"][0], attrs, 2, transpose=True)
    return {"Output": [out]}


@register("depthwise_conv2d_transpose")
def _depthwise_conv2d_transpose(ctx, ins, attrs):
    x = ins["Input"][0]
    out = _conv_nd(x, ins["Filter"][0], attrs, 2, transpose=True)
    return {"Output": [out]}


@register("pool3d")
def _pool3d(ctx, ins, attrs):
    return {"Out": [_pool_nd(ins["X"][0], attrs, 3)]}


@register("max_pool2d_with_index", grad=_pool_index_grad_maker)
def _max_pool2d_with_index(ctx, ins, attrs):
    return _max_pool_with_index(ctx, ins, attrs, 2)


@register("max_pool3d_with_index", grad=_pool_index_grad_maker)
def _max_pool3d_with_index(ctx, ins, attrs):
    return _max_pool_with_index(ctx, ins, attrs, 3)


@register("max_pool2d_with_index_grad", no_grad=True)
def _max_pool2d_with_index_grad(ctx, ins, attrs):
    (x,) = ins["X"]
    (mask,) = ins["Mask"]
    (dout,) = ins["Out@GRAD"]
    flat = _mask_scatter_grad(dout, mask, int(np.prod(x.shape[2:])))
    return {"X@GRAD": [flat.reshape(x.shape)]}


@register("max_pool3d_with_index_grad", no_grad=True)
def _max_pool3d_with_index_grad(ctx, ins, attrs):
    return _max_pool2d_with_index_grad(ctx, ins, attrs)


@register("unpool")
def _unpool(ctx, ins, attrs):
    (x,) = ins["X"]
    (indices,) = ins["Indices"]
    ksize = _norm_list(attrs.get("ksize"), 2, [2, 2])
    strides = _norm_list(attrs.get("strides"), 2, ksize)
    paddings = _norm_list(attrs.get("paddings"), 2, [0, 0])
    n, c, h, w = x.shape
    oh = (h - 1) * strides[0] - 2 * paddings[0] + ksize[0]
    ow = (w - 1) * strides[1] - 2 * paddings[1] + ksize[1]
    x2 = x.reshape(n * c, -1)
    i2 = indices.reshape(n * c, -1)

    def scat(v, m):
        return jnp.zeros((oh * ow,), v.dtype).at[m].set(v)

    out = jax.vmap(scat)(x2, i2).reshape(n, c, oh, ow)
    return {"Out": [out]}


@register("spp")
def _spp(ctx, ins, attrs):
    (x,) = ins["X"]
    height = int(attrs.get("pyramid_height", 1))
    ptype = attrs.get("pooling_type", "max")
    n, c, h, w = x.shape
    pieces = []
    for p in range(height):
        bins = 2**p
        kh = -(-h // bins)
        kw = -(-w // bins)
        ph = (kh * bins - h + 1) // 2
        pw = (kw * bins - w + 1) // 2
        pooled = _pool_nd(
            x,
            {
                "pooling_type": ptype,
                "ksize": [kh, kw],
                "strides": [kh, kw],
                "paddings": [ph, pw],
                "exclusive": False,
            },
            2,
        )
        pieces.append(pooled.reshape(n, -1))
    return {"Out": [jnp.concatenate(pieces, axis=1)]}


@register("maxout")
def _maxout(ctx, ins, attrs):
    (x,) = ins["X"]
    g = int(attrs["groups"])
    n, c = x.shape[:2]
    out = x.reshape((n, c // g, g) + x.shape[2:]).max(axis=2)
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# normalization / channel transforms
# ---------------------------------------------------------------------------


@register("group_norm")
def _group_norm(ctx, ins, attrs):
    (x,) = ins["X"]
    eps = float(attrs.get("epsilon", 1e-5))
    groups = int(attrs.get("groups", 1))
    n, c = x.shape[:2]
    xg = x.reshape(n, groups, -1).astype(jnp.float32)
    mean = xg.mean(axis=2)
    var = xg.var(axis=2)
    y = (xg - mean[:, :, None]) * lax.rsqrt(var[:, :, None] + eps)
    y = y.reshape(x.shape)
    cshape = (1, c) + (1,) * (x.ndim - 2)
    if "Scale" in ins:
        y = y * ins["Scale"][0].reshape(cshape)
    if "Bias" in ins:
        y = y + ins["Bias"][0].reshape(cshape)
    return {"Y": [y.astype(x.dtype)], "Mean": [mean], "Variance": [var]}


@register("affine_channel")
def _affine_channel(ctx, ins, attrs):
    (x,) = ins["X"]
    layout = attrs.get("data_layout", "NCHW")
    c_axis = 1 if layout == "NCHW" else x.ndim - 1
    cshape = [1] * x.ndim
    cshape[c_axis] = x.shape[c_axis]
    out = x * ins["Scale"][0].reshape(cshape) + ins["Bias"][0].reshape(cshape)
    return {"Out": [out]}


@register("bilinear_tensor_product")
def _bilinear_tensor_product(ctx, ins, attrs):
    (x,) = ins["X"]
    (y,) = ins["Y"]
    (w,) = ins["Weight"]
    out = jnp.einsum("bm,kmn,bn->bk", x, w, y)
    if "Bias" in ins:
        out = out + ins["Bias"][0].reshape(1, -1)
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# spatial samplers
# ---------------------------------------------------------------------------


@register("grid_sampler")
def _grid_sampler(ctx, ins, attrs):
    (x,) = ins["X"]
    (grid,) = ins["Grid"]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * 0.5 * (w - 1)
    gy = (grid[..., 1] + 1.0) * 0.5 * (h - 1)
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    out = jnp.zeros((n, c) + grid.shape[1:3], x.dtype)
    batch = jnp.arange(n).reshape(n, 1, 1)
    for dx, dy in ((0, 0), (0, 1), (1, 0), (1, 1)):
        xs = x0 + dx
        ys = y0 + dy
        wgt = (1.0 - jnp.abs(gx - xs)) * (1.0 - jnp.abs(gy - ys))
        inb = (xs >= 0) & (xs <= w - 1) & (ys >= 0) & (ys <= h - 1)
        xi = jnp.clip(xs, 0, w - 1).astype(jnp.int32)
        yi = jnp.clip(ys, 0, h - 1).astype(jnp.int32)
        v = x[batch, :, yi, xi]  # (n, gh, gw, c)
        v = jnp.moveaxis(v, -1, 1)
        out = out + v * (wgt * inb)[:, None]
    return {"Output": [out]}


@register("affine_grid")
def _affine_grid(ctx, ins, attrs):
    (theta,) = ins["Theta"]
    if "OutputShape" in ins and ins["OutputShape"][0] is not None:
        oshape = [int(d) for d in np.asarray(ins["OutputShape"][0])]
    else:
        oshape = [int(d) for d in attrs["output_shape"]]
    n, _, h, w = oshape
    xs = jnp.linspace(-1.0, 1.0, w)
    ys = jnp.linspace(-1.0, 1.0, h)
    gx, gy = jnp.meshgrid(xs, ys)  # (h, w)
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # (h, w, 3)
    out = jnp.einsum("hwk,nck->nhwc", base, theta.astype(jnp.float32))
    return {"Output": [out.astype(theta.dtype)]}


# ---------------------------------------------------------------------------
# small math / tensor ops
# ---------------------------------------------------------------------------


@register("minus")
def _minus(ctx, ins, attrs):
    return {"Out": [ins["X"][0] - ins["Y"][0]]}


@register("l1_norm")
def _l1_norm(ctx, ins, attrs):
    return {"Out": [jnp.abs(ins["X"][0]).sum().reshape(1)]}


@register("squared_l2_distance")
def _squared_l2_distance(ctx, ins, attrs):
    (x,) = ins["X"]
    (y,) = ins["Y"]
    if y.shape[0] == 1 and x.shape[0] > 1:
        y = jnp.broadcast_to(y, x.shape)
    sub = x - y
    out = jnp.square(sub.reshape(sub.shape[0], -1)).sum(axis=1, keepdims=True)
    return {"sub_result": [sub], "Out": [out]}


@register("selu")
def _selu(ctx, ins, attrs):
    (x,) = ins["X"]
    scale = float(attrs.get("scale", 1.0507009873554804934193349852946))
    alpha = float(attrs.get("alpha", 1.6732632423543772848170429916717))
    return {"Out": [scale * jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1.0))]}


@register("fill", no_grad=True)
def _fill(ctx, ins, attrs):
    shape = [int(d) for d in attrs["shape"]]
    dtype = attrs.get("dtype", "float32")
    value = np.asarray(attrs["value"], dtype=np.float64).reshape(shape)
    return {"Out": [jnp.asarray(value).astype(jnp.dtype(dtype))]}


@register("is_empty", no_grad=True)
def _is_empty(ctx, ins, attrs):
    (x,) = ins["X"]
    return {"Out": [jnp.full((1,), x.size == 0, jnp.bool_)]}


@register("multiplex")
def _multiplex(ctx, ins, attrs):
    xs = ins["X"]
    (ids,) = ins["Ids"]
    stacked = jnp.stack(xs, axis=0)  # (k, n, ...)
    rows = ids.reshape(-1).astype(jnp.int32)
    return {"Out": [stacked[rows, jnp.arange(stacked.shape[1])]]}


@register("crop")
def _crop(ctx, ins, attrs):
    (x,) = ins["X"]
    if "Y" in ins and ins["Y"][0] is not None:
        shape = list(ins["Y"][0].shape)
    else:
        shape = [int(d) for d in attrs["shape"]]
    if "Offsets" in ins and ins["Offsets"][0] is not None:
        offsets = [int(o) for o in np.asarray(ins["Offsets"][0])]
    else:
        offsets = [int(o) for o in attrs.get("offsets", [0] * x.ndim)]
    idx = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return {"Out": [x[idx]]}


@register("pad_constant_like")
def _pad_constant_like(ctx, ins, attrs):
    (x,) = ins["X"]
    (y,) = ins["Y"]
    val = float(attrs.get("pad_value", 0.0))
    pads = [(0, x.shape[i] - y.shape[i]) for i in range(x.ndim)]
    return {"Out": [jnp.pad(y, pads, constant_values=val)]}


@register("random_crop", no_grad=True, stochastic=True)
def _random_crop(ctx, ins, attrs):
    (x,) = ins["X"]
    shape = [int(d) for d in attrs["shape"]]
    lead = x.ndim - len(shape)
    key = ctx.next_rng()
    starts = []
    for i, s in enumerate(shape):
        key, sub = jax.random.split(key)
        hi = x.shape[lead + i] - s
        starts.append(
            jax.random.randint(sub, (), 0, hi + 1) if hi > 0 else jnp.int32(0)
        )
    idx = [jnp.int32(0)] * lead + starts
    out = lax.dynamic_slice(x, idx, list(x.shape[:lead]) + shape)
    outs = {"Out": [out]}
    if "Seed" in ins and ins["Seed"][0] is not None:
        outs["SeedOut"] = [ins["Seed"][0]]
    return outs


@register("space_to_depth")
def _space_to_depth(ctx, ins, attrs):
    (x,) = ins["X"]
    b = int(attrs["blocksize"])
    n, c, h, w = x.shape
    out = x.reshape(n, c, h // b, b, w // b, b)
    out = out.transpose(0, 3, 5, 1, 2, 4).reshape(n, c * b * b, h // b, w // b)
    return {"Out": [out]}


@register("conv_shift")
def _conv_shift(ctx, ins, attrs):
    (x,) = ins["X"]  # (B, M)
    (y,) = ins["Y"]  # (B, N), N odd, N <= M
    m = x.shape[1]
    nn = y.shape[1]
    half = nn // 2
    out = jnp.zeros_like(x)
    for j in range(nn):
        out = out + y[:, j : j + 1] * jnp.roll(x, half - j, axis=1)
    return {"Out": [out]}


@register("add_position_encoding")
def _add_position_encoding(ctx, ins, attrs):
    (x,) = ins["X"]  # (B, T, D)
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 1.0))
    b, t, d = x.shape
    half = d // 2
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    k = jnp.arange(half, dtype=jnp.float32)[None, :]
    denom = jnp.power(10000.0, k / (half - 1)) if half > 1 else jnp.ones_like(k)
    val = pos / denom  # (T, half)
    enc = jnp.concatenate([jnp.sin(val), jnp.cos(val)], axis=1)  # (T, D)
    return {"Out": [alpha * x + beta * enc[None].astype(x.dtype)]}


@register("mean_iou", no_grad=True)
def _mean_iou(ctx, ins, attrs):
    (pred,) = ins["Predictions"]
    (label,) = ins["Labels"]
    nc = int(attrs["num_classes"])
    p = pred.reshape(-1).astype(jnp.int32)
    l = label.reshape(-1).astype(jnp.int32)
    eq = p == l
    correct = jnp.zeros((nc,), jnp.int32).at[jnp.where(eq, p, nc)].add(1, mode="drop")
    wrong = (
        jnp.zeros((nc,), jnp.int32)
        .at[jnp.where(eq, nc, l)]
        .add(1, mode="drop")
        .at[jnp.where(eq, nc, p)]
        .add(1, mode="drop")
    )
    for extra in ins.get("InCorrects", []) or []:
        correct = correct + extra.astype(jnp.int32)
    for extra in ins.get("InWrongs", []) or []:
        wrong = wrong + extra.astype(jnp.int32)
    denom = wrong + correct
    valid = (denom > 0).sum()
    iou_sum = (correct / jnp.maximum(denom, 1)).sum()
    mean_iou = (iou_sum / valid).astype(jnp.float32).reshape(1)
    for extra in ins.get("InMeanIou", []) or []:
        mean_iou = mean_iou + extra
    return {"OutMeanIou": [mean_iou], "OutWrong": [wrong], "OutCorrect": [correct]}


@register("similarity_focus", no_grad=True)
def _similarity_focus(ctx, ins, attrs):
    (x,) = ins["X"]  # (N, d1, d2, d3)
    axis = int(attrs["axis"])
    indexes = [int(i) for i in attrs["indexes"]]
    # move the focus axis to position 1; greedy selection runs on the
    # remaining (a, b) plane
    perm = {1: (0, 1, 2, 3), 2: (0, 2, 1, 3), 3: (0, 3, 1, 2)}[axis]
    xt = x.transpose(perm)
    n, _, a, bdim = xt.shape
    steps = min(a, bdim)

    def one_slice(s):  # s: (a, b) -> mask (a, b) of greedily picked cells
        def body(_, carry):
            rowtag, coltag, sel = carry
            masked = jnp.where(rowtag[:, None] | coltag[None, :], -jnp.inf, s)
            flat = jnp.argmax(masked)
            i, j = flat // bdim, flat % bdim
            return rowtag.at[i].set(True), coltag.at[j].set(True), sel.at[i, j].set(True)

        _, _, sel = lax.fori_loop(
            0,
            steps,
            body,
            (
                jnp.zeros((a,), jnp.bool_),
                jnp.zeros((bdim,), jnp.bool_),
                jnp.zeros((a, bdim), jnp.bool_),
            ),
        )
        return sel

    mask = jnp.zeros((n, a, bdim), jnp.bool_)
    for idx in indexes:
        mask = mask | jax.vmap(one_slice)(xt[:, idx])
    out = jnp.broadcast_to(mask[:, None], xt.shape).astype(x.dtype)
    inv = np.argsort(perm)
    return {"Out": [out.transpose(tuple(inv))]}
