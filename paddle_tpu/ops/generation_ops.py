"""Generation-serving ops: paged KV-cache attention and cache writes.

The decode hot loop of the generation engine (`serving/generation.py`) runs
one fixed-shape program per step: every live slot contributes exactly one
query token, and all past K/V live in a preallocated paged pool indexed
through per-slot block tables (the vLLM layout). Keeping the gather/scatter
inside registered ops means the decode program lowers through the same
`aot_serve_lowering` path as everything else — the pool tensors classify as
mutable state and can be donated, so pages update in place and the step
never retraces.

Conventions:
  * A pool is a persistable ``[n_pages * page_size, n_head * d_head]`` f32
    array. Row ``page_id * page_size + offset`` holds the K (or V) row for
    one token. Page 0 is a scratch page the allocator never hands out —
    writes landing there (padded prefill tail, idle decode slots) are
    masked out of every attention read.
  * ``kv_cache_write`` outputs the pool variable itself (the in-place
    idiom, like ``increment``), so the executor classifies the pool as
    written state and threads the new buffer to the next step.
  * ``paged_attention`` takes a block table of either shape: ``[S, P]``
    (decode — one page list per query row) or ``[P]`` (chunked prefill —
    one slot's list shared by every row of the chunk). Masking is a proper
    where-mask with a safe softmax: masked scores are dropped, never added
    as a large negative constant (the additive ``-1e9`` form leaks
    probability mass once scores live in bf16 at long context), and a
    fully-masked row (pos < 0) emits zeros instead of 0/0 NaN.
  * **int8 pool mode** — when ``kv_cache_write`` is given a ``Scales``
    input, the pool holds int8 levels (symmetric per-row absmax/127
    quantization on the scatter) and a ``[n_pages * page_size]`` f32 scale
    pool rides along as a second piece of written state. ``paged_attention``
    takes the matching ``KScales``/``VScales`` and dequantizes inline — in
    the dense path on the gathered rows, in the Pallas kernel on the
    block-table page walk (the f32 rows exist only in VMEM). One HBM pool
    at ~¼ the bytes per row (int8 + one f32 scale per row) holds ≥2× the
    generation slots.
  * On TPU (or when FLAGS_paged_flash forces it) the lowering dispatches to
    the paged flash-attention Pallas kernel (ops/pallas_kernels.py), which
    walks the block table page by page with an online softmax and never
    materializes the gathered context. The dense form below stays as the
    decline target and the parity oracle (PR 11 contract).
"""

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


def _flat_rows(block_table, positions, page_size):
    """Pool row index for each (slot, position): block_table picks the page,
    position % page_size the offset. block_table may be [S, P] (decode, one
    row per slot) or [P] (prefill, one slot writing many positions). A
    position at or past the table's capacity (P * page_size — only the
    padded tail of a prefill chunk near the context bound can get there) is
    routed to the scratch page's rows instead of clamp-corrupting the last
    real page."""
    positions = positions.reshape(-1).astype(jnp.int32)
    page_idx = positions // page_size
    n_pages = block_table.shape[-1]
    safe_idx = jnp.minimum(page_idx, n_pages - 1)
    if block_table.ndim == 1:
        page_id = block_table.astype(jnp.int32)[safe_idx]
    else:
        page_id = jnp.take_along_axis(
            block_table.astype(jnp.int32), safe_idx[:, None], axis=1
        )[:, 0]
    page_id = jnp.where(page_idx < n_pages, page_id, 0)
    return page_id * page_size + positions % page_size


KV_QUANT_LEVELS = 127.0  # symmetric int8: round(x / scale), scale = absmax/127


@register("kv_cache_write", no_grad=True)
def _kv_cache_write(ctx, ins, attrs):
    """Scatter K/V rows into the pool. With a Scales input the pool holds
    int8 levels: each row quantizes symmetrically on the way in (scale =
    absmax/127 per row — a page's scale vector fills incrementally as its
    rows are written, so earlier rows are never re-scaled) and the f32
    per-row scale lands in the scale pool at the same flat index. Both the
    pool and the scale pool come back as written state (the in-place
    idiom), so decode steps donate both buffers."""
    (pool,) = ins["Pool"]
    (rows,) = ins["Rows"]
    (bt,) = ins["BlockTable"]
    (pos,) = ins["Pos"]
    page_size = int(attrs["page_size"])
    flat = _flat_rows(bt, pos, page_size)
    scales = ins.get("Scales", [None])[0]
    if scales is None:
        return {"Out": [pool.at[flat].set(rows.astype(pool.dtype))]}
    r32 = rows.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(r32), axis=-1)
    scale = jnp.maximum(absmax, 1e-8) / KV_QUANT_LEVELS
    q = jnp.clip(
        jnp.round(r32 / scale[:, None]), -KV_QUANT_LEVELS, KV_QUANT_LEVELS
    ).astype(pool.dtype)
    return {
        "Out": [pool.at[flat].set(q)],
        "OutScales": [scales.at[flat].set(scale.astype(scales.dtype))],
    }


@register("paged_attention", no_grad=True)
def _paged_attention(ctx, ins, attrs):
    (q,) = ins["Q"]  # [S, H*D] — one query token per row
    (kp,) = ins["KPool"]
    (vp,) = ins["VPool"]
    (bt,) = ins["BlockTable"]  # [S, P] or [P] int32 page ids (0 = scratch)
    (pos,) = ins["Pos"]  # [S] position of each query (attends 0..pos)
    n_head = int(attrs["n_head"])
    page_size = int(attrs["page_size"])
    s = q.shape[0]
    p = bt.shape[-1]
    ctx_len = p * page_size
    d = q.shape[-1] // n_head
    scale = float(attrs.get("sm_scale") or 0.0) or d**-0.5
    ks = ins.get("KScales", [None])[0]
    vs = ins.get("VScales", [None])[0]

    from . import pallas_kernels as _pk

    if _pk.paged_flash_path_taken(s, p, page_size, n_head, d):
        out = _pk.paged_flash_attention(
            q, kp, vp, bt, pos,
            n_head=n_head, page_size=page_size, sm_scale=scale,
            k_scales=ks, v_scales=vs,
        )
        return {"Out": [out]}

    def _deq(levels, row_scales, flat_idx):
        # int8-pool dequant in the dense decline path: per-row scales gather
        # through the same flat indices as their rows
        x = levels.astype(jnp.float32)
        if row_scales is None:
            return x
        sc = jnp.take(row_scales.reshape(-1), flat_idx.reshape(-1), axis=0)
        return x * sc.astype(jnp.float32).reshape(flat_idx.shape + (1, 1))

    qh = q.reshape(s, n_head, d).astype(jnp.float32)
    offsets = jnp.arange(page_size, dtype=jnp.int32)
    if bt.ndim == 1:
        # one shared page list: gather each context row once for all queries
        flat = (bt.astype(jnp.int32)[:, None] * page_size + offsets[None, :])
        flat = flat.reshape(ctx_len)
        k = jnp.take(kp, flat, axis=0).reshape(ctx_len, n_head, d)
        v = jnp.take(vp, flat, axis=0).reshape(ctx_len, n_head, d)
        k = _deq(k, ks, flat)
        v = _deq(v, vs, flat)
        scores = jnp.einsum("shd,chd->shc", qh, k.astype(jnp.float32)) * scale
    else:
        flat = (
            bt.astype(jnp.int32)[:, :, None] * page_size
            + offsets[None, None, :]
        ).reshape(s, ctx_len)
        k = jnp.take(kp, flat.reshape(-1), axis=0).reshape(s, ctx_len, n_head, d)
        v = jnp.take(vp, flat.reshape(-1), axis=0).reshape(s, ctx_len, n_head, d)
        k = _deq(k, ks, flat)
        v = _deq(v, vs, flat)
        scores = jnp.einsum("shd,schd->shc", qh, k.astype(jnp.float32)) * scale

    # causal-by-position where-mask + safe softmax: the query at position
    # pos sees context rows 0..pos inclusive (its own K/V row was written
    # earlier this step). Dead rows are EXCLUDED (weight exactly 0), not
    # additively depressed; a fully-masked row (pos < 0) emits zeros.
    live = (
        jnp.arange(ctx_len, dtype=jnp.int32)[None, :]
        <= pos.reshape(-1).astype(jnp.int32)[:, None]
    )[:, None, :]
    scores = jnp.where(live, scores, -jnp.inf)
    m = jnp.max(scores, axis=-1, keepdims=True)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    w = jnp.where(live, jnp.exp(scores - m), 0.0)
    denom = jnp.sum(w, axis=-1, keepdims=True)
    w = w / jnp.where(denom > 0.0, denom, 1.0)
    if bt.ndim == 1:
        out = jnp.einsum("shc,chd->shd", w, v.astype(jnp.float32))
    else:
        out = jnp.einsum("shc,schd->shd", w, v.astype(jnp.float32))
    return {"Out": [out.reshape(s, n_head * d).astype(q.dtype)]}
