"""Generation-serving ops: paged KV-cache attention and cache writes.

The decode hot loop of the generation engine (`serving/generation.py`) runs
one fixed-shape program per step: every live slot contributes exactly one
query token, and all past K/V live in a preallocated paged pool indexed
through per-slot block tables (the vLLM layout). Keeping the gather/scatter
inside registered ops means the decode program lowers through the same
`aot_serve_lowering` path as everything else — the pool tensors classify as
mutable state and can be donated, so pages update in place and the step
never retraces.

Conventions:
  * A pool is a persistable ``[n_pages * page_size, n_head * d_head]`` f32
    array. Row ``page_id * page_size + offset`` holds the K (or V) row for
    one token. Page 0 is a scratch page the allocator never hands out —
    writes landing there (padded prefill tail, idle decode slots) are
    masked out of every attention read.
  * ``kv_cache_write`` outputs the pool variable itself (the in-place
    idiom, like ``increment``), so the executor classifies the pool as
    written state and threads the new buffer to the next step.
"""

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []

_NEG_INF = -1e9


def _flat_rows(block_table, positions, page_size):
    """Pool row index for each (slot, position): block_table picks the page,
    position % page_size the offset. block_table may be [S, P] (decode, one
    row per slot) or [P] (prefill, one slot writing many positions)."""
    positions = positions.reshape(-1).astype(jnp.int32)
    page_idx = positions // page_size
    if block_table.ndim == 1:
        page_id = block_table.astype(jnp.int32)[page_idx]
    else:
        page_id = jnp.take_along_axis(
            block_table.astype(jnp.int32), page_idx[:, None], axis=1
        )[:, 0]
    return page_id * page_size + positions % page_size


@register("kv_cache_write", no_grad=True)
def _kv_cache_write(ctx, ins, attrs):
    (pool,) = ins["Pool"]
    (rows,) = ins["Rows"]
    (bt,) = ins["BlockTable"]
    (pos,) = ins["Pos"]
    page_size = int(attrs["page_size"])
    flat = _flat_rows(bt, pos, page_size)
    return {"Out": [pool.at[flat].set(rows.astype(pool.dtype))]}


@register("paged_attention", no_grad=True)
def _paged_attention(ctx, ins, attrs):
    (q,) = ins["Q"]  # [S, H*D] — one query token per slot
    (kp,) = ins["KPool"]
    (vp,) = ins["VPool"]
    (bt,) = ins["BlockTable"]  # [S, P] int32 page ids (0 = scratch/unused)
    (pos,) = ins["Pos"]  # [S] position of the query token (attends 0..pos)
    n_head = int(attrs["n_head"])
    page_size = int(attrs["page_size"])
    s, p = bt.shape
    ctx_len = p * page_size
    d = q.shape[-1] // n_head
    scale = float(attrs.get("sm_scale") or 0.0) or d**-0.5

    flat = (
        bt.astype(jnp.int32)[:, :, None] * page_size
        + jnp.arange(page_size, dtype=jnp.int32)[None, None, :]
    ).reshape(s, ctx_len)
    k = jnp.take(kp, flat.reshape(-1), axis=0).reshape(s, ctx_len, n_head, d)
    v = jnp.take(vp, flat.reshape(-1), axis=0).reshape(s, ctx_len, n_head, d)
    qh = q.reshape(s, n_head, d).astype(jnp.float32)

    scores = jnp.einsum("shd,schd->shc", qh, k.astype(jnp.float32)) * scale
    # causal-by-position: the query at position pos sees context rows
    # 0..pos inclusive (its own K/V row was written earlier this step).
    live = (
        jnp.arange(ctx_len, dtype=jnp.int32)[None, :]
        <= pos.reshape(-1).astype(jnp.int32)[:, None]
    )
    scores = jnp.where(live[:, None, :], scores, _NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("shc,schd->shd", weights, v.astype(jnp.float32))
    return {"Out": [out.reshape(s, n_head * d).astype(q.dtype)]}
