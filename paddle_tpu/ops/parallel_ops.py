"""Mesh-aware graph ops: ring attention and sharded embedding lookup.

These are new TPU-native capabilities (the reference has no sequence
parallelism, SURVEY.md §5.7; its embedding parallelism was the pserver
distributed lookup table, §2.7.5). Each op picks its distributed lowering when
the executor compiles over a mesh whose relevant axis is >1, and falls back to
the exact single-device computation otherwise — so the same program runs
anywhere.
"""

import jax.numpy as jnp

from ..parallel.ring_attention import ring_attention, ring_attention_sharded
from ..parallel.sharded_embedding import sharded_embedding_lookup
from .registry import register


@register("ring_attention")
def _ring_attention(ctx, ins, attrs):
    (q,) = ins["Q"]
    (k,) = ins["K"]
    (v,) = ins["V"]
    causal = bool(attrs.get("causal", False))
    axis = attrs.get("axis_name", "sp")
    mesh = ctx.mesh
    if mesh is not None and mesh.shape.get(axis, 1) > 1:
        out = ring_attention_sharded(q, k, v, mesh, axis_name=axis, causal=causal)
    else:
        out = ring_attention(q, k, v, causal=causal)
    return {"Out": [out]}


@register("distributed_lookup_table")
def _distributed_lookup_table(ctx, ins, attrs):
    (w,) = ins["W"]
    (ids,) = ins["Ids"]
    axis = attrs.get("axis_name", "ep")
    flat = ids.reshape(ids.shape[:-1]) if ids.shape[-1] == 1 else ids
    mesh = ctx.mesh
    if mesh is not None and mesh.shape.get(axis, 1) > 1:
        out = sharded_embedding_lookup(w, flat.astype(jnp.int32), mesh, axis_name=axis)
    else:
        out = jnp.take(w, flat.reshape(-1).astype(jnp.int32), axis=0).reshape(
            flat.shape + (w.shape[1],)
        )
    return {"Out": [out]}
