"""Mesh-aware graph ops: ring attention and sharded embedding lookup.

These are new TPU-native capabilities (the reference has no sequence
parallelism, SURVEY.md §5.7; its embedding parallelism was the pserver
distributed lookup table, §2.7.5). Each op picks its distributed lowering when
the executor compiles over a mesh whose relevant axis is >1, and falls back to
the exact single-device computation otherwise — so the same program runs
anywhere.
"""

import jax.numpy as jnp

from ..embedding.lookup import sharded_embedding_lookup
from ..parallel.ring_attention import ring_attention, ring_attention_sharded
from .registry import register


@register("ring_attention")
def _ring_attention(ctx, ins, attrs):
    (q,) = ins["Q"]
    (k,) = ins["K"]
    (v,) = ins["V"]
    causal = bool(attrs.get("causal", False))
    axis = attrs.get("axis_name", "sp")
    mesh = ctx.mesh
    if mesh is not None and mesh.shape.get(axis, 1) > 1:
        out = ring_attention_sharded(q, k, v, mesh, axis_name=axis, causal=causal)
    else:
        out = ring_attention(q, k, v, causal=causal)
    return {"Out": [out]}


@register("distributed_lookup_table")
def _distributed_lookup_table(ctx, ins, attrs):
    """Forward of embedding.EmbeddingEngine.lookup: row-sharded gather+psum
    over `axis_name` when the mesh has it, otherwise the exact dense lookup.
    Semantics match lookup_table (negative ids and padding_idx → zero rows,
    table dtype preserved) so the single-device fallback and the sharded path
    are numerically interchangeable."""
    (w,) = ins["W"]
    (ids,) = ins["Ids"]
    axis = attrs.get("axis_name", "ep")
    padding_idx = int(attrs.get("padding_idx", -1))
    flat = ids.reshape(ids.shape[:-1]) if ids.shape[-1] == 1 else ids
    mesh = ctx.mesh
    if mesh is not None and mesh.shape.get(axis, 1) > 1:
        out = sharded_embedding_lookup(
            w, flat.astype(jnp.int32), mesh, axis_name=axis,
            padding_idx=padding_idx if padding_idx != -1 else None,
        )
    else:
        fl = flat.reshape(-1).astype(jnp.int32)
        out = jnp.take(w, fl, axis=0)
        zero = jnp.zeros((), out.dtype)
        mask = fl < 0
        if padding_idx != -1:
            pad = padding_idx if padding_idx >= 0 else padding_idx + w.shape[0]
            mask = mask | (fl == pad)
        out = jnp.where(mask[:, None], zero, out)
        out = out.reshape(flat.shape + (w.shape[1],))
    return {"Out": [out]}
