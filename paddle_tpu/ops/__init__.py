"""Op registry + lowerings. Importing this package registers all ops."""

from . import registry
from . import core_ops  # noqa: F401 — registration side effects
from . import sequence_ops  # noqa: F401 — registration side effects
from . import parallel_ops  # noqa: F401 — registration side effects
from . import sparse_ops  # noqa: F401 — registration side effects (after core/parallel: attaches lookup grad makers)
from . import control_flow_ops  # noqa: F401 — registration side effects
from . import loss_ops  # noqa: F401 — registration side effects
from . import decode_ops  # noqa: F401 — registration side effects
from . import detection_ops  # noqa: F401 — registration side effects
from . import dist_ops  # noqa: F401 — registration side effects
from . import quant_ops  # noqa: F401 — registration side effects
from . import nn_extra_ops  # noqa: F401 — registration side effects
from . import compose_ops  # noqa: F401 — registration side effects
from . import frame_ops  # noqa: F401 — registration side effects
from . import pallas_kernels  # noqa: F401 — registration side effects
from . import generation_ops  # noqa: F401 — registration side effects
from .registry import OPS, get, is_registered, register
