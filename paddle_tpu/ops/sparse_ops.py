"""Sparse (SelectedRows) gradient + per-row optimizer ops.

Reference analog: the is_sparse=True path of lookup_table_grad_op
(lookup_table_op.h LookupTableGradKernel's SelectedRows branch), the sparse
functors in operators/optimizers (sgd_op.h SparseSGDFunctor, adam_op.h
SparseAdamFunctor lazy_mode, adagrad_op.h SparseAdagradFunctor), and
merge_add (math/selected_rows_functor.cc). On pservers these made the wire
and the update cost O(touched rows); here they make the HBM traffic of the
backward+update O(touched rows) — the dense path reads AND writes the whole
(rows, dim) table plus every moment each step, the sparse path touches
(ids_per_batch, dim) rows of each.

Three pieces:

- `lookup_table_grad_sparse`: emits the SelectedRows pair (embedding/
  selected_rows.py) — values in the cotangent's dtype + int32 global row ids
  (ROW_SENTINEL for masked/padding slots). No table-shaped tensor exists
  anywhere in its lowering.
- `{sgd,adagrad,adam}_sparse`: merge duplicate rows in f32, gather the
  touched param/moment rows, update in f32, scatter back in storage dtype.
  When the op carries an `axis_name` whose mesh extent is >1 the update runs
  under shard_map with the table and moments kept row-sharded — each rank
  updates only its own rows (ids/values are replicated, so every dp replica
  computes identical updates: no cross-replica divergence). This is the ZeRO
  composition for embeddings: moments shard along `ep` with the table
  (optimizer._add_accumulator copies the param's sharding_spec) instead of
  the dense ZeRO-1 `dp` sharding, and bf16 moments ride through unchanged.
- `selected_rows_to_dense`: densify fallback for optimizers without a sparse
  kernel (momentum, rmsprop, …), matching the reference's SelectedRows→
  LoDTensor merge before a dense update.

Adam here is the reference's lazy_mode: untouched rows' moments do not decay
that step (their params also don't move). SGD/Adagrad sparse updates are
exactly the dense math restricted to touched rows — untouched rows are
bit-identical either way.

The custom grad maker for lookup_table/embedding/distributed_lookup_table
lives here too: it chooses sparse vs dense per op instance (is_sparse attr,
and the table must have exactly ONE differentiable consumer — a twice-used
table would need a SelectedRows-aware grad summation, so it falls back to
the dense scatter-add instead).
"""

import functools

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..framework import OpRole, grad_var_name
from ..embedding.selected_rows import (
    ROW_SENTINEL,
    densify,
    mark_selected_rows,
    merge_rows,
    rows_var_name,
)
from .registry import OPS, register

__all__ = ["SPARSE_OPTIMIZER_TYPES"]

# optimizer op types with a per-row sparse lowering; everything else densifies
SPARSE_OPTIMIZER_TYPES = {
    "sgd": "sgd_sparse",
    "adagrad": "adagrad_sparse",
    "adam": "adam_sparse",
}


def _gauges(param, height, dim, cap, vbytes, tbytes, shards):
    """Trace-time embedding gauges (PR 4 registry). Set once per (re)compile;
    `cap` is the id-slot capacity of the step — the rows-touched upper bound
    (the exact unique count is data-dependent, invisible to a static trace)."""
    try:
        from ..observability.registry import default_registry

        reg = default_registry()
        lbl = {"table": str(param)}
        if cap is not None:
            reg.gauge(
                "embedding/rows_touched_per_step",
                help="id slots per step (upper bound on unique touched rows)",
            ).set(float(cap), **lbl)
            reg.gauge(
                "embedding/sparse_grad_bytes",
                help="bytes of the SelectedRows gradient per step",
            ).set(float(cap * dim * vbytes + cap * 4), **lbl)
            reg.gauge(
                "embedding/dense_grad_bytes",
                help="bytes a dense gradient of this table would be",
            ).set(float(height * dim * vbytes), **lbl)
        if tbytes is not None:
            reg.gauge(
                "embedding/table_bytes_per_shard",
                help="per-device HBM bytes of the table at the current ep",
            ).set(float(tbytes) / max(1, shards), **lbl)
    except Exception:
        pass  # observability must never break a trace


# --------------------------------------------------------------------------
# sparse gradient op
# --------------------------------------------------------------------------


def _sparse_grad_infer(op, block):
    """(capacity, dim) values + (capacity,) rows; capacity is ids.size, which
    is dynamic when the batch dim is (-1 stays -1 — the executor re-traces
    with concrete feed shapes)."""
    w = block._var_recursive(op.inputs["W"][0])
    ids = block._var_recursive(op.inputs["Ids"][0])
    dim = int(w.shape[1])
    n, dyn = 1, False
    for d in ids.shape:
        if d == -1:
            dyn = True
        else:
            n *= int(d)
    n = -1 if dyn else n
    gv = block._var_recursive(op.outputs["W@GRAD"][0])
    gv.shape = (n, dim)
    rv = block._var_recursive(op.outputs["Rows"][0])
    rv.shape = (n,)
    rv.dtype = "int32"


@register("lookup_table_grad_sparse", no_grad=True, infer_shape=_sparse_grad_infer)
def _lookup_table_grad_sparse(ctx, ins, attrs):
    """d(loss)/d(W) as SelectedRows: every id slot becomes one (row, value)
    pair; masked slots (negative ids, padding_idx) get ROW_SENTINEL so the
    optimizer's OOB-dropping scatter ignores them. W contributes shape only."""
    (w,) = ins["W"]
    (ids,) = ins["Ids"]
    (dout,) = ins["Out@GRAD"]
    dim = w.shape[1]
    flat = ids.reshape(-1).astype(jnp.int32)
    vals = dout.reshape(-1, dim)
    invalid = flat < 0
    padding_idx = int(attrs.get("padding_idx", -1))
    if padding_idx != -1:
        pad = padding_idx if padding_idx >= 0 else padding_idx + w.shape[0]
        invalid = invalid | (flat == pad)
    rows = jnp.where(invalid, jnp.int32(ROW_SENTINEL), flat)
    _gauges(
        attrs.get("param", "?"),
        int(w.shape[0]),
        int(dim),
        int(flat.shape[0]),
        jnp.dtype(vals.dtype).itemsize,
        None,
        1,
    )
    return {"W@GRAD": [vals], "Rows": [rows]}


@register("selected_rows_to_dense", no_grad=True)
def _selected_rows_to_dense(ctx, ins, attrs):
    (vals,) = ins["X"]
    (rows,) = ins["Rows"]
    height = int(attrs["height"])
    return {"Out": [densify(rows, vals, height)]}


# --------------------------------------------------------------------------
# per-row optimizer updates
# --------------------------------------------------------------------------


def _row_update(table, states, uniq, summed, height, compute, axis_name=None):
    """Gather touched rows of table+states, apply `compute` in f32, scatter
    back in storage dtype. Runs per-shard inside shard_map (axis_name set,
    rows offset by the shard's base) or on the full table otherwise. Rows that
    are invalid (sentinel → height) or live on another shard scatter out of
    bounds and are dropped."""
    rows_local = table.shape[0]
    local = uniq - (
        lax.axis_index(axis_name) * rows_local if axis_name else 0
    )
    valid = (uniq < height) & (local >= 0) & (local < rows_local)
    gidx = jnp.where(valid, local, 0)
    sidx = jnp.where(valid, local, rows_local)  # OOB → dropped on scatter
    p_rows = jnp.take(table, gidx, axis=0).astype(jnp.float32)
    s_rows = [jnp.take(s, gidx, axis=0).astype(jnp.float32) for s in states]
    new_p, new_s = compute(p_rows, s_rows, summed)
    # mask BEFORE the scatter so invalid slots can't even race valid ones
    table = table.at[sidx].set(new_p.astype(table.dtype), mode="drop")
    states = [
        s.at[sidx].set(ns.astype(s.dtype), mode="drop")
        for s, ns in zip(states, new_s)
    ]
    return (table, *states)


def _sparse_apply(ctx, ins, attrs, state_slots, make_compute):
    """Shared driver for the *_sparse optimizer ops. state_slots names the
    row-aligned moment inputs; make_compute(attrs, scalars) returns the f32
    per-row math. Scalar state (lr, beta pows) is replicated, like the dense
    ZeRO-1 tier."""
    (p,) = ins["Param"]
    (vals,) = ins["Grad"]
    (rows,) = ins["GradRows"]
    lr = ins["LearningRate"][0].reshape(()).astype(jnp.float32)
    states = [ins[s][0] for s in state_slots]
    height = int(p.shape[0])
    # merge duplicate ids once, in f32, on the replicated (cap, dim) pair —
    # O(cap) work vs the dense path's O(height) table-wide scatter
    uniq, summed = merge_rows(rows, vals, height)
    compute = make_compute(attrs, lr)

    axis = attrs.get("axis_name") or None
    mesh = ctx.mesh
    use_shard = bool(axis) and mesh is not None and mesh.shape.get(axis, 1) > 1
    _gauges(
        attrs.get("param", "?"),
        height,
        int(p.shape[1]),
        None,
        jnp.dtype(vals.dtype).itemsize,
        height * int(p.shape[1]) * jnp.dtype(p.dtype).itemsize,
        mesh.shape.get(axis, 1) if use_shard else 1,
    )
    if use_shard:
        from ..parallel.collectives import SHARD_MAP_CHECK_KW, shard_map

        nshard = len(states) + 1
        shard_spec = tuple(P((axis,), None) for _ in range(nshard))
        fn = shard_map(
            functools.partial(
                _shard_body,
                nstates=len(states),
                height=height,
                compute=compute,
                axis_name=axis,
            ),
            mesh=mesh,
            in_specs=shard_spec + (P(), P()),
            # table+moments stay row-sharded; every dp replica computed the
            # same update from the replicated (uniq, summed), so disabling
            # the replication check is sound
            out_specs=shard_spec,
            **{SHARD_MAP_CHECK_KW: False},
        )
        outs = fn(p, *states, uniq, summed)
    else:
        outs = _row_update(p, states, uniq, summed, height, compute)
    return outs


def _shard_body(*args, nstates, height, compute, axis_name):
    table = args[0]
    states = list(args[1 : 1 + nstates])
    uniq, summed = args[1 + nstates], args[2 + nstates]
    return _row_update(
        table, states, uniq, summed, height, compute, axis_name=axis_name
    )


def _pack(outs, out_slots):
    return {slot: [v] for slot, v in zip(out_slots, outs)}


@register("sgd_sparse", no_grad=True, infer_shape=lambda op, block: None)
def _sgd_sparse(ctx, ins, attrs):
    """Per-row SGD — exactly the dense sgd math restricted to touched rows
    (untouched rows are unchanged in both), so sparse-vs-dense SGD training
    is bit-identical on f32 tables."""

    def make(attrs, lr):
        def compute(p_rows, s_rows, g):
            return p_rows - lr * g, []

        return compute

    outs = _sparse_apply(ctx, ins, attrs, (), make)
    return _pack(outs, ("ParamOut",))


@register("adagrad_sparse", no_grad=True, infer_shape=lambda op, block: None)
def _adagrad_sparse(ctx, ins, attrs):
    def make(attrs, lr):
        eps = attrs.get("epsilon", 1e-6)

        def compute(p_rows, s_rows, g):
            (mom,) = s_rows
            mom_out = mom + jnp.square(g)
            return p_rows - lr * g / (jnp.sqrt(mom_out) + eps), [mom_out]

        return compute

    outs = _sparse_apply(ctx, ins, attrs, ("Moment",), make)
    return _pack(outs, ("ParamOut", "MomentOut"))


def _adam_sparse_lower(ctx, ins, attrs):
    """Lazy Adam (reference adam_op.h SparseAdamFunctor, lazy_mode=True):
    moments of untouched rows are frozen, not decayed — the property the
    touched-rows-only test asserts bit-exactly. Beta pows advance globally
    via the optimizer's _finish_update scale ops, same as dense."""
    b1p = ins["Beta1Pow"][0].reshape(()).astype(jnp.float32)
    b2p = ins["Beta2Pow"][0].reshape(()).astype(jnp.float32)

    def make(attrs, lr):
        b1 = attrs.get("beta1", 0.9)
        b2 = attrs.get("beta2", 0.999)
        eps = attrs.get("epsilon", 1e-8)
        lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)

        def compute(p_rows, s_rows, g):
            m1, m2 = s_rows
            m1o = b1 * m1 + (1 - b1) * g
            m2o = b2 * m2 + (1 - b2) * jnp.square(g)
            p_out = p_rows - lr_t * m1o / (jnp.sqrt(m2o) + eps)
            return p_out, [m1o, m2o]

        return compute

    outs = _sparse_apply(ctx, ins, attrs, ("Moment1", "Moment2"), make)
    return _pack(outs, ("ParamOut", "Moment1Out", "Moment2Out"))


register("adam_sparse", no_grad=True, infer_shape=lambda op, block: None)(
    _adam_sparse_lower
)


# --------------------------------------------------------------------------
# custom grad maker: sparse vs dense per lookup instance
# --------------------------------------------------------------------------


def _forward_consumers(block, w_name):
    """Differentiable forward-role ops reading w_name (backward/optimize ops
    excluded by role bit — by maker time the block already holds the grad ops
    appended for later program positions)."""
    n = 0
    for o in block.ops:
        role = int(o.attrs.get(OpRole.OP_ROLE_KEY, 0) or 0)
        if role & (OpRole.Backward | OpRole.Optimize):
            continue
        if w_name in o.input_arg_names:
            n += 1
    return n


def _lookup_grad_maker(op, block, grad_map):
    """Grad for lookup_table/embedding/distributed_lookup_table.

    is_sparse=True AND the table has a single differentiable consumer →
    SelectedRows pair via lookup_table_grad_sparse. Otherwise the dense f32
    scatter-add (lookup_table_grad) — when the table is looked up twice its
    contributions must be summed, which backward.py only knows how to do
    densely (the reference merges multi-consumer SelectedRows the same way:
    merged to dense before apply)."""
    w_name = op.inputs["W"][0]
    ids_name = op.inputs["Ids"][0]
    out_name = op.outputs["Out"][0]
    g_out = grad_map.get(out_name)
    g_w = grad_map.get(w_name)
    if g_out is None or g_w is None:
        return []
    attrs = {
        "padding_idx": int(op.attrs.get("padding_idx", -1)),
        "param": w_name,
        OpRole.OP_ROLE_VAR_KEY: [w_name, g_w],
    }
    w_var = block._var_recursive(w_name)
    sparse_ok = (
        bool(op.attrs.get("is_sparse", False))
        and g_w == grad_var_name(w_name)
        and _forward_consumers(block, w_name) == 1
    )
    if not sparse_ok:
        return [
            {
                "type": "lookup_table_grad",
                "inputs": {
                    "W": [w_name],
                    "Ids": [ids_name],
                    "Out@GRAD": [g_out],
                },
                "outputs": {"W@GRAD": [g_w]},
                "attrs": attrs,
            }
        ]
    rows_name = rows_var_name(g_w)
    if not block.has_var(rows_name):
        rv = block.create_var(
            name=rows_name,
            shape=[-1],
            dtype="int32",
            persistable=False,
        )
        rv.stop_gradient = True
    g_var = block._var_recursive(g_w)
    mark_selected_rows(g_var, rows_name, int(w_var.shape[0]))
    return [
        {
            "type": "lookup_table_grad_sparse",
            "inputs": {"W": [w_name], "Ids": [ids_name], "Out@GRAD": [g_out]},
            "outputs": {"W@GRAD": [g_w], "Rows": [rows_name]},
            "attrs": attrs,
        }
    ]


# attach to the already-registered lookup ops (core_ops.py / parallel_ops.py
# own the forward lowerings; the maker is the backward policy layer)
for _t in ("lookup_table", "embedding", "distributed_lookup_table"):
    OPS[_t].grad = _lookup_grad_maker
