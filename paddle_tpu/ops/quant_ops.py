"""Fake-quantization ops for quantization-aware training.

Reference analog: operators/fake_quantize_op.{cc,cu} (fake_quantize_abs_max,
fake_quantize_range_abs_max) and fake_dequantize_op.cc (fake_dequantize_max_abs)
— used by the contrib QuantizeTranspiler (quantize_transpiler.py:81). Gradients
are straight-through (the reference wires Out@GRAD to X@GRAD identically in
the transpiler's backward rewrite); here the quantize ops register an identity
grad maker so append_backward handles quantized programs unchanged. TPU note:
values stay in float with quantization *simulated* (round-to-level), which is
exactly the reference's training-time behavior; true int8 serving is the
freeze step of the transpiler.
"""

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register

__all__ = []


def _identity_grad(slot_in="X", slot_out="Out"):
    def maker(op, block, grad_map):
        return [
            {
                "type": "assign",
                "inputs": {"X": [grad_map[op.output(slot_out)[0]]]},
                "outputs": {"Out": [grad_map[op.input(slot_in)[0]]]},
                "attrs": {},
            }
        ]

    return maker


def _quant_levels(bit_length):
    return float((1 << (int(bit_length) - 1)) - 1)


@register("fake_quantize_abs_max", grad=_identity_grad())
def _fake_quantize_abs_max(ctx, ins, attrs):
    """Out = round(X / scale * s) where scale = max|X|, s = 2^(bits-1)-1
    (reference fake_quantize_op.cc FakeQuantizeAbsMaxOp)."""
    (x,) = ins["X"]
    s = _quant_levels(attrs.get("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    scale = jnp.where(scale == 0, jnp.ones_like(scale), scale)
    out = jnp.round(x / scale * s)
    return {"Out": [out], "OutScale": [scale]}


@register("fake_quantize_range_abs_max", grad=_identity_grad())
def _fake_quantize_range_abs_max(ctx, ins, attrs):
    """Training: scale = max(|X|, decayed running scale); inference: scale =
    InScale (reference FakeQuantizeRangeAbsMaxOp; the window of the reference
    becomes an exponential moving max — same fixed-point, no host-side window
    buffer, which would be a dynamic gather under jit)."""
    (x,) = ins["X"]
    s = _quant_levels(attrs.get("bit_length", 8))
    in_scale = ins["InScale"][0] if ins.get("InScale") else None
    if attrs.get("is_test", False) and in_scale is not None:
        scale = jnp.reshape(in_scale, ())
    else:
        cur = jnp.max(jnp.abs(x))
        if in_scale is not None:
            prev = jnp.reshape(in_scale, ())
            scale = jnp.maximum(cur, 0.9 * prev)
        else:
            scale = cur
    scale = jnp.where(scale == 0, jnp.ones_like(scale), scale)
    out = jnp.clip(jnp.round(x / scale * s), -s, s)
    return {"Out": [out], "OutScale": [jnp.reshape(scale, (1,))]}


@register("fake_dequantize_max_abs", grad=_identity_grad())
def _fake_dequantize_max_abs(ctx, ins, attrs):
    """Out = X * scale / max_range (reference fake_dequantize_op.cc)."""
    (x,) = ins["X"]
    (scale,) = ins["Scale"]
    max_range = float(attrs.get("max_range", 127.0))
    return {"Out": [x * (jnp.reshape(scale, ()) / max_range)]}


# ---------------------------------------------------------------------------
# real-int8 serving tier (QuantizeTranspiler.convert_to_int8): the reference's
# convert_to_int8 (contrib quantize_transpiler.py:236) only re-types weights —
# its int8 EXECUTION lived in MKL-DNN kernels. Here the int8 execution target
# is the MXU itself: v5e runs int8×int8→int32 matmul/conv at 2× the bf16 rate
# (measured 383 TOPS vs 192 TF/s on chip), so these ops carry the serving math.
# Outputs are float32 holding exact integer level-products, which keeps the
# downstream fake_dequantize chain unchanged.
# ---------------------------------------------------------------------------


@register("quantize_abs_max", no_grad=True)
def _quantize_abs_max(ctx, ins, attrs):
    """Serving-time activation quantization: int8 levels + scale (the real-
    int8 twin of fake_quantize_abs_max, which keeps levels in float for QAT)."""
    (x,) = ins["X"]
    s = _quant_levels(attrs.get("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    scale = jnp.where(scale == 0, jnp.ones_like(scale), scale)
    q = jnp.clip(jnp.round(x / scale * s), -s, s).astype(jnp.int8)
    return {"Out": [q], "OutScale": [jnp.reshape(scale, (1,))]}


@register("quantize_static", no_grad=True)
def _quantize_static(ctx, ins, attrs):
    """Calibrated activation quantization: int8 levels from a FROZEN scale
    (a persistable const the calibrate pass baked — the absmax observed over
    representative feeds). Unlike quantize_abs_max there is no reduction on
    the hot path and no OutScale: the scale is already program state, so the
    downstream dequantize reads the same const. Out-of-range activations
    saturate at ±levels — the calibrated-range contract."""
    (x,) = ins["X"]
    (scale,) = ins["Scale"]
    s = _quant_levels(attrs.get("bit_length", 8))
    sc = jnp.reshape(scale, ())
    sc = jnp.where(sc == 0, jnp.ones_like(sc), sc)
    q = jnp.clip(jnp.round(x / sc * s), -s, s).astype(jnp.int8)
    return {"Out": [q]}


@register("int8_mul", no_grad=True)
def _int8_mul(ctx, ins, attrs):
    """mul over int8 levels: int8×int8→int32 on the MXU, emitted as f32
    level-products (same flatten semantics as the mul op)."""
    (x,) = ins["X"]
    (y,) = ins["Y"]
    xnc = int(attrs.get("x_num_col_dims", 1))
    ync = int(attrs.get("y_num_col_dims", 1))
    x2 = x.reshape((int(np.prod(x.shape[:xnc])), -1))
    y2 = y.reshape((int(np.prod(y.shape[:ync])), -1))
    out = jax.lax.dot_general(
        x2, y2, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
    ).astype(jnp.float32)
    out_shape = tuple(x.shape[:xnc]) + tuple(y.shape[ync:])
    return {"Out": [out.reshape(out_shape)]}


@register("int8_conv2d", no_grad=True)
def _int8_conv2d(ctx, ins, attrs):
    """conv2d over int8 levels (NCHW, int32 accumulate), f32 level output."""
    (x,) = ins["Input"]
    (w,) = ins["Filter"]
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    paddings = [int(p) for p in attrs.get("paddings", [0, 0])]
    dilations = [int(d) for d in attrs.get("dilations", [1, 1])]
    groups = int(attrs.get("groups", 1) or 1)
    out = jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=[(paddings[0], paddings[0]), (paddings[1], paddings[1])],
        rhs_dilation=dilations,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=groups,
        preferred_element_type=jnp.int32,
    )
    return {"Output": [out.astype(jnp.float32)]}
