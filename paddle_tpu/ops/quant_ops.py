"""Fake-quantization ops for quantization-aware training.

Reference analog: operators/fake_quantize_op.{cc,cu} (fake_quantize_abs_max,
fake_quantize_range_abs_max) and fake_dequantize_op.cc (fake_dequantize_max_abs)
— used by the contrib QuantizeTranspiler (quantize_transpiler.py:81). Gradients
are straight-through (the reference wires Out@GRAD to X@GRAD identically in
the transpiler's backward rewrite); here the quantize ops register an identity
grad maker so append_backward handles quantized programs unchanged. TPU note:
values stay in float with quantization *simulated* (round-to-level), which is
exactly the reference's training-time behavior; true int8 serving is the
freeze step of the transpiler.
"""

import jax.numpy as jnp

from .registry import register

__all__ = []


def _identity_grad(slot_in="X", slot_out="Out"):
    def maker(op, block, grad_map):
        return [
            {
                "type": "assign",
                "inputs": {"X": [grad_map[op.output(slot_out)[0]]]},
                "outputs": {"Out": [grad_map[op.input(slot_in)[0]]]},
                "attrs": {},
            }
        ]

    return maker


def _quant_levels(bit_length):
    return float((1 << (int(bit_length) - 1)) - 1)


@register("fake_quantize_abs_max", grad=_identity_grad())
def _fake_quantize_abs_max(ctx, ins, attrs):
    """Out = round(X / scale * s) where scale = max|X|, s = 2^(bits-1)-1
    (reference fake_quantize_op.cc FakeQuantizeAbsMaxOp)."""
    (x,) = ins["X"]
    s = _quant_levels(attrs.get("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    scale = jnp.where(scale == 0, jnp.ones_like(scale), scale)
    out = jnp.round(x / scale * s)
    return {"Out": [out], "OutScale": [scale]}


@register("fake_quantize_range_abs_max", grad=_identity_grad())
def _fake_quantize_range_abs_max(ctx, ins, attrs):
    """Training: scale = max(|X|, decayed running scale); inference: scale =
    InScale (reference FakeQuantizeRangeAbsMaxOp; the window of the reference
    becomes an exponential moving max — same fixed-point, no host-side window
    buffer, which would be a dynamic gather under jit)."""
    (x,) = ins["X"]
    s = _quant_levels(attrs.get("bit_length", 8))
    in_scale = ins["InScale"][0] if ins.get("InScale") else None
    if attrs.get("is_test", False) and in_scale is not None:
        scale = jnp.reshape(in_scale, ())
    else:
        cur = jnp.max(jnp.abs(x))
        if in_scale is not None:
            prev = jnp.reshape(in_scale, ())
            scale = jnp.maximum(cur, 0.9 * prev)
        else:
            scale = cur
    scale = jnp.where(scale == 0, jnp.ones_like(scale), scale)
    out = jnp.clip(jnp.round(x / scale * s), -s, s)
    return {"Out": [out], "OutScale": [jnp.reshape(scale, (1,))]}


@register("fake_dequantize_max_abs", grad=_identity_grad())
def _fake_dequantize_max_abs(ctx, ins, attrs):
    """Out = X * scale / max_range (reference fake_dequantize_op.cc)."""
    (x,) = ins["X"]
    (scale,) = ins["Scale"]
    max_range = float(attrs.get("max_range", 127.0))
    return {"Out": [x * (jnp.reshape(scale, ()) / max_range)]}
