"""Fused / composite ops registered for reference op-registry parity.

Reference analogs: fc_op.cc (inference-fused fc), fused/fused_elemwise_
activation_op.cc, fused/fusion_lstm_op.cc, fused/fusion_gru_op.cc,
fused/fusion_seqconv_eltadd_relu_op.cc, fused/fusion_seqexpand_concat_fc_op.cc,
fused/fused_embedding_fc_lstm_op.cc, fused/fusion_transpose_flatten_concat_op.cc,
attention_lstm_op.cc, lstm_op.cc ("lstm"), lstmp_op.cc, gru_op.cc ("gru"),
cudnn_lstm_op.cu.cc.

On TPU these exist for PROGRAM parity, not speed: the reference fused them
because its per-op executor couldn't (CPU JIT /手写 kernels); here every
composite is expressed in terms of the same jnp lowerings the unfused ops use
and XLA refuses nothing — the fusion happens in the compiler. Sequence inputs
follow this framework's padded-dense + SeqLen convention (LoD redesign,
SURVEY.md §5.7).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import sequence_ops
from .registry import OPS, bcast_y, register


def _opt(ins, slot):
    """Optional-slot read: empty-var placeholders arrive as [None]
    (registry.lower_ops), so both absence and None must read as missing."""
    vals = ins.get(slot)
    return vals[0] if vals and vals[0] is not None else None


# ---------------------------------------------------------------------------
# fc + elementwise fusions
# ---------------------------------------------------------------------------


@register("fc")
def _fc(ctx, ins, attrs):
    """Sum of Input[i] @ W[i] (+ Bias), the inference-pass fc fusion
    (fc_op.cc; in training fc is composed from mul + elementwise_add)."""
    xs = ins["Input"]
    ws = ins["W"]
    in_num_col_dims = int(attrs.get("in_num_col_dims", 1))
    out = None
    for x, w in zip(xs, ws):
        lead = int(np.prod(x.shape[:in_num_col_dims]))
        x2 = x.reshape(lead, -1)
        term = x2 @ w
        out = term if out is None else out + term
    bias = _opt(ins, "Bias")
    if bias is not None:
        out = out + bias.reshape(1, -1)
    if attrs.get("activation_type"):
        out = _ACT[attrs["activation_type"]](out)
    x0 = xs[0]
    out = out.reshape(x0.shape[:in_num_col_dims] + (out.shape[-1],))
    return {"Out": [out]}


_ACT = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "identity": lambda x: x,
    "": lambda x: x,
}

_BINOPS = {
    "elementwise_add": jnp.add,
    "elementwise_sub": jnp.subtract,
    "elementwise_mul": jnp.multiply,
}

_UNOPS = {
    "relu": jax.nn.relu,
    "scale": None,  # handled with the scale attr
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
}


@register("fused_elemwise_activation")
def _fused_elemwise_activation(ctx, ins, attrs):
    """functor_list[0] is the OUTER function (reference
    fused_elemwise_activation_op.h IsUnaryCompound): [binary, unary] →
    Out = binary(x, unary(y)), [unary, binary] → Out = unary(binary(x, y));
    IntermediateOut is the inner result either way."""
    (x,) = ins["X"]
    (y,) = ins["Y"]
    functors = [f.lower() for f in attrs["functor_list"]]
    axis = int(attrs.get("axis", -1))
    scale = float(attrs.get("scale", 0.0))

    def unary(name, v):
        if name == "scale":
            return v * scale
        return _UNOPS[name](v)

    if functors[0] in _BINOPS:
        inter = unary(functors[1], y)
        out = _BINOPS[functors[0]](x, bcast_y(x, inter, axis))
    else:
        inter = _BINOPS[functors[1]](x, bcast_y(x, y, axis))
        out = unary(functors[0], inter)
    return {"Out": [out], "IntermediateOut": [inter]}


@register("fusion_transpose_flatten_concat")
def _fusion_transpose_flatten_concat(ctx, ins, attrs):
    trans = [int(a) for a in attrs["trans_axis"]]
    flat_axis = int(attrs["flatten_axis"])
    concat_axis = int(attrs["concat_axis"])
    pieces = []
    for x in ins["X"]:
        t = x.transpose(trans)
        lead = int(np.prod(t.shape[:flat_axis]))
        pieces.append(t.reshape(lead, -1))
    return {"Out": [jnp.concatenate(pieces, axis=concat_axis)]}


# ---------------------------------------------------------------------------
# recurrent composites. "lstm"/"gru" are the reference's canonical op names
# for what this framework registered as dynamic_lstm / dynamic_gru (the fluid
# layers emit type "lstm"/"gru"); alias them so transpiled/imported programs
# using reference op names execute unchanged.
# ---------------------------------------------------------------------------

register("lstm")(OPS["dynamic_lstm"].lower)
register("gru")(OPS["dynamic_gru"].lower)


@register("lstmp")
def _lstmp(ctx, ins, attrs):
    """LSTM with recurrent projection (reference lstmp_op.cc): the recurrent
    connection feeds the projection r = act(h @ ProjWeight) instead of h.
    Weight is (p, 4h), ProjWeight is (h, p)."""
    (x,) = ins["Input"]  # (b, t, 4h) pre-projected input contribution
    (w,) = ins["Weight"]
    (wp,) = ins["ProjWeight"]
    (seqlen,) = ins["SeqLen"]
    bias = _opt(ins, "Bias")
    b, t, h4 = x.shape
    h = h4 // 4
    p = wp.shape[1]
    lens = seqlen.reshape(-1).astype(jnp.int32)
    proj_act = _ACT[attrs.get("proj_activation", "identity")]

    gate_bias = bias.reshape(-1)[: 4 * h] if bias is not None else None
    xs = jnp.moveaxis(x, 1, 0)
    tidx = jnp.arange(t)

    def step(carry, inp):
        r_prev, c_prev = carry
        xt, ti = inp
        gates = xt + r_prev @ w
        if gate_bias is not None:
            gates = gates + gate_bias
        gc, gi, gf, go = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(gi)
        f = jax.nn.sigmoid(gf)
        o = jax.nn.sigmoid(go)
        c_new = f * c_prev + i * jnp.tanh(gc)
        h_new = o * jnp.tanh(c_new)
        r_new = proj_act(h_new @ wp)
        mask = (ti < lens).astype(x.dtype).reshape(-1, 1)
        r_out = mask * r_new + (1 - mask) * r_prev
        c_out = mask * c_new + (1 - mask) * c_prev
        return (r_out, c_out), (r_out, c_out, mask * h_new)

    init = (jnp.zeros((b, p), x.dtype), jnp.zeros((b, h), x.dtype))
    _, (rs, cs, hs) = lax.scan(step, init, (xs, tidx))
    mask = (jnp.arange(t)[None, :] < lens[:, None]).astype(x.dtype)[..., None]
    return {
        "Projection": [jnp.moveaxis(rs, 0, 1) * mask],
        "Cell": [jnp.moveaxis(cs, 0, 1) * mask],
        "Hidden": [jnp.moveaxis(hs, 0, 1) * mask],
    }


def cudnn_lstm_weight_size(input_size, hidden_size, num_layers=1, is_bidirec=False):
    """Flat-blob length for cudnn_lstm's layout (documented below) (layer helper for users)."""
    num_dir = 2 if is_bidirec else 1
    total = 0
    d_in = input_size
    for _ in range(num_layers):
        for _ in range(num_dir):
            total += d_in * 4 * hidden_size + hidden_size * 4 * hidden_size + 4 * hidden_size
        d_in = hidden_size * num_dir
    return total


@register("cudnn_lstm")
def _cudnn_lstm(ctx, ins, attrs):
    """Stacked (optionally bidirectional) LSTM over seq-major padded input
    (reference cudnn_lstm_op.cu.cc). W is a flat blob in layer-major,
    direction-minor order; per (layer, direction) the segment is
    [Wx(d_in,4h) | Wh(h,4h) | b(4h)], the cuDNN packed-weights analog
    (layout documented here, not byte-compatible with cuDNN's). Bidirection
    concatenates fwd/bwd hidden per layer, doubling the next layer's d_in.
    InitH/InitC are (num_layers*num_dir, N, h)."""
    (x,) = ins["Input"]  # (T, N, D) seq-major like cuDNN
    (w,) = ins["W"]
    h = int(attrs["hidden_size"])
    num_layers = int(attrs.get("num_layers", 1))
    bidirec = bool(attrs.get("is_bidirec", False))
    num_dir = 2 if bidirec else 1
    t, n, d = x.shape
    flat = w.reshape(-1)
    expected = cudnn_lstm_weight_size(d, h, num_layers, bidirec)
    if flat.size != expected:
        raise ValueError(
            "cudnn_lstm: W has %d elements but the documented layout needs %d "
            "(input=%d, hidden=%d, layers=%d, bidirec=%s) — see "
            "cudnn_lstm_weight_size" % (flat.size, expected, d, h, num_layers, bidirec)
        )
    h0_all = _opt(ins, "InitH")
    c0_all = _opt(ins, "InitC")

    def seg_sizes(d_in):
        return d_in * 4 * h, h * 4 * h, 4 * h

    def run_direction(inp, wx, wh, b, h0, c0, reverse):
        xs = jnp.flip(inp, axis=0) if reverse else inp

        def step(carry, xt):
            h_prev, c_prev = carry
            gates = xt @ wx + h_prev @ wh + b
            gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
            c_new = jax.nn.sigmoid(gf) * c_prev + jax.nn.sigmoid(gi) * jnp.tanh(gc)
            h_new = jax.nn.sigmoid(go) * jnp.tanh(c_new)
            return (h_new, c_new), h_new

        (hl, cl), hs = lax.scan(step, (h0, c0), xs)
        if reverse:
            hs = jnp.flip(hs, axis=0)
        return hs, hl, cl

    dropout_prob = float(attrs.get("dropout_prob", 0.0) or 0.0)
    is_test = bool(attrs.get("is_test", False))
    pos = 0
    cur = x
    last_h, last_c = [], []
    for layer in range(num_layers):
        if layer > 0 and dropout_prob and not is_test:
            # inter-layer dropout (reference cudnn_lstm applies it between
            # stacked layers, never after the last). LIMITATION: the mask
            # key derives from the seed attr + layer, NOT ctx.next_rng() —
            # the vjp-replay grad must resample the identical mask — so the
            # mask is FIXED across steps (static thinning, not stochastic
            # regularization). For real dropout regularization compose
            # `lstm` ops with dropout layers (models/stacked_lstm.py),
            # whose Mask-reusing grad supports per-step masks.
            import warnings

            if not attrs.get("__dropout_warned__"):
                warnings.warn(
                    "cudnn_lstm dropout_prob uses a step-constant mask "
                    "(seed attr); compose lstm + dropout layers for "
                    "per-step stochastic dropout"
                )
                attrs["__dropout_warned__"] = True
            key = jax.random.fold_in(
                jax.random.key(int(attrs.get("seed", 0) or 0)), layer
            )
            keep = jax.random.bernoulli(key, 1.0 - dropout_prob, cur.shape)
            cur = cur * keep.astype(cur.dtype) / (1.0 - dropout_prob)
        d_in = cur.shape[-1]
        sx, sh, sb = seg_sizes(d_in)
        outs = []
        for direction in range(num_dir):
            wx = flat[pos : pos + sx].reshape(d_in, 4 * h)
            pos += sx
            wh = flat[pos : pos + sh].reshape(h, 4 * h)
            pos += sh
            b = flat[pos : pos + sb]
            pos += sb
            idx = layer * num_dir + direction
            h0 = (
                h0_all.reshape(-1, n, h)[idx]
                if h0_all is not None
                else jnp.zeros((n, h), x.dtype)
            )
            c0 = (
                c0_all.reshape(-1, n, h)[idx]
                if c0_all is not None
                else jnp.zeros((n, h), x.dtype)
            )
            hs, hl, cl = run_direction(cur, wx, wh, b, h0, c0, direction == 1)
            outs.append(hs)
            last_h.append(hl)
            last_c.append(cl)
        cur = outs[0] if num_dir == 1 else jnp.concatenate(outs, axis=-1)
    return {
        "Out": [cur],
        "last_h": [jnp.stack(last_h)],
        "last_c": [jnp.stack(last_c)],
    }


def _project_then(ins, wx_slot, extra):
    (x,) = ins["X"]
    (wx,) = ins[wx_slot]
    proj = jnp.einsum("btd,dg->btg", x, wx)
    sub = dict(extra)
    sub["Input"] = [proj]
    sub["SeqLen"] = ins["SeqLen"]
    for slot in ("H0", "C0", "Bias"):
        if _opt(ins, slot) is not None:
            sub[slot] = ins[slot]
    return sub


@register("fusion_lstm")
def _fusion_lstm(ctx, ins, attrs):
    """x @ WeightX then the lstm recurrence in one op (reference
    fused/fusion_lstm_op.cc)."""
    sub = _project_then(ins, "WeightX", {"Weight": ins["WeightH"]})
    return OPS["dynamic_lstm"].lower(ctx, sub, attrs)


@register("fusion_gru")
def _fusion_gru(ctx, ins, attrs):
    sub = _project_then(ins, "WeightX", {"Weight": ins["WeightH"]})
    return OPS["dynamic_gru"].lower(ctx, sub, attrs)


@register("fused_embedding_fc_lstm")
def _fused_embedding_fc_lstm(ctx, ins, attrs):
    """Embedding lookup (rows are pre-multiplied by the fc weight, as the
    reference's pass rewrites them) + lstm (fused_embedding_fc_lstm_op.cc)."""
    (ids,) = ins["Ids"]  # (b, t) or (b, t, 1)
    (emb,) = ins["Embeddings"]  # (vocab, 4h)
    ids2 = ids.reshape(ids.shape[0], -1).astype(jnp.int32)
    proj = emb[ids2]
    sub = {
        "Input": [proj],
        "Weight": ins["WeightH"],
        "SeqLen": ins["SeqLen"],
    }
    for slot in ("H0", "C0", "Bias"):
        if _opt(ins, slot) is not None:
            sub[slot] = ins[slot]
    return OPS["dynamic_lstm"].lower(ctx, sub, attrs)


@register("fusion_seqconv_eltadd_relu")
def _fusion_seqconv_eltadd_relu(ctx, ins, attrs):
    out = sequence_ops._sequence_conv(
        ctx,
        {"X": ins["X"], "Filter": ins["Filter"], "SeqLen": ins["SeqLen"]},
        attrs,
    )["Out"][0]
    out = jax.nn.relu(out + ins["Bias"][0].reshape(1, 1, -1))
    # re-mask: bias+relu puts relu(bias) into padded rows, and downstream
    # sequence ops rely on padding staying zero
    lens = ins["SeqLen"][0].reshape(-1).astype(jnp.int32)
    out = sequence_ops._masked(out, lens)
    return {"Out": [out]}


@register("fusion_seqexpand_concat_fc")
def _fusion_seqexpand_concat_fc(ctx, ins, attrs):
    """First input is the full sequence (b,t,d0); the rest are per-sequence
    vectors broadcast over time; concat + fc + activation
    (fused/fusion_seqexpand_concat_fc_op.cc)."""
    xs = ins["X"]
    (w,) = ins["FCWeight"]
    seq = xs[0]
    b, t = seq.shape[:2]
    parts = [seq] + [jnp.broadcast_to(v[:, None, :], (b, t, v.shape[-1])) for v in xs[1:]]
    cat = jnp.concatenate(parts, axis=-1)
    out = jnp.einsum("btd,do->bto", cat, w)
    fc_bias = _opt(ins, "FCBias")
    if fc_bias is not None:
        out = out + fc_bias.reshape(1, 1, -1)
    out = _ACT[attrs.get("fc_activation", "identity")](out)
    return {"Out": [out]}


@register("attention_lstm")
def _attention_lstm(ctx, ins, attrs):
    """Per-step content attention over the input sequence feeding an LSTM
    (reference attention_lstm_op.cc): score_t = fc([x_t, h_prev]); softmax
    over valid steps; the attended vector drives one lstm step. Padded-dense
    redesign of the reference's LoD loop."""
    (x,) = ins["X"]  # (b, t, d)
    (seqlen,) = ins["SeqLen"]
    (aw,) = ins["AttentionWeight"]  # (d + h, 1)
    (lw,) = ins["LSTMWeight"]  # (d + h, 4h)
    lstm_bias = _opt(ins, "LSTMBias")
    lb = lstm_bias.reshape(-1) if lstm_bias is not None else 0.0
    atten_bias = _opt(ins, "AttentionBias")
    ab = atten_bias.reshape(-1) if atten_bias is not None else None
    b, t, d = x.shape
    h = lw.shape[1] // 4
    lens = seqlen.reshape(-1).astype(jnp.int32)
    valid = jnp.arange(t)[None, :] < lens[:, None]  # (b, t)
    h0 = _opt(ins, "H0")
    h0 = jnp.zeros((b, h), x.dtype) if h0 is None else h0
    c0 = _opt(ins, "C0")
    c0 = jnp.zeros((b, h), x.dtype) if c0 is None else c0

    aw_x = aw[:d, 0]
    aw_h = aw[d:, 0]

    def step(carry, _):
        h_prev, c_prev = carry
        score = x @ aw_x + (h_prev @ aw_h[:, None]).reshape(b, 1)
        if ab is not None:
            score = score + ab
        scalar = _opt(ins, "AttentionScalar")
        if scalar is not None:
            score = score * scalar.reshape(())
            scalar_bias = _opt(ins, "AttentionScalarBias")
            if scalar_bias is not None:
                score = score + scalar_bias.reshape(())
        score = jnp.where(valid, score, -jnp.inf)
        alpha = jax.nn.softmax(score, axis=1)  # (b, t)
        atted = jnp.einsum("bt,btd->bd", alpha, x)
        gates = jnp.concatenate([atted, h_prev], axis=-1) @ lw + lb
        gc, gi, gf, go = jnp.split(gates, 4, axis=-1)
        c_new = jax.nn.sigmoid(gf) * c_prev + jax.nn.sigmoid(gi) * jnp.tanh(gc)
        h_new = jax.nn.sigmoid(go) * jnp.tanh(c_new)
        return (h_new, c_new), (h_new, c_new)

    max_len = t
    (_, _), (hs, cs) = lax.scan(step, (h0, c0), None, length=max_len)
    mask = valid.astype(x.dtype)[..., None]
    return {
        "Hidden": [jnp.moveaxis(hs, 0, 1) * mask],
        "Cell": [jnp.moveaxis(cs, 0, 1) * mask],
    }


@register("conv2d_fusion")
def _conv2d_fusion(ctx, ins, attrs):
    """conv + bias + activation (+ residual) in one op (reference
    conv_fusion_op.cu.cc over cudnnConvolutionBiasActivationForward). XLA
    performs this fusion automatically; registered so imported inference
    programs run."""
    from .core_ops import _conv2d

    out = _conv2d(ctx, ins, attrs)["Output"][0]
    bias = _opt(ins, "Bias")
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    residual = _opt(ins, "ResidualData")
    if residual is not None:
        out = out + residual
    act = attrs.get("activation", "relu")
    if act and act != "identity":
        out = _ACT[act](out)
    return {"Output": [out]}
